"""JSON-RPC transport: HTTP POST, GET URI routes, and WebSocket subscribe.

Reference: rpc/jsonrpc/server — http_server.go (Serve w/ panic recovery),
http_json_handler.go (POST JSON-RPC 2.0, single + batch),
http_uri_handler.go (GET with query params), ws_handler.go (per-conn
read/write pumps carrying JSON-RPC frames; subscribe/unsubscribe ride the
event bus). The WebSocket side is a from-scratch RFC6455 server handshake
+ frame codec on the stdlib HTTP machinery — no external deps.
"""

from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qsl, urlparse

from cometbft_tpu.libs.log import Logger, new_nop_logger
from cometbft_tpu.libs.pubsub.pubsub import SubscriptionCancelled
from cometbft_tpu.libs.pubsub.query import parse_query
from cometbft_tpu.rpc.core import Environment, RPCError

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# route name → (method name on Environment, {param: coercer})
_ROUTES = {
    "health": ("health", {}),
    "status": ("status", {}),
    "net_info": ("net_info", {}),
    "genesis": ("genesis", {}),
    "blockchain": (
        "blockchain",
        {"minHeight": ("min_height", int), "maxHeight": ("max_height", int)},
    ),
    "block": ("block", {"height": ("height", int)}),
    "block_by_hash": ("block_by_hash", {"hash": ("hash_", "b64bytes")}),
    "commit": ("commit", {"height": ("height", int)}),
    "validators": (
        "validators",
        {
            "height": ("height", int),
            "page": ("page", int),
            "per_page": ("per_page", int),
        },
    ),
    "consensus_params": ("consensus_params", {"height": ("height", int)}),
    "consensus_state": ("consensus_state", {}),
    "dump_consensus_state": ("dump_consensus_state", {}),
    "abci_info": ("abci_info", {}),
    "abci_query": (
        "abci_query",
        {
            "path": ("path", str),
            "data": ("data", "hexbytes"),
            "height": ("height", int),
            "prove": ("prove", bool),
        },
    ),
    "unconfirmed_txs": ("unconfirmed_txs", {"limit": ("limit", int)}),
    "num_unconfirmed_txs": ("num_unconfirmed_txs", {}),
    "broadcast_tx_async": ("broadcast_tx_async", {"tx": ("tx", "b64bytes")}),
    "broadcast_tx_sync": ("broadcast_tx_sync", {"tx": ("tx", "b64bytes")}),
    "broadcast_tx_commit": ("broadcast_tx_commit", {"tx": ("tx", "b64bytes")}),
    "tx": ("tx", {"hash": ("hash_", "b64bytes"), "prove": ("prove", bool)}),
    "block_results": ("block_results", {"height": ("height", int)}),
    "check_tx": ("check_tx", {"tx": ("tx", "b64bytes")}),
    "broadcast_evidence": (
        "broadcast_evidence",
        {"evidence": ("evidence", "b64bytes")},
    ),
    "genesis_chunked": ("genesis_chunked", {"chunk": ("chunk", int)}),
    "dial_seeds": ("unsafe_dial_seeds", {"seeds": ("seeds", "strlist")}),
    "dial_peers": (
        "unsafe_dial_peers",
        {"peers": ("peers", "strlist"), "persistent": ("persistent", bool)},
    ),
    "unsafe_flush_mempool": ("unsafe_flush_mempool", {}),
    "tx_search": (
        "tx_search",
        {
            "query": ("query", str),
            "page": ("page", int),
            "per_page": ("per_page", int),
            "order_by": ("order_by", str),
        },
    ),
    "block_search": (
        "block_search",
        {
            "query": ("query", str),
            "page": ("page", int),
            "per_page": ("per_page", int),
            "order_by": ("order_by", str),
        },
    ),
}


def _coerce(kind, value):
    if kind is int:
        return int(value.strip('"')) if isinstance(value, str) else int(value)
    if kind is bool:
        if isinstance(value, bool):
            return value
        return str(value).lower() in ("true", "1")
    if kind is str:
        return str(value)
    if kind == "b64bytes":
        # JSON-RPC params carry bytes base64'd; URI params hex with 0x or b64
        if isinstance(value, (bytes, bytearray)):
            return bytes(value)
        s = str(value).strip('"')
        if s.startswith("0x"):
            return bytes.fromhex(s[2:])
        try:
            return base64.b64decode(s, validate=True)
        except Exception as exc:
            raise RPCError(-32602, f"invalid base64 parameter: {exc}") from exc
    if kind == "strlist":
        if isinstance(value, (list, tuple)):
            return [str(v) for v in value]
        s = str(value).strip('"')
        return [p for p in s.split(",") if p]
    if kind == "hexbytes":
        if isinstance(value, (bytes, bytearray)):
            return bytes(value)
        s = str(value).strip('"')
        if s.startswith("0x"):
            s = s[2:]
        try:
            return bytes.fromhex(s)
        except ValueError:
            return s.encode()
    raise ValueError(f"unknown coercion {kind}")


def _dispatch(env: Environment, method: str, params):
    route = _ROUTES.get(method)
    if route is None:
        raise RPCError(-32601, f"Method not found: {method}")
    fn_name, spec = route
    if isinstance(params, (list, tuple)):
        # positional form: map onto the route's declared parameter order
        if len(params) > len(spec):
            raise RPCError(
                -32602,
                f"{method} takes at most {len(spec)} parameters",
            )
        params = dict(zip(spec.keys(), params))
    kwargs = {}
    for wire_name, (py_name, kind) in spec.items():
        if params and wire_name in params and params[wire_name] is not None:
            kwargs[py_name] = _coerce(kind, params[wire_name])
    return getattr(env, fn_name)(**kwargs)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "cometbft-tpu-rpc"

    # injected by RPCServer
    env: Environment = None
    logger: Logger = None

    def log_message(self, fmt, *args):  # route http.server noise to our logger
        self.logger.debug(f"rpc http: {fmt % args}")

    # -- JSON-RPC over POST ---------------------------------------------------

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        try:
            req = json.loads(body)
        except ValueError:
            self._reply_json(
                _error_obj(None, -32700, "Parse error", "invalid JSON")
            )
            return
        if isinstance(req, list):
            out = [self._handle_one(r) for r in req]
            out = [o for o in out if o is not None]
            self._reply_json(out)
        else:
            self._reply_json(self._handle_one(req))

    def _handle_one(self, req):
        if not isinstance(req, dict):
            # a JSON scalar/array member is not a request object — answer
            # Invalid Request instead of crashing the connection
            return _error_obj(None, -32600, "Invalid Request", "")
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params") or {}
        try:
            result = _dispatch(self.env, method, params)
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except RPCError as exc:
            return _error_obj(rid, exc.code, exc.message, exc.data)
        except Exception as exc:  # panic recovery (http_server.go:161)
            self.logger.error("rpc handler panic", method=method, err=str(exc))
            return _error_obj(rid, -32603, "Internal error", str(exc))

    # -- URI routes over GET -----------------------------------------------------

    def do_GET(self):
        parsed = urlparse(self.path)
        route = parsed.path.strip("/")
        if route == "websocket":
            self._upgrade_websocket()
            return
        if route == "":
            self._reply_text(self._index_page())
            return
        params = dict(parse_qsl(parsed.query))
        try:
            result = _dispatch(self.env, route, params)
            self._reply_json(
                {"jsonrpc": "2.0", "id": -1, "result": result}
            )
        except RPCError as exc:
            self._reply_json(_error_obj(-1, exc.code, exc.message, exc.data))
        except Exception as exc:
            self.logger.error("rpc handler panic", route=route, err=str(exc))
            self._reply_json(_error_obj(-1, -32603, "Internal error", str(exc)))

    def _index_page(self) -> str:
        lines = ["Available endpoints:"]
        for name in sorted(_ROUTES):
            lines.append(f"//{self.headers.get('Host', 'localhost')}/{name}")
        return "\n".join(lines) + "\n"

    # -- plumbing ----------------------------------------------------------------

    def _reply_json(self, obj) -> None:
        data = json.dumps(obj).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_text(self, text: str) -> None:
        data = text.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- WebSocket (RFC 6455) -----------------------------------------------------

    def _upgrade_websocket(self) -> None:
        key = self.headers.get("Sec-WebSocket-Key")
        if (
            self.headers.get("Upgrade", "").lower() != "websocket"
            or key is None
        ):
            self.send_error(400, "not a websocket handshake")
            return
        accept = base64.b64encode(
            hashlib.sha1((key + _WS_GUID).encode()).digest()
        ).decode()
        self.send_response(101, "Switching Protocols")
        self.send_header("Upgrade", "websocket")
        self.send_header("Connection", "Upgrade")
        self.send_header("Sec-WebSocket-Accept", accept)
        self.end_headers()
        self.close_connection = True
        _WSConn(
            self.connection, self.env, self.logger
        ).run()  # blocks until the client leaves

    def do_OPTIONS(self):
        self.send_response(204)
        self.send_header("Access-Control-Allow-Origin", "*")
        self.send_header("Access-Control-Allow-Methods", "GET, POST, OPTIONS")
        self.end_headers()


def _error_obj(rid, code, message, data=""):
    return {
        "jsonrpc": "2.0",
        "id": rid,
        "error": {"code": code, "message": message, "data": data},
    }


class _WSConn:
    """One WebSocket client: JSON-RPC frames; subscribe/unsubscribe route
    to the event bus, everything else through the normal dispatcher
    (ws_handler.go read/write pumps)."""

    def __init__(self, sock: socket.socket, env: Environment, logger: Logger):
        self._sock = sock
        self._env = env
        self._logger = logger
        self._send_mtx = threading.Lock()
        self._subscriber = f"ws-{uuid.uuid4().hex[:12]}"
        self._subs = {}  # query string -> (Subscription, pump thread stop flag)
        self._alive = True

    # -- frame codec ------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf.extend(chunk)
        return bytes(buf)

    def _read_frame(self):
        b1, b2 = self._read_exact(2)
        opcode = b1 & 0x0F
        masked = b2 & 0x80
        length = b2 & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", self._read_exact(2))
        elif length == 127:
            (length,) = struct.unpack(">Q", self._read_exact(8))
        mask = self._read_exact(4) if masked else b""
        payload = self._read_exact(length)
        if mask:
            payload = bytes(
                c ^ mask[i % 4] for i, c in enumerate(payload)
            )
        return opcode, payload

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([n])
        elif n < 1 << 16:
            header += bytes([126]) + struct.pack(">H", n)
        else:
            header += bytes([127]) + struct.pack(">Q", n)
        with self._send_mtx:
            self._sock.sendall(header + payload)

    def _send_json(self, obj) -> None:
        self._send_frame(0x1, json.dumps(obj).encode())

    # -- main loop ----------------------------------------------------------------

    def run(self) -> None:
        try:
            while self._alive:
                opcode, payload = self._read_frame()
                if opcode == 0x8:  # close
                    break
                if opcode == 0x9:  # ping
                    self._send_frame(0xA, payload)
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                try:
                    req = json.loads(payload)
                except ValueError:
                    self._send_json(
                        _error_obj(None, -32700, "Parse error", "")
                    )
                    continue
                self._handle(req)
        except (ConnectionError, OSError):
            pass
        finally:
            self._alive = False
            try:
                self._env.node.event_bus.unsubscribe_all(self._subscriber)
            except Exception:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    def _handle(self, req: dict) -> None:
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params") or {}
        try:
            if method == "subscribe":
                self._subscribe(rid, params.get("query", ""))
            elif method == "unsubscribe":
                self._unsubscribe(rid, params.get("query", ""))
            elif method == "unsubscribe_all":
                self._env.node.event_bus.unsubscribe_all(self._subscriber)
                self._subs.clear()  # stale entries would count toward caps
                self._send_json({"jsonrpc": "2.0", "id": rid, "result": {}})
            else:
                result = _dispatch(self._env, method, params)
                self._send_json(
                    {"jsonrpc": "2.0", "id": rid, "result": result}
                )
        except RPCError as exc:
            self._send_json(_error_obj(rid, exc.code, exc.message, exc.data))
        except Exception as exc:
            self._send_json(_error_obj(rid, -32603, "Internal error", str(exc)))

    # -- subscriptions ---------------------------------------------------------------

    def _subscribe(self, rid, query_str: str) -> None:
        q = parse_query(query_str)
        bus = self._env.node.event_bus
        # reference rpc/core/events.go Subscribe: both limits enforced at
        # subscribe time — the config knobs were previously inert
        rpc_cfg = self._env.node.config.rpc
        max_clients = rpc_cfg.max_subscription_clients
        max_per_client = rpc_cfg.max_subscriptions_per_client
        if (
            max_clients > 0
            and not self._subs
            and bus.num_clients() >= max_clients
        ):
            raise RPCError(
                -32000,
                f"max_subscription_clients {max_clients} reached",
            )
        if max_per_client > 0 and len(self._subs) >= max_per_client:
            raise RPCError(
                -32000,
                f"max_subscriptions_per_client {max_per_client} reached",
            )
        sub = bus.subscribe(self._subscriber, q)
        self._subs[query_str] = sub
        self._send_json({"jsonrpc": "2.0", "id": rid, "result": {}})

        def pump():
            while self._alive:
                try:
                    msg = sub.next(timeout=0.5)
                except TimeoutError:
                    continue
                except SubscriptionCancelled as exc:
                    if query_str not in self._subs:
                        return  # client unsubscribed deliberately: no error
                    # tell the client instead of going silent (the bus
                    # evicts subscribers that fall behind)
                    try:
                        self._send_json(
                            _error_obj(
                                rid, -32000, "subscription cancelled", str(exc)
                            )
                        )
                    except (ConnectionError, OSError):
                        pass
                    self._subs.pop(query_str, None)
                    return
                try:
                    self._send_json(
                        {
                            "jsonrpc": "2.0",
                            "id": rid,
                            "result": {
                                "query": query_str,
                                "data": {
                                    "type": type(msg.data).__name__,
                                    "value": _event_value_json(msg.data),
                                },
                                "events": {
                                    k: list(v) for k, v in msg.events.items()
                                },
                            },
                        }
                    )
                except (ConnectionError, OSError):
                    return

        threading.Thread(
            target=pump, name=f"ws-pump-{self._subscriber}", daemon=True
        ).start()

    def _unsubscribe(self, rid, query_str: str) -> None:
        sub = self._subs.pop(query_str, None)
        if sub is not None:
            self._env.node.event_bus.unsubscribe(
                self._subscriber, sub.query
            )
        self._send_json({"jsonrpc": "2.0", "id": rid, "result": {}})


def _event_value_json(data) -> dict:
    """Best-effort JSON for event payloads."""
    from cometbft_tpu.rpc.serializers import block_json, header_json, tx_result_json
    from cometbft_tpu.types.event_bus import (
        EventDataNewBlock,
        EventDataNewBlockHeader,
        EventDataTx,
    )
    from cometbft_tpu.rpc.serializers import b64

    if isinstance(data, EventDataNewBlock):
        return {"block": block_json(data.block)}
    if isinstance(data, EventDataNewBlockHeader):
        return {"header": header_json(data.header)}
    if isinstance(data, EventDataTx):
        return {
            "TxResult": {
                "height": str(data.height),
                "index": data.index,
                "tx": b64(data.tx),
                "result": tx_result_json(data.result),
            }
        }
    return {"repr": repr(data)}


class RPCServer:
    def __init__(self, env: Environment, logger: Optional[Logger] = None):
        self.env = env
        self.logger = logger or new_nop_logger()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.bound_port: Optional[int] = None

    def serve(self, host: str, port: int) -> None:
        env, logger = self.env, self.logger

        class Handler(_Handler):
            pass

        Handler.env = env
        Handler.logger = logger
        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.bound_port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="rpc-http",
            daemon=True,
        )
        self._thread.start()
        self.logger.info("RPC server listening", addr=f"{host}:{self.bound_port}")

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def is_running(self) -> bool:
        return self._httpd is not None
