"""JSON-RPC HTTP client.

Reference: rpc/client/http — the Go client used by operators, the light
client's HTTP provider, and statesync's RPC state providers. Speaks the
same JSON-RPC-over-HTTP-POST the server in rpc/server.py serves; result
payloads are returned as parsed dicts (the JSON shapes in
rpc/serializers.py).
"""

from __future__ import annotations

import base64
import itertools
import json
import urllib.request
from typing import List, Optional

from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import BlockID, Commit, CommitSig, PartSetHeader
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet


class RPCClientError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message} {data}".strip())
        self.code = code
        self.message = message
        self.data = data


class HTTPClient:
    """Minimal blocking JSON-RPC client over HTTP POST."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        if "://" not in base_url:
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, params: Optional[dict] = None):
        body = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": next(self._ids),
                "method": method,
                "params": params or {},
            }
        ).encode()
        req = urllib.request.Request(
            self.base_url + "/",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if "error" in payload:
            err = payload["error"]
            raise RPCClientError(
                err.get("code", -1), err.get("message", ""), err.get("data", "")
            )
        return payload["result"]

    # -- typed convenience wrappers (rpc/client/http verbs) ------------------

    def status(self) -> dict:
        return self.call("status")

    def block(self, height: Optional[int] = None) -> dict:
        return self.call("block", {"height": height} if height else {})

    def commit(self, height: Optional[int] = None) -> dict:
        return self.call("commit", {"height": height} if height else {})

    def validators(
        self, height: Optional[int] = None, page: int = 1, per_page: int = 100
    ) -> dict:
        params = {"page": page, "per_page": per_page}
        if height:
            params["height"] = height
        return self.call("validators", params)

    def consensus_params(self, height: Optional[int] = None) -> dict:
        return self.call(
            "consensus_params", {"height": height} if height else {}
        )

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        return self.call(
            "broadcast_tx_sync", {"tx": base64.b64encode(tx).decode()}
        )

    def broadcast_tx_commit(self, tx: bytes) -> dict:
        return self.call(
            "broadcast_tx_commit", {"tx": base64.b64encode(tx).decode()}
        )

    def tx(self, tx_hash: bytes) -> dict:
        return self.call("tx", {"hash": base64.b64encode(tx_hash).decode()})

    def tx_search(self, query: str, **kw) -> dict:
        return self.call("tx_search", {"query": query, **kw})

    def block_search(self, query: str, **kw) -> dict:
        return self.call("block_search", {"query": query, **kw})

    def abci_query(self, path: str, data: bytes) -> dict:
        return self.call(
            "abci_query", {"path": path, "data": data.hex()}
        )

    def net_info(self) -> dict:
        return self.call("net_info")


# -- JSON → domain type parsing (inverse of rpc/serializers.py) --------------


def _b64(s: str) -> bytes:
    return base64.b64decode(s) if s else b""


def _ts(s: str) -> Timestamp:
    # RFC3339 with nanoseconds
    if "." in s:
        base_part, frac = s.rstrip("Z").split(".", 1)
        nanos = int(frac.ljust(9, "0")[:9])
    else:
        base_part, nanos = s.rstrip("Z"), 0
    import datetime as dt

    d = dt.datetime.strptime(base_part, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=dt.timezone.utc
    )
    return Timestamp(int(d.timestamp()), nanos)


def parse_block_id(j: dict) -> BlockID:
    parts = j.get("parts") or j.get("part_set_header") or {}
    return BlockID(
        bytes.fromhex(j.get("hash", "")),
        PartSetHeader(
            int(parts.get("total", 0)), bytes.fromhex(parts.get("hash", ""))
        ),
    )


def parse_header(j: dict):
    from cometbft_tpu.proto.version import ConsensusVersion
    from cometbft_tpu.types.block import Header

    h = Header()
    ver = j.get("version", {})
    h.version = ConsensusVersion(
        int(ver.get("block", 0)), int(ver.get("app", 0))
    )
    h.chain_id = j["chain_id"]
    h.height = int(j["height"])
    h.time = _ts(j["time"])
    h.last_block_id = parse_block_id(j.get("last_block_id") or {})
    h.last_commit_hash = bytes.fromhex(j.get("last_commit_hash", ""))
    h.data_hash = bytes.fromhex(j.get("data_hash", ""))
    h.validators_hash = bytes.fromhex(j.get("validators_hash", ""))
    h.next_validators_hash = bytes.fromhex(j.get("next_validators_hash", ""))
    h.consensus_hash = bytes.fromhex(j.get("consensus_hash", ""))
    h.app_hash = bytes.fromhex(j.get("app_hash", ""))
    h.last_results_hash = bytes.fromhex(j.get("last_results_hash", ""))
    h.evidence_hash = bytes.fromhex(j.get("evidence_hash", ""))
    h.proposer_address = bytes.fromhex(j.get("proposer_address", ""))
    return h


def parse_commit(j: dict) -> Commit:
    sigs = []
    for s in j.get("signatures", []):
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=bytes.fromhex(s.get("validator_address", "")),
                timestamp=_ts(s["timestamp"])
                if s.get("timestamp")
                else Timestamp(0, 0),
                signature=_b64(s.get("signature") or ""),
            )
        )
    return Commit(
        height=int(j["height"]),
        round=int(j["round"]),
        block_id=parse_block_id(j["block_id"]),
        signatures=sigs,
    )


def parse_validators(items: List[dict]) -> ValidatorSet:
    from cometbft_tpu.crypto import ed25519

    vals = []
    for v in items:
        pk = v["pub_key"]
        vals.append(
            Validator(
                address=bytes.fromhex(v["address"]),
                pub_key=ed25519.PubKeyEd25519(_b64(pk["value"])),
                voting_power=int(v["voting_power"]),
                proposer_priority=int(v.get("proposer_priority", 0)),
            )
        )
    return ValidatorSet(vals)
