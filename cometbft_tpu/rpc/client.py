"""JSON-RPC HTTP client.

Reference: rpc/client/http — the Go client used by operators, the light
client's HTTP provider, and statesync's RPC state providers. Speaks the
same JSON-RPC-over-HTTP-POST the server in rpc/server.py serves; result
payloads are returned as parsed dicts (the JSON shapes in
rpc/serializers.py).
"""

from __future__ import annotations

import base64
import itertools
import json
import urllib.request
from typing import List, Optional

from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import BlockID, Commit, CommitSig, PartSetHeader
from cometbft_tpu.types.validator import Validator
from cometbft_tpu.types.validator_set import ValidatorSet


class RPCClientError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(f"RPC error {code}: {message} {data}".strip())
        self.code = code
        self.message = message
        self.data = data


class HTTPClient:
    """Minimal blocking JSON-RPC client over HTTP POST."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        if "://" not in base_url:
            base_url = "http://" + base_url
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self._ids = itertools.count(1)

    def call(self, method: str, params: Optional[dict] = None):
        body = json.dumps(
            {
                "jsonrpc": "2.0",
                "id": next(self._ids),
                "method": method,
                "params": params or {},
            }
        ).encode()
        req = urllib.request.Request(
            self.base_url + "/",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            payload = json.loads(resp.read())
        if "error" in payload:
            err = payload["error"]
            raise RPCClientError(
                err.get("code", -1), err.get("message", ""), err.get("data", "")
            )
        return payload["result"]

    # -- typed convenience wrappers (rpc/client/http verbs) ------------------

    def status(self) -> dict:
        return self.call("status")

    def block(self, height: Optional[int] = None) -> dict:
        return self.call("block", {"height": height} if height else {})

    def commit(self, height: Optional[int] = None) -> dict:
        return self.call("commit", {"height": height} if height else {})

    def validators(
        self, height: Optional[int] = None, page: int = 1, per_page: int = 100
    ) -> dict:
        params = {"page": page, "per_page": per_page}
        if height:
            params["height"] = height
        return self.call("validators", params)

    def consensus_params(self, height: Optional[int] = None) -> dict:
        return self.call(
            "consensus_params", {"height": height} if height else {}
        )

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        return self.call(
            "broadcast_tx_sync", {"tx": base64.b64encode(tx).decode()}
        )

    def broadcast_tx_commit(self, tx: bytes) -> dict:
        return self.call(
            "broadcast_tx_commit", {"tx": base64.b64encode(tx).decode()}
        )

    def tx(self, tx_hash: bytes) -> dict:
        return self.call("tx", {"hash": base64.b64encode(tx_hash).decode()})

    def tx_search(self, query: str, **kw) -> dict:
        return self.call("tx_search", {"query": query, **kw})

    def block_search(self, query: str, **kw) -> dict:
        return self.call("block_search", {"query": query, **kw})

    def abci_query(self, path: str, data: bytes) -> dict:
        return self.call(
            "abci_query", {"path": path, "data": data.hex()}
        )

    def net_info(self) -> dict:
        return self.call("net_info")


# -- JSON → domain type parsing (inverse of rpc/serializers.py) --------------


def _b64(s: str) -> bytes:
    return base64.b64decode(s) if s else b""


def _ts(s: str) -> Timestamp:
    # RFC3339 with nanoseconds
    if "." in s:
        base_part, frac = s.rstrip("Z").split(".", 1)
        nanos = int(frac.ljust(9, "0")[:9])
    else:
        base_part, nanos = s.rstrip("Z"), 0
    import datetime as dt

    d = dt.datetime.strptime(base_part, "%Y-%m-%dT%H:%M:%S").replace(
        tzinfo=dt.timezone.utc
    )
    return Timestamp(int(d.timestamp()), nanos)


def parse_block_id(j: dict) -> BlockID:
    parts = j.get("parts") or j.get("part_set_header") or {}
    return BlockID(
        bytes.fromhex(j.get("hash", "")),
        PartSetHeader(
            int(parts.get("total", 0)), bytes.fromhex(parts.get("hash", ""))
        ),
    )


def parse_header(j: dict):
    from cometbft_tpu.proto.version import ConsensusVersion
    from cometbft_tpu.types.block import Header

    h = Header()
    ver = j.get("version", {})
    h.version = ConsensusVersion(
        int(ver.get("block", 0)), int(ver.get("app", 0))
    )
    h.chain_id = j["chain_id"]
    h.height = int(j["height"])
    h.time = _ts(j["time"])
    h.last_block_id = parse_block_id(j.get("last_block_id") or {})
    h.last_commit_hash = bytes.fromhex(j.get("last_commit_hash", ""))
    h.data_hash = bytes.fromhex(j.get("data_hash", ""))
    h.validators_hash = bytes.fromhex(j.get("validators_hash", ""))
    h.next_validators_hash = bytes.fromhex(j.get("next_validators_hash", ""))
    h.consensus_hash = bytes.fromhex(j.get("consensus_hash", ""))
    h.app_hash = bytes.fromhex(j.get("app_hash", ""))
    h.last_results_hash = bytes.fromhex(j.get("last_results_hash", ""))
    h.evidence_hash = bytes.fromhex(j.get("evidence_hash", ""))
    h.proposer_address = bytes.fromhex(j.get("proposer_address", ""))
    return h


def parse_commit(j: dict) -> Commit:
    sigs = []
    for s in j.get("signatures", []):
        sigs.append(
            CommitSig(
                block_id_flag=int(s["block_id_flag"]),
                validator_address=bytes.fromhex(s.get("validator_address", "")),
                timestamp=_ts(s["timestamp"])
                if s.get("timestamp")
                else Timestamp(0, 0),
                signature=_b64(s.get("signature") or ""),
            )
        )
    return Commit(
        height=int(j["height"]),
        round=int(j["round"]),
        block_id=parse_block_id(j["block_id"]),
        signatures=sigs,
    )


def parse_validators(items: List[dict]) -> ValidatorSet:
    from cometbft_tpu.crypto import ed25519

    vals = []
    for v in items:
        pk = v["pub_key"]
        vals.append(
            Validator(
                address=bytes.fromhex(v["address"]),
                pub_key=ed25519.PubKeyEd25519(_b64(pk["value"])),
                voting_power=int(v["voting_power"]),
                proposer_priority=int(v.get("proposer_priority", 0)),
            )
        )
    return ValidatorSet(vals)


# ---------------------------------------------------------------------------
# WebSocket client (rpc/client/http/http.go:574 WSEvents)
# ---------------------------------------------------------------------------


class WSClient:
    """JSON-RPC over WebSocket with event subscriptions — the programmatic
    consumer of the server's event stream, so tooling can subscribe
    instead of polling (reference WSEvents).

    Usage::

        ws = WSClient("127.0.0.1:26657")
        ws.connect()
        sub = ws.subscribe("tm.event='NewBlock'")
        msg = sub.next(timeout=10)   # {"query", "data", "events"}
        ws.close()
    """

    def __init__(self, addr: str, timeout: float = 10.0):
        import socket as _socket

        self._socket_mod = _socket
        addr = addr.split("://", 1)[-1].rstrip("/")
        host, _, port = addr.partition(":")
        self._host, self._port = host, int(port or 26657)
        self.timeout = timeout
        self._sock = None
        self._send_mtx = None
        self._ids = itertools.count(1)
        self._pending = {}  # id -> queue of responses
        self._subs = {}  # query -> _WSSubscription
        self._reader = None
        self._closed = False

    # -- connection -----------------------------------------------------------

    def connect(self) -> None:
        import hashlib
        import os
        import queue
        import threading

        self._queue_mod = queue
        sock = self._socket_mod.create_connection(
            (self._host, self._port), timeout=self.timeout
        )
        key = base64.b64encode(os.urandom(16)).decode()
        sock.sendall(
            (
                f"GET /websocket HTTP/1.1\r\n"
                f"Host: {self._host}:{self._port}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        # read the 101 response headers
        buf = b""
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(4096)
            if not chunk:
                raise ConnectionError("ws handshake: connection closed")
            buf += chunk
        status = buf.split(b"\r\n", 1)[0].decode()
        if " 101 " not in status + " ":
            raise ConnectionError(f"ws handshake rejected: {status}")
        want = base64.b64encode(
            hashlib.sha1(
                (key + "258EAFA5-E914-47DA-95CA-C5AB0DC85B11").encode()
            ).digest()
        ).decode()
        accept = ""
        for line in buf.split(b"\r\n"):
            if line.lower().startswith(b"sec-websocket-accept:"):
                accept = line.split(b":", 1)[1].strip().decode()
        if accept != want:
            raise ConnectionError("ws handshake: bad Sec-WebSocket-Accept")
        sock.settimeout(None)
        self._sock = sock
        import threading as _threading

        self._send_mtx = _threading.Lock()
        self._reader = _threading.Thread(
            target=self._read_loop, name="ws-client-reader", daemon=True
        )
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        if self._sock is not None:
            try:
                self._send_frame(0x8, b"")
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    # -- frame codec (client side: payloads MUST be masked) -------------------

    def _send_frame(self, opcode: int, payload: bytes) -> None:
        import os
        import struct

        mask = os.urandom(4)
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([0x80 | n])
        elif n < 1 << 16:
            header += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            header += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        with self._send_mtx:
            self._sock.sendall(header + mask + masked)

    def _read_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("ws closed")
            buf.extend(chunk)
        return bytes(buf)

    def _read_frame(self):
        import struct

        b1, b2 = self._read_exact(2)
        opcode = b1 & 0x0F
        masked = b2 & 0x80
        length = b2 & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", self._read_exact(2))
        elif length == 127:
            (length,) = struct.unpack(">Q", self._read_exact(8))
        mask = self._read_exact(4) if masked else b""
        payload = self._read_exact(length)
        if mask:
            payload = bytes(c ^ mask[i % 4] for i, c in enumerate(payload))
        return opcode, payload

    def _read_loop(self) -> None:
        try:
            while not self._closed:
                opcode, payload = self._read_frame()
                if opcode == 0x8:
                    break
                if opcode == 0x9:  # ping
                    self._send_frame(0xA, payload)
                    continue
                if opcode not in (0x1, 0x2):
                    continue
                try:
                    msg = json.loads(payload)
                except ValueError:
                    continue
                self._route(msg)
        except (ConnectionError, OSError):
            pass
        finally:
            self._closed = True
            for sub in self._subs.values():
                sub._push(None)  # wake blocked readers with EOF
            for q in self._pending.values():
                q.put(None)

    def _route(self, msg: dict) -> None:
        rid = msg.get("id")
        result = msg.get("result")
        # subscription events carry the subscribe call's id and a query
        if isinstance(result, dict) and "query" in result and "data" in result:
            sub = self._subs.get(result["query"])
            if sub is not None:
                sub._push(result)
            return
        q = self._pending.pop(rid, None)
        if q is not None:
            q.put(msg)
            return
        if "error" in msg:
            # an un-requested error frame is the server's async signal
            # that a subscription died (the bus evicts slow subscribers);
            # surface it on every live subscription rather than dropping
            # it — readers get RPCClientError instead of hanging
            for sub in list(self._subs.values()):
                sub._push(msg)

    # -- calls ----------------------------------------------------------------

    def call(self, method: str, params: Optional[dict] = None):
        if self._sock is None:
            raise ConnectionError("not connected — call connect() first")
        rid = next(self._ids)
        q = self._queue_mod.Queue()
        self._pending[rid] = q
        self._send_frame(
            0x1,
            json.dumps(
                {
                    "jsonrpc": "2.0",
                    "id": rid,
                    "method": method,
                    "params": params or {},
                }
            ).encode(),
        )
        try:
            msg = q.get(timeout=self.timeout)
        except self._queue_mod.Empty:
            self._pending.pop(rid, None)
            raise TimeoutError(f"ws call {method!r} timed out") from None
        if msg is None:
            raise ConnectionError("ws closed while waiting for response")
        if "error" in msg:
            err = msg["error"]
            raise RPCClientError(
                err.get("code", -1), err.get("message", ""), err.get("data", "")
            )
        return msg.get("result")

    def subscribe(self, query: str) -> "_WSSubscription":
        sub = _WSSubscription(self, query)
        self._subs[query] = sub
        try:
            self.call("subscribe", {"query": query})
        except Exception:
            self._subs.pop(query, None)
            raise
        return sub

    def unsubscribe(self, query: str) -> None:
        self._subs.pop(query, None)
        self.call("unsubscribe", {"query": query})


class _WSSubscription:
    """A stream of event messages for one query."""

    def __init__(self, client: WSClient, query: str):
        import queue

        self.query = query
        self._client = client
        self._q = queue.Queue()

    def _push(self, item) -> None:
        self._q.put(item)

    def next(self, timeout: Optional[float] = None) -> dict:
        """Block for the next event ({"query", "data", "events"}).
        Raises ConnectionError if the socket died, RPCClientError if the
        server cancelled the subscription (e.g. slow-subscriber
        eviction)."""
        item = self._q.get(timeout=timeout)
        if item is None:
            raise ConnectionError("ws connection closed")
        if "error" in item:
            err = item["error"]
            raise RPCClientError(
                err.get("code", -1), err.get("message", ""), err.get("data", "")
            )
        return item
