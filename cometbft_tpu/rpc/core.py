"""RPC route handlers over a node Environment.

Reference: rpc/core/ — Environment (env.go) + the route set
(routes.go:10-49). Each handler returns a JSON-ready dict; transport
(HTTP POST JSON-RPC, GET URI, WS) lives in rpc/server.py.
"""

from __future__ import annotations

import threading
import uuid
from typing import List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import amino_json
from cometbft_tpu.libs.pubsub.pubsub import SubscriptionCancelled
from cometbft_tpu.mempool import ErrTxInCache
from cometbft_tpu.rpc.serializers import (
    b64,
    block_id_json,
    block_json,
    block_meta_json,
    commit_json,
    header_json,
    hex_up,
    tx_result_json,
    validator_json,
)
from cometbft_tpu.types.event_bus import EVENT_QUERY_TX, TX_HASH_KEY
from cometbft_tpu.types.tx import Tx


class RPCError(Exception):
    def __init__(self, code: int, message: str, data: str = ""):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


class Environment:
    """rpc/core/env.go — everything the handlers reach into."""

    def __init__(self, node):
        self.node = node

    # -- info routes ---------------------------------------------------------

    def health(self) -> dict:
        return {}

    def status(self) -> dict:
        node = self.node
        latest_height = node.block_store.height()
        latest_meta = (
            node.block_store.load_block_meta(latest_height)
            if latest_height > 0
            else None
        )
        earliest_height = node.block_store.base()
        earliest_meta = (
            node.block_store.load_block_meta(earliest_height)
            if earliest_height > 0
            else None
        )
        pub_key = (
            node.priv_validator.get_pub_key()
            if node.priv_validator is not None
            else None
        )
        la = node.listen_addr()
        return {
            "node_info": {
                "id": node.node_key.id(),
                "listen_addr": f"{la.ip}:{la.port}" if la else "",
                "network": node.genesis_doc.chain_id,
                "moniker": node.config.base.moniker,
                "channels": node.transport.node_info.channels.hex(),
            },
            "sync_info": {
                "latest_block_hash": hex_up(
                    latest_meta.block_id.hash if latest_meta else b""
                ),
                "latest_app_hash": hex_up(
                    latest_meta.header.app_hash if latest_meta else b""
                ),
                "latest_block_height": str(latest_height),
                "latest_block_time": (
                    latest_meta.header.time.to_rfc3339()
                    if latest_meta
                    else ""
                ),
                "earliest_block_height": str(earliest_height),
                "earliest_block_hash": hex_up(
                    earliest_meta.block_id.hash if earliest_meta else b""
                ),
                "catching_up": node.is_syncing(),
            },
            "validator_info": {
                "address": hex_up(pub_key.address()) if pub_key else "",
                "pub_key": amino_json.to_tagged(pub_key)
                if pub_key
                else None,
                "voting_power": str(self._our_voting_power(pub_key)),
            },
        }

    def _our_voting_power(self, pub_key) -> int:
        if pub_key is None:
            return 0
        state = self.node.consensus_state.state
        _, val = state.validators.get_by_address(pub_key.address())
        return val.voting_power if val else 0

    def net_info(self) -> dict:
        sw = self.node.switch
        peers = []
        for p in sw.peers.list():
            na = p.net_address()
            peers.append(
                {
                    "node_info": {
                        "id": p.id(),
                        "moniker": p.node_info.moniker,
                        "network": p.node_info.network,
                    },
                    "is_outbound": p.is_outbound(),
                    "remote_ip": na.ip if na else "",
                }
            )
        return {
            "listening": self.node.transport.listen_addr is not None,
            "listeners": [str(self.node.transport.listen_addr or "")],
            "n_peers": str(len(peers)),
            "peers": peers,
        }

    def genesis(self) -> dict:
        import json

        return {"genesis": json.loads(self.node.genesis_doc.to_json())}

    # -- blockchain routes ----------------------------------------------------

    def _height_or_latest(self, height: Optional[int]) -> int:
        store = self.node.block_store
        if height is None or height <= 0:
            return store.height()
        if height > store.height():
            raise RPCError(
                -32603,
                f"height {height} must be less than or equal to the "
                f"current blockchain height {store.height()}",
            )
        if height < store.base():
            raise RPCError(
                -32603,
                f"height {height} is not available, lowest height is "
                f"{store.base()}",
            )
        return height

    def block(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        block = self.node.block_store.load_block(h)
        meta = self.node.block_store.load_block_meta(h)
        if block is None or meta is None:
            raise RPCError(-32603, f"block at height {h} not found")
        return {
            "block_id": block_id_json(meta.block_id),
            "block": block_json(block),
        }

    def block_by_hash(self, hash_: bytes) -> dict:
        block = self.node.block_store.load_block_by_hash(hash_)
        if block is None:
            return {"block_id": None, "block": None}
        return self.block(block.header.height)

    def blockchain(
        self, min_height: int = 0, max_height: int = 0
    ) -> dict:
        """rpc/core/blocks.go BlockchainInfo — metas for a height range,
        newest first, capped at 20."""
        store = self.node.block_store
        base, height = store.base(), store.height()
        if max_height <= 0:
            max_height = height
        max_height = min(height, max_height)
        if min_height <= 0:
            min_height = 1
        min_height = max(base, min_height, max_height - 19)
        if min_height > max_height:
            raise RPCError(
                -32603,
                f"min height {min_height} can't be greater than max "
                f"height {max_height}",
            )
        metas = []
        for h in range(max_height, min_height - 1, -1):
            meta = store.load_block_meta(h)
            if meta is not None:
                metas.append(block_meta_json(meta))
        return {"last_height": str(height), "block_metas": metas}

    def commit(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        store = self.node.block_store
        meta = store.load_block_meta(h)
        if meta is None:
            raise RPCError(-32603, f"block at height {h} not found")
        if h == store.height():
            commit = store.load_seen_commit(h)
            canonical = False
        else:
            commit = store.load_block_commit(h)
            canonical = True
        return {
            "signed_header": {
                "header": header_json(meta.header),
                "commit": commit_json(commit),
            },
            "canonical": canonical,
        }

    def validators(
        self,
        height: Optional[int] = None,
        page: int = 1,
        per_page: int = 30,
    ) -> dict:
        h = self._height_or_latest(height)
        vals = self.node.state_store.load_validators(h)
        total = vals.size()
        per_page = max(1, min(per_page, 100))
        pages = max(1, (total + per_page - 1) // per_page)
        if page < 1 or page > pages:
            raise RPCError(-32603, f"page should be within [1, {pages}]")
        start = (page - 1) * per_page
        return {
            "block_height": str(h),
            "validators": [
                validator_json(v)
                for v in vals.validators[start : start + per_page]
            ],
            "count": str(min(per_page, total - start)),
            "total": str(total),
        }

    def consensus_params(self, height: Optional[int] = None) -> dict:
        h = self._height_or_latest(height)
        params = self.node.state_store.load_consensus_params(h)
        return {
            "block_height": str(h),
            "consensus_params": params.to_json(),
        }

    def consensus_state(self) -> dict:
        rs = self.node.consensus_state.get_round_state()
        return {
            "round_state": {
                "height/round/step": f"{rs.height}/{rs.round}/{int(rs.step)}",
                "height": str(rs.height),
                "round": rs.round,
                "step": int(rs.step),
                "proposal_block_hash": hex_up(
                    rs.proposal_block.hash()
                    if rs.proposal_block is not None
                    else b""
                ),
            }
        }

    def dump_consensus_state(self) -> dict:
        out = self.consensus_state()
        peers = []
        from cometbft_tpu.types.keys import PEER_STATE_KEY

        for p in self.node.switch.peers.list():
            ps = p.get(PEER_STATE_KEY)
            if ps is None:
                continue
            prs = ps.get_round_state()
            peers.append(
                {
                    "node_address": p.id(),
                    "peer_state": {
                        "height": str(prs.height),
                        "round": prs.round,
                        "step": int(prs.step),
                    },
                }
            )
        out["peers"] = peers
        return out

    # -- ABCI routes -----------------------------------------------------------

    def abci_info(self) -> dict:
        res = self.node.proxy_app.query().info_sync(abci.RequestInfo())
        return {
            "response": {
                "data": res.data,
                "version": res.version,
                "app_version": str(res.app_version),
                "last_block_height": str(res.last_block_height),
                "last_block_app_hash": b64(res.last_block_app_hash),
            }
        }

    def abci_query(
        self,
        path: str = "",
        data: bytes = b"",
        height: int = 0,
        prove: bool = False,
    ) -> dict:
        res = self.node.proxy_app.query().query_sync(
            abci.RequestQuery(path=path, data=data, height=height, prove=prove)
        )
        return {
            "response": {
                "code": res.code,
                "log": res.log,
                "key": b64(res.key) if res.key else None,
                "value": b64(res.value) if res.value else None,
                "height": str(res.height),
            }
        }

    # -- mempool routes ----------------------------------------------------------

    def unconfirmed_txs(self, limit: int = 30) -> dict:
        txs = self.node.mempool.reap_max_txs(max(1, min(limit, 100)))
        return {
            "n_txs": str(len(txs)),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
            "txs": [b64(tx) for tx in txs],
        }

    def num_unconfirmed_txs(self) -> dict:
        return {
            "n_txs": str(self.node.mempool.size()),
            "total": str(self.node.mempool.size()),
            "total_bytes": str(self.node.mempool.size_bytes()),
            "txs": None,
        }

    def broadcast_tx_async(self, tx: bytes) -> dict:
        """Fire and forget (rpc/core/mempool.go:22)."""
        try:
            self.node.mempool.check_tx(tx, None)
        except ErrTxInCache:
            pass
        except Exception as exc:
            raise RPCError(-32603, str(exc)) from exc
        return {
            "code": 0, "data": "", "log": "", "codespace": "",
            "hash": hex_up(Tx(tx).hash()),
        }

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        """Wait for CheckTx (rpc/core/mempool.go:38)."""
        done = threading.Event()
        out = {}

        def cb(res):
            r = res.value
            out.update(
                code=r.code, data=b64(r.data) if r.data else "", log=r.log,
                codespace=getattr(r, "codespace", ""),
            )
            done.set()

        try:
            self.node.mempool.check_tx(tx, cb)
        except ErrTxInCache as exc:
            raise RPCError(-32603, f"tx already exists in cache") from exc
        except Exception as exc:
            raise RPCError(-32603, str(exc)) from exc
        if not done.wait(10.0):
            raise RPCError(-32603, "timed out waiting for CheckTx")
        out["hash"] = hex_up(Tx(tx).hash())
        return out

    def broadcast_tx_commit_raw(self, tx: bytes):
        """CheckTx, then wait for the DeliverTx event — returning the
        REAL ABCI response objects, for callers that re-serialize to a
        different wire format (the gRPC BroadcastAPI).

        → (ResponseCheckTx, Optional[ResponseDeliverTx], height)."""
        bus = self.node.event_bus
        tx_hash = Tx(tx).hash()
        subscriber = f"rpc-commit-{uuid.uuid4().hex[:12]}"
        from cometbft_tpu.libs.pubsub.query import parse_query

        q = parse_query(f"{TX_HASH_KEY}='{tx_hash.hex().upper()}'")
        sub = bus.subscribe(subscriber, q)
        try:
            done = threading.Event()
            check_box = []

            def cb(res):
                check_box.append(res.value)
                done.set()

            try:
                self.node.mempool.check_tx(tx, cb)
            except ErrTxInCache as exc:
                raise RPCError(-32603, "tx already exists in cache") from exc
            except Exception as exc:
                raise RPCError(-32603, str(exc)) from exc
            if not done.wait(10.0):
                raise RPCError(-32603, "timed out waiting for CheckTx")
            check = check_box[0]
            if check.code != 0:
                return check, None, 0
            timeout = (
                self.node.config.rpc.timeout_broadcast_tx_commit_ns / 1e9
            )
            try:
                msg = sub.next(timeout=timeout)
            except (TimeoutError, SubscriptionCancelled) as exc:
                raise RPCError(
                    -32603, "timed out waiting for tx to be included in a block"
                ) from exc
            ev = msg.data
            return check, ev.result, ev.height
        finally:
            bus.unsubscribe_all(subscriber)

    def broadcast_tx_commit(self, tx: bytes) -> dict:
        """CheckTx, then wait for the DeliverTx event
        (rpc/core/mempool.go:58) — bounded by
        config.rpc.timeout_broadcast_tx_commit."""
        check, deliver, height = self.broadcast_tx_commit_raw(tx)
        check_json = tx_result_json(check) | {"hash": hex_up(Tx(tx).hash())}
        return {
            "check_tx": check_json,
            "deliver_tx": tx_result_json(deliver) if deliver else None,
            "hash": hex_up(Tx(tx).hash()),
            "height": str(height),
        }

    # -- indexer routes (rpc/core/tx.go, blocks.go) ---------------------------

    def _tx_json(self, res) -> dict:
        return {
            "hash": hex_up(Tx(res.tx).hash()),
            "height": str(res.height),
            "index": res.index,
            "tx_result": tx_result_json(res.result),
            "tx": b64(res.tx),
        }

    def tx(self, hash_: bytes, prove: bool = False) -> dict:
        """rpc/core/tx.go:19 Tx — look one transaction up by hash;
        prove=true attaches the Merkle inclusion proof against the
        block's DataHash (tx.go:39-47)."""
        res = self.node.tx_indexer.get(hash_)
        if res is None:
            raise RPCError(-32603, f"tx ({hash_.hex()}) not found")
        out = self._tx_json(res)
        if prove:
            from cometbft_tpu.types.tx import Txs

            block = self.node.block_store.load_block(res.height)
            if block is None:
                raise RPCError(
                    -32603, f"block {res.height} not found for proof"
                )
            root, proof = Txs(block.data.txs).proof(res.index)
            out["proof"] = {
                "root_hash": hex_up(root),
                "data": b64(res.tx),
                "proof": {
                    "total": str(proof.total),
                    "index": str(proof.index),
                    "leaf_hash": b64(proof.leaf_hash),
                    "aunts": [b64(a) for a in proof.aunts],
                },
            }
        return out

    @staticmethod
    def _search(
        searcher,
        query: str,
        page: int,
        per_page: int,
        order_by: str,
        default_order: str = "asc",
    ):
        """Shared tx_search/block_search plumbing: parse + validate up
        front (before paying for the index scan), then paginate. Returns
        (page of results, total count)."""
        from cometbft_tpu.libs.pubsub.query import parse_query

        if order_by not in ("asc", "desc", ""):
            raise RPCError(-32602, "order_by must be 'asc' or 'desc'")
        try:
            q = parse_query(query)
        except Exception as exc:
            raise RPCError(-32602, f"failed to parse query: {exc}") from exc
        results = searcher(q)
        if (order_by or default_order) == "desc":
            results = list(reversed(results))
        page = max(1, page)
        per_page = min(max(1, per_page), 100)
        start = (page - 1) * per_page
        return results[start : start + per_page], len(results)

    def tx_search(
        self,
        query: str,
        page: int = 1,
        per_page: int = 30,
        order_by: str = "",
    ) -> dict:
        """rpc/core/tx.go:54 TxSearch."""
        results, total = self._search(
            self.node.tx_indexer.search, query, page, per_page, order_by
        )
        return {
            "txs": [self._tx_json(r) for r in results],
            "total_count": str(total),
        }

    def block_results(self, height: Optional[int] = None) -> dict:
        """rpc/core/blocks.go:149 BlockResults — the persisted ABCI
        responses for one height: DeliverTx results, BeginBlock/EndBlock
        events, validator and consensus-param updates. This is the
        standard surface apps and indexers consume execution results
        from."""
        from cometbft_tpu.rpc.serializers import abci_params_json, events_json
        from cometbft_tpu.state.store import ErrNoABCIResponsesForHeight

        h = self._height_or_latest(height)
        try:
            resp = self.node.state_store.load_abci_responses(h)
        except ErrNoABCIResponsesForHeight as exc:
            raise RPCError(-32603, str(exc)) from exc
        end = resp.end_block or abci.ResponseEndBlock()
        begin = resp.begin_block or abci.ResponseBeginBlock()
        params = None
        if end.consensus_param_updates is not None:
            params = abci_params_json(end.consensus_param_updates)
        return {
            "height": str(h),
            "txs_results": [tx_result_json(d) for d in resp.deliver_txs]
            or None,
            "begin_block_events": events_json(begin.events) or None,
            "end_block_events": events_json(end.events) or None,
            "validator_updates": [
                {
                    "pub_key": {v.pub_key.type: b64(v.pub_key.data)},
                    "power": str(v.power),
                }
                for v in end.validator_updates
            ]
            or None,
            "consensus_param_updates": params,
        }

    def check_tx(self, tx: bytes) -> dict:
        """rpc/core/mempool.go:177 CheckTx — run a transaction through
        the app's mempool-connection CheckTx WITHOUT adding it to the
        mempool. For probing validity."""
        res = self.node.proxy_app.mempool().check_tx_sync(
            abci.RequestCheckTx(tx=bytes(tx))
        )
        return tx_result_json(res)

    def broadcast_evidence(self, evidence: bytes) -> dict:
        """rpc/core/evidence.go:14 BroadcastEvidence. The evidence rides
        as base64 of its proto encoding (this framework's RPC carries all
        binary payloads b64, where the reference uses amino JSON)."""
        from cometbft_tpu.types.evidence import decode_evidence

        try:
            ev = decode_evidence(bytes(evidence))
        except Exception as exc:
            raise RPCError(-32602, f"invalid evidence: {exc}") from exc
        try:
            self.node.evidence_pool.add_evidence(ev)
        except Exception as exc:
            raise RPCError(-32603, f"failed to add evidence: {exc}") from exc
        return {"hash": hex_up(ev.hash())}

    _GENESIS_CHUNK_SIZE = 16 * 1024 * 1024

    def genesis_chunked(self, chunk: int = 0) -> dict:
        """rpc/core/routes.go:22 GenesisChunked — the genesis document
        b64'd and split into 16 MB chunks, for genesis files too large
        for one JSON-RPC response."""
        data = getattr(self, "_genesis_chunks", None)
        if data is None:
            raw = b64(self.node.genesis_doc.to_json().encode()).encode()
            size = self._GENESIS_CHUNK_SIZE
            data = [
                raw[i : i + size].decode() for i in range(0, len(raw), size)
            ] or [""]
            self._genesis_chunks = data
        if not 0 <= chunk < len(data):
            raise RPCError(
                -32603,
                f"there are {len(data)} chunks, but specified chunk {chunk}",
            )
        return {
            "chunk": str(chunk),
            "total": str(len(data)),
            "data": data[chunk],
        }

    # -- unsafe routes (routes.go:52-57, registered only with rpc.unsafe) ----

    def _require_unsafe(self):
        if not self.node.config.rpc.unsafe:
            raise RPCError(
                -32601, "unsafe routes are disabled ([rpc] unsafe = false)"
            )

    def unsafe_dial_seeds(self, seeds: List[str]) -> dict:
        """rpc/core/net.go UnsafeDialSeeds."""
        self._require_unsafe()
        if not seeds:
            raise RPCError(-32602, "no seeds provided")
        addrs = self.node.switch.add_persistent_peers(list(seeds))
        self.node.switch.dial_peers_async(addrs)
        return {"log": "Dialing seeds in progress. See /net_info for details"}

    def unsafe_dial_peers(
        self, peers: List[str], persistent: bool = False
    ) -> dict:
        """rpc/core/net.go UnsafeDialPeers."""
        self._require_unsafe()
        if not peers:
            raise RPCError(-32602, "no peers provided")
        peers = list(peers)
        if persistent:
            addrs = self.node.switch.add_persistent_peers(peers)
        else:
            from cometbft_tpu.p2p.netaddr import NetAddress

            addrs = [NetAddress.from_string(p) for p in peers]
        self.node.switch.dial_peers_async(addrs)
        return {"log": "Dialing peers in progress. See /net_info for details"}

    def unsafe_flush_mempool(self) -> dict:
        """rpc/core/mempool.go UnsafeFlushMempool — drop every pending tx."""
        self._require_unsafe()
        self.node.mempool.flush()
        return {}

    def block_search(
        self,
        query: str,
        page: int = 1,
        per_page: int = 30,
        order_by: str = "",
    ) -> dict:
        """rpc/core/blocks.go:174 BlockSearch — unlike tx_search, the
        reference defaults to DESCENDING order (blocks.go:202-207)."""
        heights, total = self._search(
            self.node.block_indexer.search, query, page, per_page, order_by,
            default_order="desc",
        )
        blocks = []
        for h in heights:
            meta = self.node.block_store.load_block_meta(h)
            block = self.node.block_store.load_block(h)
            if meta is None or block is None:
                continue
            blocks.append(
                {
                    "block_id": block_id_json(meta.block_id),
                    "block": block_json(block),
                }
            )
        return {"blocks": blocks, "total_count": str(total)}
