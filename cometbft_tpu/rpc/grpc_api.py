"""Minimal broadcast-only gRPC API.

Reference: rpc/grpc/api.go — service tendermint.rpc.grpc.BroadcastAPI
with Ping and BroadcastTx (types.proto in rpc/grpc). BroadcastTx runs
CheckTx through the mempool and, on success, waits for the DeliverTx
result like broadcast_tx_commit. Frames are hand-rolled proto codecs
driven through gRPC's generic handler API, the same pattern as
abci/grpc.py — no generated stubs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import grpc

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.service import BaseService

_SERVICE = "tendermint.rpc.grpc.BroadcastAPI"


@dataclass
class RequestPing:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestPing":
        return cls()


@dataclass
class ResponsePing:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "ResponsePing":
        return cls()


@dataclass
class RequestBroadcastTx:
    tx: bytes = b""

    def encode(self) -> bytes:
        return protoio.field_bytes(1, self.tx) if self.tx else b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestBroadcastTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.tx = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseBroadcastTx:
    check_tx: Optional[abci.ResponseCheckTx] = field(default=None)
    deliver_tx: Optional[abci.ResponseDeliverTx] = field(default=None)

    def encode(self) -> bytes:
        out = b""
        if self.check_tx is not None:
            out += protoio.field_message(1, self.check_tx.encode())
        if self.deliver_tx is not None:
            out += protoio.field_message(2, self.deliver_tx.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseBroadcastTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.check_tx = abci.ResponseCheckTx.decode(r.read_bytes())
            elif f == 2:
                out.deliver_tx = abci.ResponseDeliverTx.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


class BroadcastAPIServer(BaseService):
    """Serves BroadcastAPI over a node (rpc/grpc/api.go:11)."""

    def __init__(self, addr: str, node):
        super().__init__("BroadcastAPIServer")
        self._addr = addr.split("://", 1)[-1]
        self._node = node
        self._server: Optional[grpc.Server] = None
        self._bound_port = 0

    @property
    def bound_port(self) -> int:
        return self._bound_port

    def _ping(self, request_bytes: bytes, _ctx) -> bytes:
        return ResponsePing().encode()

    def _broadcast_tx(self, request_bytes: bytes, _ctx) -> bytes:
        from cometbft_tpu.rpc.core import Environment, RPCError

        req = RequestBroadcastTx.decode(request_bytes)
        env = Environment(self._node)
        try:
            # the raw ABCI objects, so data/gas/events survive intact
            check, deliver, _ = env.broadcast_tx_commit_raw(req.tx)
        except RPCError as exc:
            raise RuntimeError(exc.message) from exc
        return ResponseBroadcastTx(check_tx=check, deliver_tx=deliver).encode()

    def on_start(self) -> None:
        from concurrent import futures

        handlers = {
            "Ping": grpc.unary_unary_rpc_method_handler(
                self._ping,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
            "BroadcastTx": grpc.unary_unary_rpc_method_handler(
                self._broadcast_tx,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            ),
        }
        service = grpc.method_handlers_generic_handler(_SERVICE, handlers)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
        self._server.add_generic_rpc_handlers((service,))
        self._bound_port = self._server.add_insecure_port(self._addr)
        if self._bound_port == 0:
            raise RuntimeError(f"gRPC server failed to bind {self._addr}")
        self._server.start()

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
            self._server = None


class BroadcastAPIClient:
    """Client for the BroadcastAPI (rpc/grpc/client_server.go)."""

    def __init__(self, addr: str):
        self._addr = addr.split("://", 1)[-1]
        self._channel: Optional[grpc.Channel] = None

    def start(self) -> None:
        self._channel = grpc.insecure_channel(self._addr)

    def stop(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None

    def _call(self, method: str, req_bytes: bytes) -> bytes:
        fn = self._channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        return fn(req_bytes)

    def ping(self) -> ResponsePing:
        return ResponsePing.decode(self._call("Ping", RequestPing().encode()))

    def broadcast_tx(self, tx: bytes) -> ResponseBroadcastTx:
        return ResponseBroadcastTx.decode(
            self._call("BroadcastTx", RequestBroadcastTx(tx=tx).encode())
        )
