"""OpenAPI description of the RPC surface.

Reference: rpc/openapi/openapi.yaml (a hand-maintained 3k-line YAML
served to dredd and docs tooling). Here the spec is GENERATED from the
live route table (`rpc.server._ROUTES`), so it can never drift from the
implementation — `python -m cometbft_tpu.rpc.openapi` prints it, and
the committed `openapi.yaml` is refreshed by the same command.
"""

from __future__ import annotations

from typing import Dict

_TYPE_MAP = {
    int: ("integer", None),
    str: ("string", None),
    bool: ("boolean", None),
    "b64bytes": ("string", "byte"),
    "hexbytes": ("string", "hex"),
    "strlist": ("array", None),
}

_SUMMARIES: Dict[str, str] = {
    "health": "Node heartbeat — empty result when up",
    "status": "Node status: sync info, validator info, node info",
    "net_info": "Network info: listeners, peer list",
    "genesis": "Full genesis document",
    "genesis_chunked": "Genesis served in base64 chunks",
    "blockchain": "Block metas for a height range (newest first)",
    "block": "Block at height (latest when omitted)",
    "block_by_hash": "Block by hash",
    "commit": "Commit (signatures) at height",
    "validators": "Validator set at height, paginated",
    "consensus_params": "Consensus parameters at height",
    "consensus_state": "Compact live consensus round state",
    "dump_consensus_state": "Full live consensus state incl. peers",
    "abci_info": "ABCI application info",
    "abci_query": "Query the application, optionally with proof",
    "unconfirmed_txs": "Mempool transactions, bounded by limit",
    "num_unconfirmed_txs": "Mempool size counters",
    "broadcast_tx_async": "Submit tx, return immediately",
    "broadcast_tx_sync": "Submit tx, wait for CheckTx",
    "broadcast_tx_commit": "Submit tx, wait for a commit (dev only)",
    "tx": "Committed transaction by hash, optional inclusion proof",
    "tx_search": "Search committed txs by event query",
    "block_search": "Search blocks by event query",
    "block_results": "ABCI results (DeliverTx/Begin/EndBlock) at height",
    "check_tx": "Run CheckTx without adding to the mempool",
    "broadcast_evidence": "Submit committed-misbehavior evidence",
    "dial_seeds": "UNSAFE: dial the given seed nodes",
    "dial_peers": "UNSAFE: dial the given peers",
    "unsafe_flush_mempool": "UNSAFE: clear the mempool",
}


def spec() -> dict:
    from cometbft_tpu.rpc.server import _ROUTES

    paths = {}
    for method, (_handler, params) in sorted(_ROUTES.items()):
        parameters = []
        for wire_name, (_py_name, kind) in params.items():
            typ, fmt = _TYPE_MAP.get(kind, ("string", None))
            schema = {"type": typ}
            if fmt:
                schema["format"] = fmt
            if typ == "array":
                schema["items"] = {"type": "string"}
            parameters.append(
                {
                    "name": wire_name,
                    "in": "query",
                    "required": False,
                    "schema": schema,
                }
            )
        op = {
            "operationId": method,
            "summary": _SUMMARIES.get(method, method),
            "tags": ["unsafe"] if "unsafe" in _handler else ["info"],
            "responses": {
                "200": {"description": "JSON-RPC response envelope"}
            },
        }
        if parameters:
            op["parameters"] = parameters
        paths[f"/{method}"] = {"get": op}
    return {
        "openapi": "3.0.0",
        "info": {
            "title": "cometbft_tpu RPC",
            "version": "v0.34-compat",
            "description": (
                "JSON-RPC 2.0 over HTTP GET/POST and WebSocket; every "
                "method is also callable as a URI route (reference: "
                "rpc/openapi/openapi.yaml)."
            ),
        },
        "paths": paths,
    }


def to_yaml() -> str:
    """Minimal YAML emitter (no external deps) — the spec is plain
    dicts/lists/scalars."""

    def emit(obj, indent=0):
        pad = "  " * indent
        out = []
        if isinstance(obj, dict):
            for k, v in obj.items():
                if isinstance(v, (dict, list)) and v:
                    out.append(f"{pad}{k}:")
                    out.extend(emit(v, indent + 1))
                else:
                    out.append(f"{pad}{k}: {_scalar(v)}")
        elif isinstance(obj, list):
            for item in obj:
                if isinstance(item, (dict, list)) and item:
                    lines = emit(item, indent + 1)
                    first = lines[0].lstrip()
                    out.append(f"{pad}- {first}")
                    out.extend(lines[1:])
                else:
                    out.append(f"{pad}- {_scalar(item)}")
        return out

    def _scalar(v):
        if isinstance(v, bool):
            return "true" if v else "false"
        if v is None or v == {} or v == []:
            return "{}" if isinstance(v, dict) else "null"
        if isinstance(v, (int, float)):
            return str(v)
        s = str(v)
        if any(c in s for c in ":#{}[]") or s != s.strip():
            return '"' + s.replace('"', '\\"') + '"'
        return s

    return "\n".join(emit(spec())) + "\n"


if __name__ == "__main__":
    print(to_yaml(), end="")
