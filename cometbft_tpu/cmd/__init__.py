"""CLI — `python -m cometbft_tpu <command>`.

Reference: cmd/cometbft/main.go:16-49 (cobra command tree) and
cmd/cometbft/commands/*: init, start, testnet, show_node_id,
show_validator, gen_validator, gen_node_key, version.
"""

from cometbft_tpu.cmd.commands import main

__all__ = ["main"]
