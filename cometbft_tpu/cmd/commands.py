"""Command implementations.

Reference: cmd/cometbft/commands/{init,run_node,testnet,show_node_id,
show_validator,gen_validator,gen_node_key,version}.go — argparse in place
of cobra, same command surface.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from cometbft_tpu.config import (
    Config,
    default_config,
    load_config_file,
    write_config_file,
)
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.privval import load_or_gen_file_pv
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.genesis import (
    GenesisDoc,
    GenesisValidator,
    pub_key_to_json,
)
from cometbft_tpu.types.params import default_consensus_params
from cometbft_tpu.version import __version__ as VERSION


def _load_config(home: str) -> Config:
    cfg = default_config().set_root(home)
    toml_path = os.path.join(home, "config", "config.toml")
    if os.path.exists(toml_path):
        cfg = load_config_file(toml_path, cfg).set_root(home)
    return cfg


def _ensure_dirs(home: str) -> None:
    for d in ("config", "data"):
        os.makedirs(os.path.join(home, d), exist_ok=True)


def cmd_init(args) -> int:
    """commands/init.go — private validator, node key, genesis."""
    home = args.home
    _ensure_dirs(home)
    cfg = default_config().set_root(home)

    pv = load_or_gen_file_pv(
        cfg.base.priv_validator_key_path(), cfg.base.priv_validator_state_path()
    )
    node_key_path = os.path.join(home, cfg.base.node_key_file)
    NodeKey.load_or_gen(node_key_path)

    genesis_path = cfg.base.genesis_path()
    if os.path.exists(genesis_path):
        print(f"Found genesis file {genesis_path}")
    else:
        doc = GenesisDoc(
            genesis_time=Timestamp.now(),
            chain_id=args.chain_id or f"test-chain-{os.urandom(3).hex()}",
            initial_height=1,
            consensus_params=default_consensus_params(),
            validators=[
                GenesisValidator(
                    pv.get_address(), pv.get_pub_key(), 10, "validator"
                )
            ],
        )
        with open(genesis_path, "w") as f:
            f.write(doc.to_json())
        print(f"Generated genesis file {genesis_path}")

    toml_path = os.path.join(home, "config", "config.toml")
    if not os.path.exists(toml_path):
        write_config_file(toml_path, cfg)
        print(f"Generated config file {toml_path}")
    print(f"Initialized node in {home}")
    return 0


def cmd_start(args) -> int:
    """commands/run_node.go — boot the full node and block."""
    from cometbft_tpu.libs.log import new_tm_logger
    from cometbft_tpu.node import default_new_node

    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    if args.p2p_laddr:
        cfg.p2p.laddr = args.p2p_laddr
    if args.rpc_laddr:
        cfg.rpc.laddr = args.rpc_laddr
    if args.persistent_peers:
        cfg.p2p.persistent_peers = args.persistent_peers
    if args.no_fast_sync:
        cfg.base.fast_sync_mode = False

    logger = new_tm_logger(level=cfg.base.log_level)
    node = default_new_node(cfg, logger=logger)
    node.start()
    print(
        f"Node {node.node_key.id()} started "
        f"(p2p {cfg.p2p.laddr}, rpc {cfg.rpc.laddr})",
        flush=True,
    )

    stop = threading.Event()

    def _sig(_signum, _frame):
        stop.set()

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        while not stop.is_set():
            time.sleep(0.5)
    finally:
        node.stop()
    return 0


def cmd_show_node_id(args) -> int:
    cfg = _load_config(args.home)
    nk = NodeKey.load_or_gen(os.path.join(args.home, cfg.base.node_key_file))
    print(nk.id())
    return 0


def cmd_show_validator(args) -> int:
    cfg = _load_config(args.home)
    pv = load_or_gen_file_pv(
        cfg.base.priv_validator_key_path(), cfg.base.priv_validator_state_path()
    )
    print(json.dumps(pub_key_to_json(pv.get_pub_key())))
    return 0


def cmd_gen_validator(args) -> int:
    """commands/gen_validator.go — print a fresh key pair as JSON."""
    from cometbft_tpu.crypto import ed25519
    from cometbft_tpu.libs import amino_json

    priv = ed25519.gen_priv_key()
    print(
        amino_json.marshal(
            {
                "address": priv.pub_key().address().hex().upper(),
                "pub_key": priv.pub_key(),
                "priv_key": priv,
            },
            indent=2,
        )
    )
    return 0


def cmd_testnet(args) -> int:
    """commands/testnet.go — write N validator home dirs wired together."""
    n = args.v
    base_dir = args.output_dir
    chain_id = args.chain_id or f"chain-{os.urandom(3).hex()}"

    homes = [os.path.join(base_dir, f"node{i}") for i in range(n)]
    pvs, node_keys = [], []
    for home in homes:
        _ensure_dirs(home)
        cfg = default_config().set_root(home)
        pvs.append(
            load_or_gen_file_pv(
                cfg.base.priv_validator_key_path(),
                cfg.base.priv_validator_state_path(),
            )
        )
        node_keys.append(
            NodeKey.load_or_gen(os.path.join(home, cfg.base.node_key_file))
        )

    doc = GenesisDoc(
        genesis_time=Timestamp.now(),
        chain_id=chain_id,
        initial_height=1,
        consensus_params=default_consensus_params(),
        validators=[
            GenesisValidator(pv.get_address(), pv.get_pub_key(), 10, f"node{i}")
            for i, pv in enumerate(pvs)
        ],
    )

    p2p_base, rpc_base = args.p2p_port, args.rpc_port
    if args.hostname_template:
        # container/VM mode (reference --hostname-prefix): every node binds
        # all interfaces on the SAME ports and peers dial by hostname —
        # the shape docker-compose/k8s networks need
        peers = [
            f"{node_keys[i].id()}@{args.hostname_template.format(i)}:{p2p_base}"
            for i in range(n)
        ]
    else:
        # single-host mode: stride 2 per node on loopback — with the
        # default bases (26656/26657) node i gets p2p 26656+2i and rpc
        # 26657+2i, no cross-node collisions
        peers = [
            f"{node_keys[i].id()}@127.0.0.1:{p2p_base + 2 * i}"
            for i in range(n)
        ]
    for i, home in enumerate(homes):
        cfg = default_config().set_root(home)
        cfg.base.proxy_app = args.proxy_app
        cfg.base.moniker = f"node{i}"
        if args.hostname_template:
            cfg.p2p.laddr = f"tcp://0.0.0.0:{p2p_base}"
            cfg.rpc.laddr = f"tcp://0.0.0.0:{rpc_base}"
        else:
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_base + 2 * i}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_base + 2 * i}"
        cfg.p2p.persistent_peers = ",".join(
            p for j, p in enumerate(peers) if j != i
        )
        cfg.p2p.addr_book_strict = False
        # every node shares one host IP in a localnet (testnet.go sets
        # this alongside addr_book_strict=false)
        cfg.p2p.allow_duplicate_ip = True
        with open(cfg.base.genesis_path(), "w") as f:
            f.write(doc.to_json())
        write_config_file(os.path.join(home, "config", "config.toml"), cfg)
    print(f"Successfully initialized {n} node directories in {base_dir}")
    return 0


def cmd_loadtime(args) -> int:
    """Standalone load generator + latency report (test/loadtime): txs
    carry their send timestamp; latency = commit ack - send. Drives
    broadcast_tx_commit over `--connections` concurrent workers against
    one or more node RPC endpoints and prints one JSON report."""
    import json as _json
    import threading as _threading
    import time as _time

    from cometbft_tpu.rpc.client import HTTPClient

    endpoints = [e.strip() for e in args.endpoints.split(",") if e.strip()]
    if not endpoints:
        print("no --endpoints given", file=sys.stderr)
        return 1
    stop = _threading.Event()
    mtx = _threading.Lock()
    stats = {"sent": 0, "committed": 0, "latencies": []}

    def worker(wid: int):
        client = HTTPClient(endpoints[wid % len(endpoints)], timeout=30)
        period = args.connections / args.rate if args.rate > 0 else 0.0
        seq = 0
        while not stop.is_set():
            tx = (
                f"load-c{wid}-{seq}={_time.monotonic_ns()}"
                + "x" * max(0, args.size - 24)
            ).encode()[: max(args.size, 16)]
            seq += 1
            # `sent` counts at SEND time: a commit ack landing after the
            # window closes must not erase that its tx was sent inside it
            with mtx:
                if stop.is_set():
                    break
                stats["sent"] += 1
            t0 = _time.monotonic()
            ok = False
            try:
                res = client.broadcast_tx_commit(tx)
                ok = (res.get("deliver_tx") or {}).get("code", 1) == 0
            except Exception:
                pass
            with mtx:
                # commits landing after the window closes are drained,
                # not measured — throughput divides by the WINDOW
                if ok and not stop.is_set():
                    stats["committed"] += 1
                    stats["latencies"].append(_time.monotonic() - t0)
            stop.wait(period)

    threads = [
        _threading.Thread(target=worker, args=(i,), daemon=True)
        for i in range(args.connections)
    ]
    t_start = _time.monotonic()
    for t in threads:
        t.start()
    try:
        _time.sleep(args.duration)
    except KeyboardInterrupt:
        pass
    stop.set()
    wall = _time.monotonic() - t_start  # the measurement window
    for t in threads:
        t.join(35.0)
    lat = sorted(stats["latencies"])

    def pct(p: float):
        return round(lat[min(int(len(lat) * p), len(lat) - 1)], 4) if lat else None

    print(
        _json.dumps(
            {
                "duration_s": round(wall, 2),
                "connections": args.connections,
                "target_rate_tx_s": args.rate,
                "sent": stats["sent"],
                "committed": stats["committed"],
                "throughput_tx_s": round(stats["committed"] / wall, 2),
                "latency_s": {
                    "min": round(lat[0], 4) if lat else None,
                    "p50": pct(0.50),
                    "p90": pct(0.90),
                    "p99": pct(0.99),
                    "max": round(lat[-1], 4) if lat else None,
                },
            }
        )
    )
    return 0


def cmd_probe_upnp(args) -> int:
    """probe_upnp.go — report the NAT's UPnP capabilities as JSON."""
    import json as _json

    from cometbft_tpu.p2p import upnp

    try:
        caps = upnp.probe(internal_port=args.port)
    except (upnp.UPnPError, OSError) as exc:
        # no gateway / unbindable probe port is a finding, not a crash
        print(_json.dumps({"error": str(exc)}))
        return 0
    print(
        _json.dumps(
            {"port_mapping": caps.port_mapping, "hairpin": caps.hairpin}
        )
    )
    return 0


def cmd_version(_args) -> int:
    print(VERSION)
    return 0


def cmd_abci(args) -> int:
    """abci/cmd/abci-cli — poke an ABCI app over its socket (echo, info,
    deliver_tx, check_tx, commit, query), or serve the builtin kvstore."""
    from cometbft_tpu.abci import types as abci_types
    from cometbft_tpu.abci.client import SocketClient

    sub = args.abci_command
    if sub == "kvstore":
        # serve the example app (abci-cli kvstore)
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.abci.server import SocketServer

        server = SocketServer(args.address, KVStoreApplication())
        server.start()
        print(f"ABCI kvstore server listening on {args.address}", flush=True)
        stop = threading.Event()
        signal.signal(signal.SIGINT, lambda *_: stop.set())
        signal.signal(signal.SIGTERM, lambda *_: stop.set())
        try:
            while not stop.is_set():
                time.sleep(0.5)
        finally:
            server.stop()
        return 0

    client = SocketClient(args.address, must_connect=True)
    client.start()
    try:
        if sub == "echo":
            res = client.echo_sync(args.data or "")
            print(res.message)
        elif sub == "info":
            res = client.info_sync(abci_types.RequestInfo())
            print(
                json.dumps(
                    {
                        "data": res.data,
                        "version": res.version,
                        "app_version": res.app_version,
                        "last_block_height": res.last_block_height,
                        "last_block_app_hash": res.last_block_app_hash.hex(),
                    }
                )
            )
        elif sub == "deliver_tx":
            res = client.deliver_tx_sync(
                abci_types.RequestDeliverTx(tx=(args.data or "").encode())
            )
            print(json.dumps({"code": res.code, "log": res.log}))
        elif sub == "check_tx":
            res = client.check_tx_sync(
                abci_types.RequestCheckTx(tx=(args.data or "").encode())
            )
            print(json.dumps({"code": res.code, "log": res.log}))
        elif sub == "commit":
            res = client.commit_sync()
            print(json.dumps({"data": res.data.hex()}))
        else:  # "query" — argparse choices guarantee the full set
            res = client.query_sync(
                abci_types.RequestQuery(
                    data=(args.data or "").encode(), path=args.path
                )
            )
            print(
                json.dumps(
                    {
                        "code": res.code,
                        "log": res.log,
                        "value": res.value.decode("utf-8", "replace"),
                    }
                )
            )
    finally:
        client.stop()
    return 0


def cmd_debug(args) -> int:
    """cmd/cometbft/commands/debug/ — `dump` collects a diagnostic bundle
    (config, status + consensus state via RPC, pprof stacks/heap, WAL
    tail) into a tar.gz; `kill` collects the same bundle then SIGABRTs
    the node (debug/kill.go); `inspect` serves a read-only subset of the
    RPC over a crashed node's data dirs (no p2p, no consensus)."""
    sub = args.debug_command
    if sub == "dump":
        return _debug_dump(args)
    if sub == "kill":
        # reference debug/kill.go: collect the bundle FIRST (the node is
        # about to die), then SIGABRT so the runtime dumps stacks to the
        # node's own stderr for the post-mortem
        if args.pid <= 0:
            print("debug kill requires --pid", file=sys.stderr)
            return 1
        rc = _debug_dump(args)
        try:
            os.kill(args.pid, signal.SIGABRT)
        except OSError as exc:
            print(f"failed to signal pid {args.pid}: {exc}", file=sys.stderr)
            return 1
        return rc
    if sub == "inspect":
        return _debug_inspect(args)
    print(f"unknown debug command {sub!r}", file=sys.stderr)
    return 1


def _debug_dump(args) -> int:
    import io
    import tarfile
    import urllib.request

    cfg = _load_config(args.home)
    out_path = args.output or os.path.join(
        args.home, f"debug-bundle-{int(time.time())}.tar.gz"
    )

    def fetch(url: str, body: bytes = None, headers: dict = None) -> bytes:
        """Every collection step degrades to an 'unavailable' entry — a
        half-broken home must still yield a bundle, never a traceback."""
        try:
            req = urllib.request.Request(url, data=body, headers=headers or {})
            return urllib.request.urlopen(req, timeout=5).read()
        except Exception as exc:  # noqa: BLE001 — a dead node is the point
            return f"unavailable: {exc}".encode()

    def read_file(path: str) -> bytes:
        try:
            with open(path, "rb") as f:
                return f.read()
        except OSError as exc:
            return f"unavailable: {exc}".encode()

    rpc_base = "http://" + cfg.rpc.laddr.split("://", 1)[-1]
    entries = {}
    for name, method in (
        ("status.json", "status"),
        ("net_info.json", "net_info"),
        ("consensus_state.json", "dump_consensus_state"),
    ):
        body = json.dumps(
            {"jsonrpc": "2.0", "id": 1, "method": method, "params": {}}
        ).encode()
        entries[name] = fetch(
            rpc_base + "/", body, {"Content-Type": "application/json"}
        )
    if cfg.rpc.pprof_laddr:
        pprof_base = "http://" + cfg.rpc.pprof_laddr.split("://", 1)[-1]
        entries["stacks.txt"] = fetch(pprof_base + "/debug/stacks")
        entries["heap.txt"] = fetch(pprof_base + "/debug/heap")
    toml_path = os.path.join(args.home, "config", "config.toml")
    if os.path.exists(toml_path):
        entries["config.toml"] = read_file(toml_path)
    # the WAL dir comes from [consensus] wal_path — custom paths included.
    # The head file (no numeric suffix) is the NEWEST data and must always
    # be included; numbered chunks sort numerically, newest last.
    wal_path = cfg.consensus.wal_file()
    if os.path.isdir(os.path.dirname(wal_path)):
        from cometbft_tpu.libs.autofile import list_chunk_files

        paths = [p for _, p in list_chunk_files(wal_path)][-2:]
        if os.path.exists(wal_path):
            paths.append(wal_path)  # the head: newest data, always included
        for path in paths:
            entries[f"wal/{os.path.basename(path)}"] = read_file(path)

    with tarfile.open(out_path, "w:gz") as tar:
        for name, data in entries.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
    print(f"Wrote debug bundle {out_path} ({len(entries)} entries)")
    return 0


def _debug_inspect(args) -> int:
    """Read-only RPC over a crashed node's stores — no p2p/consensus
    boots, so it is safe on a wedged home (debug/inspect.go)."""
    from cometbft_tpu.node.node import default_db_provider
    from cometbft_tpu.rpc.serializers import (
        block_id_json,
        block_json,
        block_meta_json,
        header_json,
        validator_json,
    )
    from cometbft_tpu.state.store import Store as StateStore
    from cometbft_tpu.store import BlockStore

    cfg = _load_config(args.home)
    block_store = BlockStore(default_db_provider("blockstore", cfg))
    state_store = StateStore(default_db_provider("state", cfg))

    from cometbft_tpu.libs.net import RouteServer

    _JSON = "application/json"

    def _height_param(q: dict) -> int:
        vals = q.get("height")
        if not vals:
            raise _ClientError("missing required query param 'height'")
        try:
            return int(vals[0])
        except ValueError as exc:
            raise _ClientError(f"invalid height {vals[0]!r}") from exc

    class _ClientError(ValueError):
        pass

    def _route(fn):
        def handler(q: dict):
            try:
                return 200, _JSON, json.dumps(fn(q)).encode()
            except _ClientError as exc:
                return 400, _JSON, json.dumps({"error": str(exc)}).encode()
            except Exception as exc:  # noqa: BLE001 — data errors → 500
                return 500, _JSON, json.dumps({"error": str(exc)}).encode()
        return handler

    def r_status(_q):
        state = state_store.load()
        return {
            "base": block_store.base(),
            "height": block_store.height(),
            "state_height": state.last_block_height if state else None,
            "app_hash": state.app_hash.hex().upper() if state else "",
        }

    def r_block(q):
        h = _height_param(q)
        blk = block_store.load_block(h)
        meta = block_store.load_block_meta(h)
        if blk is None or meta is None:
            raise ValueError(f"no block at height {h}")
        return {
            "block_id": block_id_json(meta.block_id),
            "block": block_json(blk),
        }

    def r_validators(q):
        vals = state_store.load_validators(_height_param(q))
        return {"validators": [validator_json(v) for v in vals.validators]}

    server = RouteServer(
        {
            "/status": _route(r_status),
            "/block": _route(r_block),
            "/validators": _route(r_validators),
        }
    )
    from cometbft_tpu.node.node import _parse_laddr

    host, port = _parse_laddr(args.laddr)
    server.serve(host, port)
    print(
        f"Inspect server on {args.laddr} "
        f"(routes: /status, /block?height=H, /validators?height=H)",
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.is_set():
            time.sleep(0.3)
    finally:
        server.stop()
    return 0


def cmd_replay(args) -> int:
    """commands/replay.go — re-execute the stored chain against the app
    (fresh app state) and report the resulting heights/hashes. Run on a
    STOPPED node; useful after an app-hash mismatch or app upgrade."""
    from cometbft_tpu.consensus.replay import Handshaker
    from cometbft_tpu.node.node import (
        default_client_creator,
        default_db_provider,
    )
    from cometbft_tpu.proxy import new_app_conns
    from cometbft_tpu.state import make_genesis_state
    from cometbft_tpu.state.store import Store as StateStore
    from cometbft_tpu.store import BlockStore

    cfg = _load_config(args.home)
    if args.proxy_app:
        cfg.base.proxy_app = args.proxy_app
    block_store = BlockStore(default_db_provider("blockstore", cfg))
    state_store = StateStore(default_db_provider("state", cfg))
    with open(cfg.base.genesis_path()) as f:
        doc = GenesisDoc.from_json(f.read())
    state = state_store.load()
    if state is None:
        state = make_genesis_state(doc)
        state_store.save(state)

    app_db = None
    if args.fresh_app:
        # replay against a brand-new app instance (the reference replay's
        # whole point: rebuild app state from the chain)
        app_db = default_db_provider("app_replay", cfg)
    else:
        app_db = default_db_provider("app", cfg)
    proxy_app = new_app_conns(
        default_client_creator(
            cfg.base.proxy_app, app_db, transport=cfg.base.abci
        )
    )
    proxy_app.start()
    try:
        replayed_hash = Handshaker(
            state_store, state, block_store, doc
        ).handshake(proxy_app)
        final = state_store.load()
        print(
            f"Replayed chain to height {block_store.height()}; state at "
            f"{final.last_block_height}, replayed app_hash "
            f"{replayed_hash.hex().upper()}"
        )
        # the whole point of --fresh-app: does re-execution reproduce the
        # app hash the chain recorded?
        if replayed_hash != final.app_hash:
            print(
                f"APP HASH MISMATCH: chain recorded "
                f"{final.app_hash.hex().upper()} — the app DIVERGES on "
                f"replay",
                file=sys.stderr,
            )
            return 1
        print("App hash matches the stored state.")
        return 0
    finally:
        proxy_app.stop()


def cmd_light(args) -> int:
    """commands/light.go — run a light client daemon: a verifying RPC
    proxy over an untrusted primary, trust-rooted at --trust-height/
    --trust-hash."""
    from cometbft_tpu.libs.db import SQLiteDB
    from cometbft_tpu.light.client import Client as LightClient, TrustOptions
    from cometbft_tpu.light.provider import HTTPProvider
    from cometbft_tpu.light.proxy import LightProxy
    from cometbft_tpu.light.store import DBStore
    from cometbft_tpu.node.node import _parse_laddr
    from cometbft_tpu.rpc.client import HTTPClient

    chain_id = args.chain_id
    if not chain_id:
        print("--chain-id is required", file=sys.stderr)
        return 1
    try:
        trust_hash = bytes.fromhex(args.trust_hash)
    except ValueError:
        trust_hash = b""
    if len(trust_hash) != 32:
        print(
            "--trust-hash must be the 64-hex-char hash of the trusted "
            "header", file=sys.stderr,
        )
        return 1
    witnesses = [w.strip() for w in args.witnesses.split(",") if w.strip()]
    providers = [HTTPProvider(chain_id, args.primary)] + [
        HTTPProvider(chain_id, w) for w in witnesses
    ]
    if len(providers) < 2:
        # the detector needs at least one witness; fall back to the
        # primary doubling as its own witness only with --insecure
        if not args.insecure_no_witnesses:
            print(
                "at least one --witnesses address is required "
                "(or pass --insecure-no-witnesses)",
                file=sys.stderr,
            )
            return 1
        providers.append(HTTPProvider(chain_id, args.primary))

    # the persisted trust store is the point of a light DAEMON — losing
    # it on restart would silently reopen the trust-on-first-use window
    os.makedirs(os.path.join(args.home, "data"), exist_ok=True)
    store_db = SQLiteDB(os.path.join(args.home, "data", "light.db"))
    lc = LightClient(
        chain_id,
        TrustOptions(
            period_ns=args.trust_period_hours * 3600 * 1_000_000_000,
            height=args.trust_height,
            hash=trust_hash,
        ),
        providers[0],
        providers[1:],
        DBStore(store_db),
    )
    proxy = LightProxy(lc, HTTPClient(args.primary))
    host, port = _parse_laddr(args.laddr)
    proxy.serve(host, port)
    print(
        f"Light client proxy for {chain_id} on {args.laddr} "
        f"(primary {args.primary})",
        flush=True,
    )
    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    try:
        while not stop.is_set():
            time.sleep(0.3)
    finally:
        proxy.stop()
    return 0


def cmd_compact(args) -> int:
    """commands/compact.go — compact the node's databases in place (run
    only on a STOPPED node)."""
    from cometbft_tpu.node.node import default_db_provider

    cfg = _load_config(args.home)
    if cfg.base.db_backend == "memdb":
        print("memdb backend has nothing to compact")
        return 0
    for name in ("blockstore", "state", "evidence", "tx_index",
                 "block_index", "app"):
        path = os.path.join(
            cfg.root_dir, cfg.base.db_dir, f"{name}.db"
        )
        if not os.path.exists(path):
            continue
        before = os.path.getsize(path)
        db = default_db_provider(name, cfg)
        db.compact()
        db.close()
        after = os.path.getsize(path)
        print(f"compacted {name}.db: {before} -> {after} bytes")
    return 0


def cmd_wal(args) -> int:
    """scripts/wal2json + json2wal — inspect/repair consensus WAL files.

    `wal export <wal-file>` prints one JSON object per record (timestamp,
    message kind, decoded height/round where present, and the lossless
    hex body); `wal import <json-file> <wal-file>` re-frames those
    records with fresh CRCs."""
    import struct
    import zlib

    from cometbft_tpu.consensus.messages import decode_wal_message
    from cometbft_tpu.consensus.wal import MAX_MSG_SIZE_BYTES, WALDecodeError
    from cometbft_tpu.libs import protoio
    from cometbft_tpu.proto.gogo import Timestamp

    if args.wal_command == "export":
        from cometbft_tpu.consensus.wal import read_records_lenient
        from cometbft_tpu.libs.autofile import list_chunk_files

        # the WAL rotates (head + .NNN chunks); given the head path,
        # export the WHOLE group oldest-first so operators see exactly
        # the record sequence replay would (chunk naming comes from the
        # shared autofile contract, not a re-derived pattern)
        paths = [p for _, p in list_chunk_files(args.path)] + [args.path]

        out = sys.stdout
        stop = False
        for p in paths:
            if stop:
                continue
            if not os.path.exists(p):
                if p == args.path and not paths[:-1]:
                    # a missing HEAD with no chunks is a wrong path, not
                    # an empty WAL — fail loudly, don't print nothing
                    raise FileNotFoundError(args.path)
                continue
            for ts, raw, warning in read_records_lenient(p):
                if warning is not None:
                    print(
                        f"warning: {warning} in {os.path.basename(p)}, "
                        "stopping",
                        file=sys.stderr,
                    )
                    stop = True
                    break
                rec = {
                    "time": ts.to_rfc3339() if ts else None,
                    "msg": raw.hex(),
                }
                try:
                    msg = decode_wal_message(raw)
                    rec["type"] = type(msg).__name__
                    for attr in ("height", "round"):
                        if hasattr(msg, attr):
                            rec[attr] = getattr(msg, attr)
                except (WALDecodeError, ValueError) as exc:
                    rec["type"] = f"undecodable: {exc}"
                out.write(json.dumps(rec) + "\n")
        return 0

    if args.wal_command == "import":
        with open(args.path) as f, open(args.out, "wb") as w:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                ts = (
                    Timestamp.from_rfc3339(rec["time"])
                    if rec.get("time")
                    else Timestamp.now()
                )
                msg_bytes = bytes.fromhex(rec["msg"])
                # validate before writing — a bad record must not produce
                # a WAL that crashes replay
                decode_wal_message(msg_bytes)
                body = protoio.field_message(1, ts.encode())
                body += protoio.field_message(2, msg_bytes)
                if len(body) > MAX_MSG_SIZE_BYTES:
                    raise ValueError(
                        f"record of {len(body)} bytes exceeds the WAL max "
                        f"({MAX_MSG_SIZE_BYTES}); replay would reject it"
                    )
                crc = zlib.crc32(body) & 0xFFFFFFFF
                w.write(struct.pack(">II", crc, len(body)) + body)
        print(f"Wrote {args.out}")
        return 0

    print(f"unknown wal command {args.wal_command!r}", file=sys.stderr)
    return 1


def cmd_gen_node_key(args) -> int:
    """commands/gen_node_key.go — create (or show) the node p2p key."""
    cfg = _load_config(args.home)
    path = cfg.base.node_key_path()
    existed = os.path.exists(path)
    nk = NodeKey.load_or_gen(path)
    print(nk.id() if existed else f"{nk.id()} (generated {path})")
    return 0


def _node_dbs(cfg):
    from cometbft_tpu.node.node import default_db_provider
    from cometbft_tpu.state.store import Store as StateStore
    from cometbft_tpu.store import BlockStore

    block_store = BlockStore(default_db_provider("blockstore", cfg))
    state_store = StateStore(default_db_provider("state", cfg))
    return block_store, state_store


def cmd_rollback(args) -> int:
    """commands/rollback.go — undo the latest state height (app state is
    untouched; roll the app back one height too)."""
    from cometbft_tpu.state.rollback import rollback

    cfg = _load_config(args.home)
    block_store, state_store = _node_dbs(cfg)
    try:
        height, app_hash = rollback(block_store, state_store)
    except Exception as exc:
        print(f"rollback failed: {exc}", file=sys.stderr)
        return 1
    print(
        f"Rolled back state to height {height} and hash "
        f"{app_hash.hex().upper()}"
    )
    return 0


def cmd_reset_state(args) -> int:
    """commands/reset.go ResetState — wipe the data dir (blocks, state,
    evidence, indexes, WAL) but keep the validator key + address book."""
    data_dir = os.path.join(args.home, "data")
    if os.path.isdir(data_dir):
        import shutil

        shutil.rmtree(data_dir)
    os.makedirs(data_dir, exist_ok=True)
    # the priv validator STATE must be reset too or the signer refuses to
    # sign lower heights on the new chain (reset.go:76-86)
    from cometbft_tpu.privval.file import FilePVLastSignState

    cfg = _load_config(args.home)
    state_path = cfg.base.priv_validator_state_path()
    fresh = FilePVLastSignState(file_path=state_path)
    fresh.save()
    print(f"Removed all data in {data_dir}")
    return 0


def cmd_unsafe_reset_all(args) -> int:
    """commands/reset.go UnsafeResetAll — reset-state + fresh addrbook."""
    rc = cmd_reset_state(args)
    cfg = _load_config(args.home)
    addr_book = os.path.join(args.home, cfg.p2p.addr_book_file)
    if os.path.exists(addr_book):
        os.remove(addr_book)
        print(f"Removed {addr_book}")
    return rc


def cmd_reindex_event(args) -> int:
    """commands/reindex_event.go — rebuild tx + block indexes from the
    block store and saved ABCI responses."""
    from cometbft_tpu.abci import types as abci
    from cometbft_tpu.node.node import default_db_provider
    from cometbft_tpu.state.indexer import KVBlockIndexer, KVTxIndexer
    from cometbft_tpu.types.event_bus import merge_block_events

    cfg = _load_config(args.home)
    block_store, state_store = _node_dbs(cfg)
    tx_indexer = KVTxIndexer(default_db_provider("tx_index", cfg))
    block_indexer = KVBlockIndexer(default_db_provider("block_index", cfg))

    base = max(block_store.base(), 1)
    height = block_store.height()
    start = args.start_height or base
    end = args.end_height or height
    if start < base or end > height or start > end:
        print(
            f"invalid range [{start}, {end}]; chain has [{base}, {height}]",
            file=sys.stderr,
        )
        return 1
    n = 0
    for h in range(start, end + 1):
        block = block_store.load_block(h)
        if block is None:
            continue
        try:
            responses = state_store.load_abci_responses(h)
        except Exception as exc:
            print(f"no ABCI responses for height {h}: {exc}", file=sys.stderr)
            return 1
        events = merge_block_events(
            getattr(responses.begin_block, "events", None),
            getattr(responses.end_block, "events", None),
        )
        block_indexer.index(events, h)
        batch = [
            abci.TxResult(height=h, index=i, tx=tx, result=responses.deliver_txs[i])
            for i, tx in enumerate(block.data.txs)
            if i < len(responses.deliver_txs)
        ]
        tx_indexer.add_batch(batch)
        n += 1
    print(f"Reindexed events for {n} blocks ([{start}, {end}])")
    return 0


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="cometbft_tpu",
        description="TPU-native BFT state-machine replication node",
    )
    parser.add_argument(
        "--home",
        default=os.path.expanduser("~/.cometbft_tpu"),
        help="node home directory",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init", help="initialize a node home directory")
    p.add_argument("--chain-id", default="")
    p.set_defaults(fn=cmd_init)

    p = sub.add_parser("start", help="run the node")
    p.add_argument("--proxy_app", default="")
    p.add_argument("--p2p.laddr", dest="p2p_laddr", default="")
    p.add_argument("--rpc.laddr", dest="rpc_laddr", default="")
    p.add_argument(
        "--p2p.persistent_peers", dest="persistent_peers", default=""
    )
    p.add_argument("--no-fast-sync", action="store_true")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("show-node-id", help="print this node's p2p ID")
    p.set_defaults(fn=cmd_show_node_id)

    p = sub.add_parser("show-validator", help="print this node's pubkey")
    p.set_defaults(fn=cmd_show_validator)

    p = sub.add_parser("gen-validator", help="generate a validator keypair")
    p.set_defaults(fn=cmd_gen_validator)

    p = sub.add_parser("testnet", help="initialize a local multi-node testnet")
    p.add_argument("--v", type=int, default=4, help="number of validators")
    p.add_argument("--output-dir", default="./mytestnet")
    p.add_argument("--chain-id", default="")
    p.add_argument("--proxy_app", default="kvstore")
    p.add_argument("--p2p-port", type=int, default=26656)
    p.add_argument("--rpc-port", type=int, default=26657)
    p.add_argument(
        "--hostname-template", default="",
        help="peer hostname pattern like 'node{}' — containers/VMs mode: "
        "all nodes bind 0.0.0.0 on the same ports",
    )
    p.set_defaults(fn=cmd_testnet)

    p = sub.add_parser(
        "abci", help="ABCI console: poke an app socket or serve kvstore"
    )
    p.add_argument(
        "abci_command",
        choices=["echo", "info", "deliver_tx", "check_tx", "commit",
                 "query", "kvstore"],
    )
    p.add_argument("data", nargs="?", default="")
    p.add_argument("--address", default="tcp://127.0.0.1:26658")
    p.add_argument("--path", default="/store")
    p.set_defaults(fn=cmd_abci)

    p = sub.add_parser("rollback", help="roll the state back one height")
    p.set_defaults(fn=cmd_rollback)

    p = sub.add_parser(
        "reset-state", help="remove all data, keep keys and address book"
    )
    p.set_defaults(fn=cmd_reset_state)

    p = sub.add_parser(
        "unsafe-reset-all",
        help="remove all data and the address book (keeps the validator key)",
    )
    p.set_defaults(fn=cmd_unsafe_reset_all)

    p = sub.add_parser(
        "debug",
        help="diagnostic bundle (dump) / crashed-home RPC (inspect) / "
        "bundle-then-SIGABRT a live node (kill)",
    )
    p.add_argument("debug_command", choices=["dump", "inspect", "kill"])
    p.add_argument(
        "--pid", type=int, default=0, help="node process id (kill)"
    )
    p.add_argument("--output", default="", help="bundle path (dump/kill)")
    p.add_argument(
        "--laddr", default="tcp://127.0.0.1:26669", help="inspect listen addr"
    )
    p.set_defaults(fn=cmd_debug)

    p = sub.add_parser(
        "replay", help="re-execute the stored chain against the app"
    )
    p.add_argument(
        "--fresh-app", action="store_true",
        help="replay into a brand-new app DB (app_replay.db)",
    )
    p.add_argument("--proxy_app", default="", help="override [base] proxy_app")
    p.set_defaults(fn=cmd_replay)

    p = sub.add_parser(
        "light", help="light client daemon: verifying RPC proxy"
    )
    p.add_argument("--chain-id", default="")
    p.add_argument("--primary", default="127.0.0.1:26657")
    p.add_argument("--witnesses", default="",
                   help="comma-separated witness RPC addresses")
    p.add_argument("--trust-height", type=int, default=1)
    p.add_argument("--trust-hash", default="")
    p.add_argument("--trust-period-hours", type=int, default=168)
    p.add_argument("--laddr", default="tcp://127.0.0.1:26648")
    p.add_argument("--insecure-no-witnesses", action="store_true")
    p.set_defaults(fn=cmd_light)

    p = sub.add_parser(
        "compact", help="compact the databases of a stopped node"
    )
    p.set_defaults(fn=cmd_compact)

    p = sub.add_parser("wal", help="export/import consensus WAL files as JSON")
    p.add_argument("wal_command", choices=["export", "import"])
    p.add_argument("path", help="WAL file (export) or JSON file (import)")
    p.add_argument("out", nargs="?", default="wal.out",
                   help="output WAL file (import)")
    p.set_defaults(fn=cmd_wal)

    p = sub.add_parser("gen-node-key", help="generate or show the node key")
    p.set_defaults(fn=cmd_gen_node_key)

    p = sub.add_parser(
        "reindex-event", help="rebuild tx/block indexes from stored blocks"
    )
    p.add_argument("--start-height", type=int, default=0)
    p.add_argument("--end-height", type=int, default=0)
    p.set_defaults(fn=cmd_reindex_event)

    p = sub.add_parser("version", help="print the version")
    p.set_defaults(fn=cmd_version)

    p = sub.add_parser(
        "probe-upnp", help="probe the local NAT for UPnP port-mapping"
    )
    p.add_argument("--port", type=int, default=8001)
    p.set_defaults(fn=cmd_probe_upnp)

    p = sub.add_parser(
        "loadtime", help="generate tx load and report commit latency"
    )
    p.add_argument(
        "--endpoints", required=True,
        help="comma-separated node RPC host:port list",
    )
    p.add_argument("--rate", type=float, default=10.0, help="total tx/s")
    p.add_argument("--connections", type=int, default=1)
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--size", type=int, default=64, help="tx bytes")
    p.set_defaults(fn=cmd_loadtime)

    args = parser.parse_args(argv)
    return args.fn(args)
