"""Peer-behaviour reporting.

Reference: behaviour/{peer_behaviour,reporter}.go — a small vocabulary of
judgements reactors can report about peers, routed either to the Switch
(good → address-book mark-good, bad → StopPeerForError) or recorded by a
MockReporter in tests.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List


@dataclass(frozen=True)
class PeerBehaviour:
    peer_id: str
    reason: str  # one of the constructors below
    explanation: str = ""


def consensus_vote(peer_id: str, explanation: str = "") -> PeerBehaviour:
    return PeerBehaviour(peer_id, "consensus_vote", explanation)


def block_part(peer_id: str, explanation: str = "") -> PeerBehaviour:
    return PeerBehaviour(peer_id, "block_part", explanation)


def bad_message(peer_id: str, explanation: str) -> PeerBehaviour:
    return PeerBehaviour(peer_id, "bad_message", explanation)


def message_out_of_order(peer_id: str, explanation: str) -> PeerBehaviour:
    return PeerBehaviour(peer_id, "message_out_of_order", explanation)


_GOOD = ("consensus_vote", "block_part")
_BAD = ("bad_message", "message_out_of_order")


class SwitchReporter:
    """Routes behaviour reports to a p2p Switch (reporter.go:29-47)."""

    def __init__(self, switch):
        self._switch = switch

    def report(self, behaviour: PeerBehaviour) -> None:
        peer = self._switch.peers.get(behaviour.peer_id)
        if peer is None:
            raise ValueError("peer not found")
        if behaviour.reason in _GOOD:
            self._switch.mark_peer_as_good(peer)
        elif behaviour.reason in _BAD:
            self._switch.stop_peer_for_error(peer, behaviour.explanation)
        else:
            raise ValueError(f"unknown reason {behaviour.reason!r}")


class MockReporter:
    """Records reports for assertion in reactor tests (reporter.go:50)."""

    def __init__(self):
        self._mtx = threading.Lock()
        self._by_peer: Dict[str, List[PeerBehaviour]] = {}

    def report(self, behaviour: PeerBehaviour) -> None:
        with self._mtx:
            self._by_peer.setdefault(behaviour.peer_id, []).append(behaviour)

    def get_behaviours(self, peer_id: str) -> List[PeerBehaviour]:
        with self._mtx:
            return list(self._by_peer.get(peer_id, ()))
