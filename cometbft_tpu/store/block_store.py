"""BlockStore — parts-encoded persistent block storage.

Reference: store/store.go — key layout :434-450 (H: meta, P: part,
C: commit, SC: seen commit, BH: by-hash index), SaveBlock :332,
PruneBlocks :248, base/height state under "blockStore".
"""

from __future__ import annotations

import threading
from typing import List, Optional

from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.block import Block, BlockMeta, Commit
from cometbft_tpu.types.part_set import Part, PartSet

_STORE_KEY = b"blockStore"


def _meta_key(height: int) -> bytes:
    return b"H:%d" % height


def _part_key(height: int, index: int) -> bytes:
    return b"P:%d:%d" % (height, index)


def _commit_key(height: int) -> bytes:
    return b"C:%d" % height


def _seen_commit_key(height: int) -> bytes:
    return b"SC:%d" % height


def _hash_key(hash_: bytes) -> bytes:
    return b"BH:" + hash_.hex().encode()


def _encode_store_state(base: int, height: int) -> bytes:
    """proto store.BlockStoreState {int64 base=1, int64 height=2}."""
    out = b""
    if base:
        out += protoio.field_varint(1, base)
    if height:
        out += protoio.field_varint(2, height)
    return out


def _decode_store_state(data: bytes):
    r = protoio.WireReader(data)
    base = height = 0
    while not r.at_end():
        f, wt = r.read_tag()
        if f == 1:
            base = r.read_varint()
        elif f == 2:
            height = r.read_varint()
        else:
            r.skip(wt)
    return base, height


class BlockStore:
    """Thread-safe; heights are contiguous [base, height]."""

    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.RLock()
        raw = db.get(_STORE_KEY)
        if raw:
            self._base, self._height = _decode_store_state(raw)
        else:
            self._base, self._height = 0, 0

    def base(self) -> int:
        with self._mtx:
            return self._base

    def height(self) -> int:
        with self._mtx:
            return self._height

    def size(self) -> int:
        with self._mtx:
            return 0 if self._height == 0 else self._height - self._base + 1

    # -- loads --------------------------------------------------------------

    def load_block_meta(self, height: int) -> Optional[BlockMeta]:
        raw = self._db.get(_meta_key(height))
        return BlockMeta.decode(raw) if raw else None

    def load_block(self, height: int) -> Optional[Block]:
        meta = self.load_block_meta(height)
        if meta is None:
            return None
        parts = []
        for i in range(meta.block_id.part_set_header.total):
            part = self.load_block_part(height, i)
            if part is None:
                return None
            parts.append(part.bytes_)
        return Block.decode(b"".join(parts))

    def load_block_by_hash(self, hash_: bytes) -> Optional[Block]:
        raw = self._db.get(_hash_key(hash_))
        if not raw:
            return None
        return self.load_block(int(raw))

    def load_block_part(self, height: int, index: int) -> Optional[Part]:
        raw = self._db.get(_part_key(height, index))
        return Part.decode(raw) if raw else None

    def load_block_commit(self, height: int) -> Optional[Commit]:
        """The canonical commit for `height` (stored at height+1 save)."""
        raw = self._db.get(_commit_key(height))
        return Commit.decode(raw) if raw else None

    def load_seen_commit(self, height: int) -> Optional[Commit]:
        raw = self._db.get(_seen_commit_key(height))
        return Commit.decode(raw) if raw else None

    # -- saves --------------------------------------------------------------

    def save_block(
        self, block: Block, block_parts: PartSet, seen_commit: Commit
    ) -> None:
        """Reference: store/store.go:332 — meta + every part + LastCommit at
        H-1 + seen commit at H, then advance the store state."""
        if block is None:
            raise ValueError("BlockStore can only save a non-nil block")
        with self._mtx:
            height = block.header.height
            expected = self._height + 1
            if self._height > 0 and height != expected:
                raise ValueError(
                    f"BlockStore can only save contiguous blocks; wanted "
                    f"{expected}, got {height}"
                )
            if not block_parts.is_complete():
                raise ValueError("can only save complete block part sets")

            batch = self._db.new_batch()
            from cometbft_tpu.types.block import BlockID

            block_id = BlockID(block.hash(), block_parts.header())
            meta = BlockMeta(
                block_id=block_id,
                block_size=block.size(),
                header=block.header,
                num_txs=len(block.data.txs),
            )
            batch.set(_meta_key(height), meta.encode())
            batch.set(_hash_key(block.hash()), b"%d" % height)
            for i in range(block_parts.total()):
                batch.set(_part_key(height, i), block_parts.get_part(i).encode())
            if block.last_commit is not None:
                batch.set(_commit_key(height - 1), block.last_commit.encode())
            batch.set(_seen_commit_key(height), seen_commit.encode())

            self._height = height
            if self._base == 0:
                self._base = height
            batch.set(_STORE_KEY, _encode_store_state(self._base, self._height))
            batch.write_sync()

    def save_seen_commit(self, height: int, commit: Commit) -> None:
        self._db.set(_seen_commit_key(height), commit.encode())

    def prune_blocks(self, retain_height: int) -> int:
        """Remove blocks below retain_height; returns count pruned
        (reference: store/store.go:248)."""
        with self._mtx:
            if retain_height <= 0:
                raise ValueError("height must be greater than 0")
            if retain_height > self._height:
                raise ValueError(
                    f"cannot prune beyond the latest height {self._height}"
                )
            if retain_height < self._base:
                return 0
            pruned = 0
            batch = self._db.new_batch()
            for h in range(self._base, retain_height):
                meta = self.load_block_meta(h)
                if meta is None:
                    continue
                batch.delete(_meta_key(h))
                batch.delete(_hash_key(meta.block_id.hash))
                for i in range(meta.block_id.part_set_header.total):
                    batch.delete(_part_key(h, i))
                batch.delete(_commit_key(h))
                batch.delete(_seen_commit_key(h))
                pruned += 1
            self._base = retain_height
            batch.set(_STORE_KEY, _encode_store_state(self._base, self._height))
            batch.write_sync()
            return pruned

    def load_base_meta(self) -> Optional[BlockMeta]:
        with self._mtx:
            if self._base == 0:
                return None
            return self.load_block_meta(self._base)
