"""store — the block store."""

from cometbft_tpu.store.block_store import BlockStore  # noqa: F401
