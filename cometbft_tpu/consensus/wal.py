"""Consensus write-ahead log.

Reference: consensus/wal.go — WAL interface :58-69, BaseWAL over a
rotating autofile.Group, CRC32C+length-framed TimedWALMessage records
(WALEncoder :130), 2-second periodic fsync (:28), WriteSync before own
messages are sent (consensus/state.go:771), SearchForEndHeight :63 used
by crash recovery.

Record framing: crc32(4 bytes BE) ‖ length(4 bytes BE) ‖ proto(TimedWALMessage).
"""

from __future__ import annotations

import io
import struct
import threading
import time
import zlib
from typing import Iterator, Optional, Tuple

from cometbft_tpu.consensus.messages import (
    EndHeightMessage,
    decode_wal_message,
    encode_wal_message,
)
from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.autofile import Group
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.proto.gogo import Timestamp

MAX_MSG_SIZE_BYTES = 1024 * 1024  # 1MB (wal.go:32)
_FLUSH_INTERVAL_S = 2.0  # walDefaultFlushInterval (wal.go:28)


def _encode_timed(msg, ts: Optional[Timestamp] = None) -> bytes:
    ts = ts or Timestamp.now()
    body = protoio.field_message(1, ts.encode()) + protoio.field_message(
        2, encode_wal_message(msg)
    )
    crc = zlib.crc32(body) & 0xFFFFFFFF
    return struct.pack(">II", crc, len(body)) + body


class WALDecodeError(ValueError):
    """Data corruption — caller may repair by truncating (reference:
    DataCorruptionError)."""


def _next_frame(read):
    """THE framing rule, shared by replay, the lenient tool reader, and
    repair — three readers that must never disagree on what a valid
    record is. → (body, None) on success, (None, None) at clean EOF,
    (None, reason) on a framing violation."""
    head = read(8)
    if not head:
        return None, None
    if len(head) < 8:
        return None, "truncated record header"
    crc, length = struct.unpack(">II", head)
    if length > MAX_MSG_SIZE_BYTES:
        return None, f"record length {length} exceeds max"
    body = read(length)
    if len(body) < length:
        return None, "truncated record body"
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
        return None, "CRC mismatch"
    return body, None


def _split_body(body: bytes):
    """TimedWALMessage {Timestamp time=1, WALMessage msg=2} → the raw
    field bytes (ts_bytes may be None; raw_msg None = missing field)."""
    reader = protoio.WireReader(body)
    ts_bytes, raw = None, None
    while not reader.at_end():
        fld, wt = reader.read_tag()
        if fld == 1:
            ts_bytes = reader.read_bytes()
        elif fld == 2:
            raw = reader.read_bytes()
        else:
            reader.skip(wt)
    return ts_bytes, raw


def _decode_record(r) -> Optional[object]:
    """Read one framed record from a binary reader; None at clean EOF."""
    body, err = _next_frame(r.read)
    if body is None:
        if err is None:
            return None
        raise WALDecodeError(err)
    _, raw = _split_body(body)
    if raw is None:
        raise WALDecodeError("record without WALMessage")
    return decode_wal_message(raw)


def read_records_lenient(path: str):
    """Yield (timestamp, raw_wal_message_bytes, warning) from a WAL file,
    degrading at the first corruption instead of raising — the shared
    reader under `wal export` so tool and replay can never disagree on
    framing. `warning` is set (and iteration ends) on a bad record."""
    with open(path, "rb") as f:
        while True:
            body, err = _next_frame(f.read)
            if body is None:
                if err is not None:
                    yield None, None, err
                return
            ts_bytes, raw = _split_body(body)
            ts = Timestamp.decode(ts_bytes) if ts_bytes is not None else None
            yield ts, raw if raw is not None else b"", None


class WAL(BaseService):
    """BaseWAL: group-backed, periodically flushed."""

    def __init__(self, wal_file: str, group_head_size: int = 10 * 1024 * 1024):
        super().__init__("baseWAL")
        self._group = Group(wal_file, head_size_limit=group_head_size)
        self._mtx = threading.Lock()
        self._flush_thread: Optional[threading.Thread] = None

    def on_start(self) -> None:
        # write an EndHeight(0) sentinel on a fresh WAL so replay finds a
        # terminator even before the first height completes (wal.go OnStart)
        size = self._group_total_size()
        if size == 0:
            self.write_sync(EndHeightMessage(0))
        self._flush_thread = threading.Thread(
            target=self._flush_loop, daemon=True
        )
        self._flush_thread.start()

    def on_stop(self) -> None:
        with self._mtx:
            self._group.flush_and_sync()
            self._group.close()

    def _group_total_size(self) -> int:
        import os

        total = 0
        for p in self._group.all_paths():
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def _flush_loop(self) -> None:
        ticks = 0
        while self.is_running():
            time.sleep(_FLUSH_INTERVAL_S)
            if not self.is_running():
                return
            try:
                with self._mtx:
                    self._group.flush_and_sync()
                ticks += 1
                if ticks % 5 == 0:
                    # ~10 s: rotate an oversized head + enforce the
                    # total-size bound (reference: the autofile group's
                    # own processTicks, group.go — without this the head
                    # file grows unboundedly on a long-running node)
                    with self._mtx:
                        self._group.check_head_size_limit()
            except (OSError, ValueError) as exc:
                if not self.is_running():
                    return  # shutdown race: head closed under us
                # a transient fs error must not kill flushing, but a
                # node whose WAL is not landing must be VISIBLE
                # (reference: "Periodic WAL flush failed" log)
                self.logger.error(
                    "periodic WAL flush failed", err=str(exc)
                )
                continue

    def write(self, msg) -> None:
        """Log before processing (reference: Write — no fsync)."""
        if not self.is_running():
            return
        with self._mtx:
            self._group.write(_encode_timed(msg))

    def write_sync(self, msg) -> None:
        """Log + fsync — used for our own votes/proposals and #ENDHEIGHT
        (reference: WriteSync)."""
        if not self.is_running() and self._flush_thread is not None:
            return
        with self._mtx:
            self._group.write(_encode_timed(msg))
            self._group.flush_and_sync()

    def flush_and_sync(self) -> None:
        with self._mtx:
            self._group.flush_and_sync()

    def group(self) -> Group:
        return self._group

    # -- replay -------------------------------------------------------------

    def iter_messages(self) -> Iterator[object]:
        """All decodable messages, oldest first. Raises WALDecodeError on
        corruption (caller decides whether to repair)."""
        with self._mtx:
            self._group.flush_and_sync()
        with self._group.reader() as r:
            while True:
                msg = _decode_record(r)
                if msg is None:
                    return
                yield msg

    def search_for_end_height(
        self, height: int
    ) -> Tuple[Optional[list], bool]:
        """Returns (messages_after_marker, found). Reference:
        WALSearchForEndHeight — position the reader just after
        EndHeight(height)."""
        found = False
        tail: list = []
        try:
            for msg in self.iter_messages():
                if isinstance(msg, EndHeightMessage) and msg.height == height:
                    found = True
                    tail = []
                    continue
                if found:
                    tail.append(msg)
        except WALDecodeError:
            if not found:
                raise
        return (tail, True) if found else (None, False)


class NilWAL:
    """Reference: nilWAL — used when the WAL is disabled."""

    def write(self, msg) -> None:
        pass

    def write_sync(self, msg) -> None:
        pass

    def flush_and_sync(self) -> None:
        pass

    def search_for_end_height(self, height: int):
        return None, False

    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    def is_running(self) -> bool:
        return True


def _scan_valid_prefix(path: str):
    """→ (end offset of the last fully-valid record, clean). clean is
    False when corruption/truncation follows the prefix. Validity =
    the shared framing rule (_next_frame) plus EXACTLY the decode
    replay applies (_split_body field 2 → decode_wal_message — the
    timestamp field is not decoded, matching _decode_record): repair
    must never truncate a record replay would have accepted."""
    good = 0
    with open(path, "rb") as f:
        while True:
            body, err = _next_frame(f.read)
            if body is None:
                return good, err is None
            try:
                _, raw = _split_body(body)
                if raw is None:
                    return good, False
                decode_wal_message(raw)
            except Exception:  # noqa: BLE001 - any decode failure ends it
                return good, False
            good += 8 + len(body)


def repair_wal_tail(wal: "WAL") -> bool:
    """Drop everything after the last valid record (reference:
    repairWalFile, consensus/state.go:2359 — copy-the-valid-prefix on a
    single file; the group form truncates the corrupt file and removes
    every later file, since their records postdate the corruption).
    → True when something was repaired."""
    group = wal.group()
    with wal._mtx:
        group.flush_and_sync()
        paths = group.all_paths()
        for i, p in enumerate(paths):
            good, clean = _scan_valid_prefix(p)
            if clean:
                continue
            group.truncate_tail(p, good, drop_after=paths[i + 1 :])
            return True
    return False
