"""Consensus metrics.

Reference: consensus/metrics.go:22-95 — the full gauge/histogram set the
reference exports under the `cometbft_consensus_*` namespace, fed from
finalizeCommit/updateToState (record_metrics) and the step machine.
"""

from __future__ import annotations

import time
from typing import Optional

from cometbft_tpu.libs.metrics import Registry

SUBSYSTEM = "consensus"


class Metrics:
    def __init__(self, registry: Optional[Registry] = None):
        r = registry if registry is not None else Registry()
        self.height = r.gauge(SUBSYSTEM, "height", "Height of the chain.")
        self.validator_last_signed_height = r.gauge(
            SUBSYSTEM, "validator_last_signed_height",
            "Last height the local validator signed.",
        )
        self.rounds = r.gauge(SUBSYSTEM, "rounds", "Number of rounds.")
        self.validators = r.gauge(
            SUBSYSTEM, "validators", "Number of validators."
        )
        self.validators_power = r.gauge(
            SUBSYSTEM, "validators_power", "Total power of all validators."
        )
        self.missing_validators = r.gauge(
            SUBSYSTEM, "missing_validators",
            "Number of validators who did not sign.",
        )
        self.missing_validators_power = r.gauge(
            SUBSYSTEM, "missing_validators_power",
            "Total power of the missing validators.",
        )
        self.byzantine_validators = r.gauge(
            SUBSYSTEM, "byzantine_validators",
            "Number of validators who tried to double sign.",
        )
        self.byzantine_validators_power = r.gauge(
            SUBSYSTEM, "byzantine_validators_power",
            "Total power of the byzantine validators.",
        )
        self.block_interval_seconds = r.histogram(
            SUBSYSTEM, "block_interval_seconds",
            "Time between this and the last block.",
            buckets=(0.5, 1, 2.5, 5, 10, 30, 60),
        )
        self.num_txs = r.gauge(SUBSYSTEM, "num_txs", "Number of transactions.")
        self.block_size_bytes = r.gauge(
            SUBSYSTEM, "block_size_bytes", "Size of the block."
        )
        self.total_txs = r.gauge(
            SUBSYSTEM, "total_txs", "Total number of transactions."
        )
        self.committed_height = r.gauge(
            SUBSYSTEM, "latest_block_height", "The latest block height."
        )
        self.fast_syncing = r.gauge(
            SUBSYSTEM, "fast_syncing", "Whether the node is fast syncing."
        )
        self.state_syncing = r.gauge(
            SUBSYSTEM, "state_syncing", "Whether the node is state syncing."
        )
        self.block_parts = r.counter(
            SUBSYSTEM, "block_parts",
            "Number of block parts transmitted by peer.",
        )
        self.step_duration = r.histogram(
            SUBSYSTEM, "step_duration_seconds",
            "Histogram of step duration.",
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10),
        )
        self.block_gossip_parts_received = r.counter(
            SUBSYSTEM, "block_gossip_parts_received",
            "Block parts received, by relevance to the gathering block.",
        )
        self.preverify_dropped = r.counter(
            SUBSYSTEM, "preverify_dropped",
            "Drained votes excluded from batch preverification, by "
            "reason (negative_index|empty_signature).",
        )
        self.quorum_prevote_delay = r.gauge(
            SUBSYSTEM, "quorum_prevote_delay",
            "Seconds from proposal timestamp to the prevote that completed "
            "+2/3.",
        )
        self.full_prevote_delay = r.gauge(
            SUBSYSTEM, "full_prevote_delay",
            "Seconds from proposal timestamp to the last prevote in a "
            "fully-prevoted round.",
        )
        self._step_start = time.monotonic()

    @classmethod
    def nop(cls) -> "Metrics":
        return cls(None)

    # step-duration helper (metrics.go MarkStep)
    def mark_step(self, step_name: str) -> None:
        now = time.monotonic()
        self.step_duration.with_labels(step=step_name).observe(
            now - self._step_start
        )
        self._step_start = now
