"""Timeout ticker — schedules at most one outstanding consensus timeout.

Reference: consensus/ticker.go — timeoutTicker keeps a single timer keyed
by (height, round, step); scheduling a newer timeout replaces the old one,
and stale fires are filtered by the state machine's handleTimeout checks.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional

from cometbft_tpu.consensus.messages import TimeoutInfo
from cometbft_tpu.libs.service import BaseService


class TimeoutTicker(BaseService):
    def __init__(self):
        super().__init__("TimeoutTicker")
        self._timer: Optional[threading.Timer] = None
        self._mtx = threading.Lock()
        self.tock_chan: "queue.Queue[TimeoutInfo]" = queue.Queue(maxsize=100)

    def on_stop(self) -> None:
        with self._mtx:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    def schedule_timeout(self, ti: TimeoutInfo) -> None:
        """Replaces any pending timeout (the reference relies on newer
        (H,R,S) always superseding older)."""
        with self._mtx:
            if self._timer is not None:
                self._timer.cancel()
            self._timer = threading.Timer(
                max(ti.duration_s, 0.0), self._fire, args=(ti,)
            )
            self._timer.daemon = True
            self._timer.start()

    def _fire(self, ti: TimeoutInfo) -> None:
        try:
            self.tock_chan.put(ti, timeout=1)
        except queue.Full:
            pass
