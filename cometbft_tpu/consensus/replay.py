"""Crash recovery: WAL catch-up replay + the ABCI handshake.

Reference: consensus/replay.go — catchupReplay :93 (re-apply WAL messages
recorded after the last #ENDHEIGHT), Handshaker.Handshake :241 (ABCI Info
→ compare app height vs store height), ReplayBlocks :284 (InitChain at
genesis; re-execute stored blocks until the app catches up, ApplyBlock for
the final one when the state snapshot is also behind).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from cometbft_tpu.abci import types as abci
from cometbft_tpu.consensus.messages import (
    EndHeightMessage,
    EventDataRoundStateWAL,
    MsgInfo,
    TimeoutInfo,
)
from cometbft_tpu.libs.log import Logger, new_nop_logger
from cometbft_tpu.proto.keys import pub_key_to_proto
from cometbft_tpu.state import State as SMState
from cometbft_tpu.state.execution import (
    exec_block_on_proxy_app,
    validator_from_update,
)
from cometbft_tpu.state.store import Store
from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.genesis import GenesisDoc
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.version import BLOCK_PROTOCOL, P2P_PROTOCOL


def catchup_replay(cs, cs_height: int) -> None:
    """Replay WAL messages recorded after the last completed height into a
    freshly-constructed ConsensusState (reference: catchupReplay :93).
    Must run before the receive routine starts; messages are applied
    directly (they are already in the WAL — no re-logging)."""
    # sanity: nothing for cs_height must have completed already
    tail, found = cs.wal.search_for_end_height(cs_height)
    if found:
        raise RuntimeError(
            f"WAL should not contain #ENDHEIGHT {cs_height}"
        )
    tail, found = cs.wal.search_for_end_height(cs_height - 1)
    if not found:
        # a fresh WAL carries the EndHeight(0) sentinel; missing marker for
        # an older height means the WAL was truncated/pruned
        if cs_height > 1:
            raise RuntimeError(
                f"cannot replay height {cs_height}: WAL has no #ENDHEIGHT "
                f"{cs_height - 1}"
            )
        cs._wal_catchup_done = True
        return
    for msg in tail or []:
        _replay_one(cs, msg)
    cs._wal_catchup_done = True  # on_start must not replay a second time
    cs.logger.info("replay: done", height=cs_height, messages=len(tail or []))


def _replay_one(cs, msg) -> None:
    if isinstance(msg, EventDataRoundStateWAL):
        return  # informational
    if isinstance(msg, TimeoutInfo):
        with cs._mtx:
            cs._handle_timeout(msg)
        return
    if isinstance(msg, MsgInfo):
        with cs._mtx:
            cs._handle_msg(msg)
        return
    if isinstance(msg, EndHeightMessage):
        return
    raise TypeError(f"unknown WAL message {type(msg)!r}")


class _MockReqRes:
    def __init__(self, response: abci.Response):
        self._response = response

    def wait(self, timeout=None) -> abci.Response:
        return self._response


class _MockProxyAppConn:
    """Replays recorded ABCIResponses (reference: newMockProxyApp
    consensus/replay.go — used when only the state snapshot is behind)."""

    def __init__(self, responses, app_hash: bytes):
        self._responses = responses
        self._app_hash = app_hash
        self._tx_index = 0

    def begin_block_sync(self, req) -> abci.ResponseBeginBlock:
        return self._responses.begin_block or abci.ResponseBeginBlock()

    def deliver_tx_async(self, req) -> _MockReqRes:
        res = self._responses.deliver_txs[self._tx_index]
        self._tx_index += 1
        return _MockReqRes(abci.Response("deliver_tx", res))

    def end_block_sync(self, req) -> abci.ResponseEndBlock:
        return self._responses.end_block or abci.ResponseEndBlock()

    def commit_sync(self) -> abci.ResponseCommit:
        return abci.ResponseCommit(data=self._app_hash)

    def flush_sync(self) -> None:
        pass

    def error(self):
        return None


class Handshaker:
    """Reconcile the app's height with the block store's via ABCI Info,
    re-executing stored blocks as needed."""

    def __init__(
        self,
        state_store: Store,
        state: SMState,
        block_store,
        genesis_doc: GenesisDoc,
        event_bus=None,
        logger: Optional[Logger] = None,
    ):
        self._state_store = state_store
        self._initial_state = state
        self._block_store = block_store
        self._gen_doc = genesis_doc
        self._event_bus = event_bus
        self._logger = logger or new_nop_logger()
        self.n_blocks = 0

    def handshake(self, proxy_app) -> bytes:
        """proxy_app: proxy.AppConns. Returns the app hash the app ended
        at after any replay. Reference: Handshake :241."""
        res = proxy_app.query().info_sync(
            abci.RequestInfo(version="", block_version=BLOCK_PROTOCOL,
                             p2p_version=P2P_PROTOCOL)
        )
        app_block_height = res.last_block_height
        if app_block_height < 0:
            raise RuntimeError(f"got negative last block height {app_block_height}")
        app_hash = res.last_block_app_hash
        self._logger.info(
            "ABCI Handshake App Info",
            height=app_block_height,
            hash=app_hash.hex(),
        )
        # only set the app version if there is no existing state
        # (reference replay.go:263-265)
        if self._initial_state.last_block_height == 0:
            self._initial_state.version.consensus_app = res.app_version
        app_hash = self.replay_blocks(
            self._initial_state, app_hash, app_block_height, proxy_app
        )
        self._logger.info(
            "Completed ABCI Handshake - CometBFT and App are synced",
            app_height=app_block_height,
            app_hash=app_hash.hex(),
        )
        return app_hash

    def replay_blocks(
        self,
        state: SMState,
        app_hash: bytes,
        app_block_height: int,
        proxy_app,
    ) -> bytes:
        """Reference: ReplayBlocks :284."""
        store_height = self._block_store.height()
        store_base = self._block_store.base()
        state_height = state.last_block_height

        # Genesis: the app has no state — InitChain.
        if app_block_height == 0:
            validators = [
                abci.ValidatorUpdate(pub_key_to_proto(gv.pub_key), gv.power)
                for gv in self._gen_doc.validators
            ]
            from cometbft_tpu.types.params import ConsensusParams

            p = self._gen_doc.consensus_params or ConsensusParams()
            req = abci.RequestInitChain(
                time=self._gen_doc.genesis_time,
                chain_id=self._gen_doc.chain_id,
                consensus_params=abci.AbciConsensusParams(
                    block=abci.AbciBlockParams(p.block.max_bytes, p.block.max_gas),
                    evidence=p.evidence,
                    validator=p.validator,
                    version=p.version,
                ),
                validators=validators,
                app_state_bytes=self._gen_doc.app_state,
                initial_height=self._gen_doc.initial_height,
            )
            res_ic = proxy_app.consensus().init_chain_sync(req)

            if store_height == 0:
                # apply InitChain results to the genesis state and persist
                if res_ic.app_hash:
                    app_hash = res_ic.app_hash
                    state.app_hash = res_ic.app_hash
                if res_ic.validators:
                    vals = [validator_from_update(u) for u in res_ic.validators]
                    state.validators = ValidatorSet(vals)
                    nv = ValidatorSet(vals)
                    nv.increment_proposer_priority(1)
                    state.next_validators = nv
                elif not self._gen_doc.validators:
                    raise RuntimeError(
                        "validator set is nil in genesis and still empty "
                        "after InitChain"
                    )
                if res_ic.consensus_params is not None:
                    state.consensus_params = state.consensus_params.update(
                        res_ic.consensus_params
                    )
                self._state_store.save(state)

        # First handshake: nothing stored yet.
        if store_height == 0:
            self._check_app_hash(state, app_hash)
            return app_hash

        if store_height < app_block_height:
            raise RuntimeError(
                f"app block height {app_block_height} is ahead of "
                f"store height {store_height}"
            )
        if store_height < state_height:
            raise RuntimeError(
                f"state height {state_height} is ahead of store height "
                f"{store_height}"
            )

        if store_height == state_height and app_block_height == store_height:
            self._check_app_hash(state, app_hash)
            return app_hash

        if app_block_height == store_height and state_height < store_height:
            # Crash landed between the app's Commit and the state save
            # (reference replay.go:419): the app already executed the final
            # block, so advance the state snapshot against a mock app that
            # replays the recorded ABCI responses instead of re-executing.
            return self._replay_final_with_mock(state, store_height, app_hash)

        return self._replay_range(
            state, proxy_app, app_block_height, store_height, state_height,
            app_hash,
        )

    def _replay_final_with_mock(
        self, state: SMState, height: int, app_hash: bytes
    ) -> bytes:
        from cometbft_tpu.state.execution import BlockExecutor

        responses = self._state_store.load_abci_responses(height)
        block = self._block_store.load_block(height)
        meta = self._block_store.load_block_meta(height)
        if block is None or meta is None:
            raise RuntimeError(f"missing block #{height} during mock replay")
        mock = _MockProxyAppConn(responses, app_hash)
        executor = BlockExecutor(
            self._state_store, mock, event_bus=self._event_bus,
            logger=self._logger,
        )
        new_state, _ = executor.apply_block(state, meta.block_id, block)
        state.__dict__.update(new_state.__dict__)
        self.n_blocks += 1
        return new_state.app_hash

    def _replay_range(
        self,
        state: SMState,
        proxy_app,
        app_height: int,
        store_height: int,
        state_height: int,
        app_hash: bytes,
    ) -> bytes:
        from cometbft_tpu.state.execution import BlockExecutor

        for h in range(app_height + 1, store_height + 1):
            block = self._block_store.load_block(h)
            if block is None:
                raise RuntimeError(f"missing block #{h} during replay")
            final = h == store_height
            if final and state_height < store_height:
                # the final block also advances the state snapshot
                meta = self._block_store.load_block_meta(h)
                executor = BlockExecutor(
                    self._state_store, proxy_app.consensus(),
                    event_bus=self._event_bus, logger=self._logger,
                )
                new_state, _ = executor.apply_block(
                    state, meta.block_id, block
                )
                state.__dict__.update(new_state.__dict__)
                app_hash = new_state.app_hash
            else:
                self._logger.info("Applying block", height=h)
                responses = exec_block_on_proxy_app(
                    proxy_app.consensus(), block, self._state_store,
                    state.initial_height, self._logger,
                )
                res_commit = proxy_app.consensus().commit_sync()
                app_hash = res_commit.data
                del responses
            self.n_blocks += 1
        return app_hash

    def _check_app_hash(self, state: SMState, app_hash: bytes) -> None:
        if state.app_hash and state.app_hash != app_hash:
            raise RuntimeError(
                f"app hash mismatch: state has "
                f"{state.app_hash.hex()}, app returned {app_hash.hex()}"
            )
