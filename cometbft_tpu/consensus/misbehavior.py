"""Pluggable per-height consensus misbehaviors — the maverick node.

Reference: test/maverick/consensus/misbehavior.go:15-17 — a maverick is a
normal node whose consensus takes a ``height → misbehavior`` schedule
(e2e manifests: ``misbehaviors = { 1018 = "double-prevote" }``,
test/e2e/networks/ci.toml:41) and departs from the protocol at exactly
those heights, so evidence detection/commitment can be tested against a
live network rather than hand-crafted votes.

Implemented misbehaviors (the reference's vote-equivocation pair):
  * ``double-prevote``   — alongside the genuine prevote, broadcast a
    conflicting prevote for a fabricated block.
  * ``double-precommit`` — same, for precommits.

`install(node, schedule)` wraps the node's ConsensusState vote signing in
place; honest peers observe both votes in the live round, route the
conflict through report_conflicting_votes into their evidence pools, and
the DuplicateVoteEvidence lands in a committed block.
"""

from __future__ import annotations

from typing import Dict

from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import BlockID, PartSetHeader
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Vote,
)

MISBEHAVIOR_TYPES = {
    "double-prevote": SIGNED_MSG_TYPE_PREVOTE,
    "double-precommit": SIGNED_MSG_TYPE_PRECOMMIT,
}

# proposer-side equivocation (consensus/byzantine_test.go: the byzantine
# proposer sends DIFFERENT proposals to different peers; v0.34 has no
# proposal-equivocation evidence, so the assertion is LIVENESS — the
# first valid proposal wins per peer and the chain keeps committing)
PROPOSER_MISBEHAVIORS = {"double-proposal"}


def install(node, schedule: Dict[int, str]) -> None:
    """Arm a node with a per-height misbehavior schedule.

    ``node`` is a node.Node (needs .consensus_state, .switch,
    .priv_validator, .genesis_doc); each scheduled height fires at most
    once. Unknown misbehavior names raise at install time, like the
    reference's maverick flag parsing."""
    for name in schedule.values():
        if name not in MISBEHAVIOR_TYPES and name not in PROPOSER_MISBEHAVIORS:
            raise ValueError(
                f"unknown misbehavior {name!r}; choose from "
                f"{sorted(MISBEHAVIOR_TYPES) + sorted(PROPOSER_MISBEHAVIORS)}"
            )

    from cometbft_tpu.consensus.messages import (
        VoteMessage,
        encode_consensus_message,
    )
    from cometbft_tpu.consensus.reactor import VOTE_CHANNEL

    cons = node.consensus_state
    chain_id = node.genesis_doc.chain_id
    pv = node.priv_validator
    genuine_sign = cons._sign_add_vote
    fired: set = set()

    def misbehaving_sign(msg_type, hash_, header):
        rs = cons.rs
        name = schedule.get(rs.height)
        want_type = MISBEHAVIOR_TYPES.get(name) if name else None
        if (
            want_type == msg_type
            and rs.height not in fired
            and hash_  # equivocate only against a real (non-nil) vote
            and cons.priv_validator_pub_key is not None
        ):
            fired.add(rs.height)
            idx, _ = rs.validators.get_by_address(
                cons.priv_validator_pub_key.address()
            )
            conflict = Vote(
                type=msg_type,
                height=rs.height,
                round=rs.round,
                block_id=BlockID(
                    b"\xee" * 32, PartSetHeader(1, b"\xdd" * 32)
                ),
                timestamp=Timestamp(1_700_000_000, 0),
                validator_address=cons.priv_validator_pub_key.address(),
                validator_index=idx,
            )
            # sign with the raw key: the FilePV double-sign guard
            # (correctly) refuses conflicting votes at one HRS, and a
            # byzantine node is exactly the thing that bypasses it
            if hasattr(pv, "priv_key"):
                conflict.signature = pv.priv_key.sign(
                    conflict.sign_bytes(chain_id)
                )
            else:
                pv.sign_vote(chain_id, conflict)
            node.switch.broadcast(
                VOTE_CHANNEL,
                encode_consensus_message(VoteMessage(conflict)),
            )
            genuine = genuine_sign(msg_type, hash_, header)
            if genuine is not None:
                # push the genuine vote too so both reach every peer
                # back-to-back within the live round (normal gossip can
                # lose the race against commit)
                node.switch.broadcast(
                    VOTE_CHANNEL,
                    encode_consensus_message(VoteMessage(genuine)),
                )
            return genuine
        return genuine_sign(msg_type, hash_, header)

    cons._sign_add_vote = misbehaving_sign

    from cometbft_tpu.consensus.messages import (
        BlockPartMessage,
        ProposalMessage,
    )
    from cometbft_tpu.consensus.reactor import DATA_CHANNEL
    from cometbft_tpu.types.proposal import Proposal

    genuine_decide = cons._decide_proposal
    node.maverick_fired = fired  # observability for tests/operators

    def misbehaving_decide(height, round_):
        genuine_decide(height, round_)
        rs = cons.rs
        if (
            schedule.get(height) != "double-proposal"
            or (height, "prop") in fired
            or cons.priv_validator_pub_key is None
        ):
            return
        # Build the SECOND block independently: the genuine one only
        # exists in _decide_proposal's locals (rs.proposal_block is not
        # assigned until the internal queue delivers the parts back to
        # the receive thread — state.py:969). Same make_block path as
        # honest proposals — valid header time included (validation.py
        # checks block time EXACTLY, so a time-tweaked block would be
        # rejected outright and peers would never face two VALID
        # proposals) — but with different DATA → different hash.
        commit = cons._proposal_commit(height)
        if commit is None:
            return
        alt, alt_parts = cons.state.make_block(
            height,
            [b"maverick-equivocation"],
            commit,
            [],
            cons.priv_validator_pub_key.address(),
        )
        alt_bid = BlockID(alt.hash(), alt_parts.header())
        prop = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=alt_bid,
            timestamp=Timestamp.now(),
        )
        if hasattr(pv, "priv_key"):
            prop.signature = pv.priv_key.sign(prop.sign_bytes(chain_id))
        else:
            pv.sign_proposal(chain_id, prop)
        node.switch.broadcast(
            DATA_CHANNEL, encode_consensus_message(ProposalMessage(prop))
        )
        for i in range(alt_parts.total()):
            node.switch.broadcast(
                DATA_CHANNEL,
                encode_consensus_message(
                    BlockPartMessage(height, round_, alt_parts.get_part(i))
                ),
            )
        # recorded only AFTER the equivocation is fully broadcast — the
        # e2e's anti-vacuous assertion reads this
        fired.add((height, "prop"))

    cons._decide_proposal = misbehaving_decide
