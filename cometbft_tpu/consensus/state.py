"""The Tendermint consensus state machine.

Reference: consensus/state.go — a single receive routine (:715-804)
serializes peer messages, own messages, and timeouts; every input is
WAL-logged before processing (own votes fsynced); step functions drive
NewRound → Propose → Prevote → (wait) → Precommit → (wait) → Commit with
the lock/unlock rules of the Tendermint algorithm; `add_vote` (:2009) is
the hot path that detects polkas and commits.

Differences from the reference are structural, not semantic: Python
threads + queues instead of goroutines + channels, and vote verification
flows through types.VoteSet → the pluggable batch-verify boundary.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, List, Optional

from cometbft_tpu.config import ConsensusConfig
from cometbft_tpu.crypto import batch as cryptobatch
from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    EndHeightMessage,
    EventDataRoundStateWAL,
    HasVoteMessage,
    MsgInfo,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
    VoteSetMaj23Message,
)
from cometbft_tpu.consensus.round_state import (
    HeightVoteSet,
    RoundState,
    RoundStepType,
)
from cometbft_tpu.consensus.ticker import TimeoutTicker
from cometbft_tpu.consensus.wal import WAL, NilWAL
from cometbft_tpu.libs.log import Logger, new_nop_logger
from cometbft_tpu.libs.service import BaseService
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.state import State as SMState
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.types.block import BlockID, Commit
from cometbft_tpu.types.event_bus import (
    EventDataCompleteProposal,
    EventDataNewRound,
    EventDataRoundState,
    EventDataVote,
    NopEventBus,
)
from cometbft_tpu.types.part_set import PartSet
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Vote,
)
from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes, VoteSet


class ConsensusState(BaseService):
    """One instance per node; owns the round state.

    External inputs arrive via `send_peer_message` / `send_internal` /
    `notify_txs_available`; the reactor subscribes to step/vote broadcasts
    via the callbacks below.
    """

    def __init__(
        self,
        config: ConsensusConfig,
        state: SMState,
        block_exec: BlockExecutor,
        block_store,
        tx_notifier=None,  # object with txs_available() -> bool (mempool)
        evpool=None,
        wal=None,
        event_bus=None,
        crypto_backend: Optional[str] = None,
        metrics=None,  # consensus.metrics.Metrics
        logger: Optional[Logger] = None,
    ):
        super().__init__("ConsensusState")
        from cometbft_tpu.consensus.metrics import Metrics

        self.config = config
        self.crypto_backend = crypto_backend
        self.metrics = metrics if metrics is not None else Metrics.nop()
        self.block_exec = block_exec
        self.block_store = block_store
        self.tx_notifier = tx_notifier
        self.evpool = evpool
        self.logger = logger or new_nop_logger()
        self.event_bus = event_bus if event_bus is not None else NopEventBus()

        self.rs = RoundState()
        self._mtx = threading.RLock()
        self.state: Optional[SMState] = None

        self.priv_validator = None
        self.priv_validator_pub_key = None

        self.peer_msg_queue: "queue.Queue[MsgInfo]" = queue.Queue(maxsize=1000)
        self.internal_msg_queue: "queue.Queue[MsgInfo]" = queue.Queue(maxsize=1000)
        self.n_batch_verify_calls = 0  # observability for the micro-batcher
        self.ticker = TimeoutTicker()
        self.wal = wal if wal is not None else NilWAL()
        self._wal_owned = wal is None

        # reactor hooks (subscribed via set_broadcast_hooks)
        self.on_new_round_step: Optional[Callable[[RoundState], None]] = None
        self.on_has_vote: Optional[Callable[[Vote], None]] = None
        self.on_valid_block: Optional[Callable[[RoundState], None]] = None

        self._receive_thread: Optional[threading.Thread] = None
        self._done_height = threading.Event()
        self.n_steps = 0

        self.update_to_state(state)
        self._reconstruct_last_commit_if_needed(state)

    # -- lifecycle -----------------------------------------------------------

    def set_priv_validator(self, pv) -> None:
        with self._mtx:
            self.priv_validator = pv
            if pv is not None:
                self.priv_validator_pub_key = pv.get_pub_key()

    def set_wal(self, wal) -> None:
        self.wal = wal
        self._wal_owned = False

    def on_start(self) -> None:
        if isinstance(self.wal, NilWAL) and self._wal_owned and self.config.wal_path:
            wal = WAL(self.config.wal_file())
            wal.start()
            self.wal = wal
        self._wal_catchup()
        self._check_double_signing_risk()
        self.ticker.start()
        self._receive_thread = threading.Thread(
            target=self._receive_routine, daemon=True, name="cs-receive"
        )
        self._receive_thread.start()
        self._schedule_round0(self.rs)

    def _wal_catchup(self) -> None:
        """Reference State.OnStart's doWALCatchup loop: we may have lost
        in-flight votes/locks if the process crashed — replay the WAL
        tail before the receive routine starts. Corruption gets ONE
        repair attempt (truncate after the last valid record —
        reference repairWalFile, state.go:2359); any other replay error
        is logged and consensus proceeds (reference behavior — e.g. a
        statesync jump leaves no marker for the new height)."""
        from cometbft_tpu.consensus.replay import catchup_replay
        from cometbft_tpu.consensus.wal import WALDecodeError, repair_wal_tail

        if isinstance(self.wal, NilWAL):
            return
        if getattr(self, "_wal_catchup_done", False):
            return  # an external catchup_replay already ran (tests, tools)
        repaired = False
        while True:
            try:
                catchup_replay(self, self.rs.height)
                return
            except WALDecodeError as exc:
                if repaired:
                    raise
                self.logger.error(
                    "WAL corrupted; repairing tail", err=str(exc)
                )
                if not repair_wal_tail(self.wal):
                    raise
                repaired = True
            except Exception as exc:  # noqa: BLE001 - reference logs all
                self.logger.error(
                    "WAL replay failed; proceeding to consensus",
                    err=str(exc),
                )
                self._wal_catchup_done = True  # attempted; never re-run
                return

    def _check_double_signing_risk(self) -> None:
        """Reference consensus/state.go:2286 checkDoubleSigningRisk
        (called from OnStart): with double_sign_check_height > 0, refuse
        to start if our key already signed a commit within the last N
        heights — the operator likely restored the sign state from an
        old backup, and signing fresh votes from it risks equivocation.
        Off by default, like the reference."""
        n = self.config.double_sign_check_height
        height = self.rs.height
        if (
            n <= 0
            or height <= 0
            or self.priv_validator is None
            or self.priv_validator_pub_key is None
            or self.block_store is None
        ):
            return
        val_addr = self.priv_validator_pub_key.address()
        for i in range(1, min(n, height)):
            commit = self.block_store.load_seen_commit(height - i)
            if commit is None:
                continue
            for sig in commit.signatures:
                if sig.for_block() and sig.validator_address == val_addr:
                    raise RuntimeError(
                        f"found signature from our key at height "
                        f"{height - i} within double_sign_check_height="
                        f"{n}; the sign state may be restored from an "
                        "old backup — refusing to start"
                    )

    def on_stop(self) -> None:
        self.ticker.stop()
        # The WAL must outlive the receive routine (the reference stops
        # the WAL from receiveRoutine's exit path): a finalize in flight
        # still needs write_sync(#ENDHEIGHT) to LAND on disk — stopping
        # the WAL first silently drops the marker while apply_block goes
        # on to persist state, leaving durable state AHEAD of the WAL,
        # and the next start refuses catchup_replay ("WAL has no
        # #ENDHEIGHT h-1"). is_running() is already False here (service
        # stop order), so the routine exits within one iteration.
        t = getattr(self, "_receive_thread", None)
        if t is not None and t is not threading.current_thread():
            # 180 s: must outlast the longest bounded stall a finalize
            # can hit (the one-time device probe is capped at
            # CBFT_TPU_PROBE_TIMEOUT 120 s + 30 s slack)
            t.join(timeout=180.0)
            if t.is_alive():
                # stopping the WAL now would reintroduce the dropped-
                # #ENDHEIGHT bug; leave it running (its flush thread is
                # a daemon — a late write_sync still lands) and say so
                self.logger.error(
                    "receive routine did not exit before stop timeout; "
                    "leaving WAL running so in-flight writes land"
                )
                return
        if not isinstance(self.wal, NilWAL):
            try:
                self.wal.stop()
            except Exception:
                pass

    # -- accessors -----------------------------------------------------------

    def get_round_state(self) -> RoundState:
        with self._mtx:
            import copy

            rs = copy.copy(self.rs)
            return rs

    def height(self) -> int:
        with self._mtx:
            return self.rs.height

    def is_proposer(self, address: bytes) -> bool:
        with self._mtx:
            return (
                self.rs.validators.proposer is not None
                and self.rs.validators.proposer.address == address
            )

    # -- input plumbing ------------------------------------------------------

    def send_peer_message(self, msg, peer_id: str) -> None:
        self.peer_msg_queue.put(MsgInfo(msg, peer_id))

    def send_internal(self, msg) -> None:
        # Never block: the only consumer is the receive thread, which may be
        # the caller (via _decide_proposal) — a blocking put on a full queue
        # would deadlock the node. Mirror sendInternalMessage's goroutine
        # fallback (reference consensus/state.go:1181-1190).
        mi = MsgInfo(msg, "")
        try:
            self.internal_msg_queue.put_nowait(mi)
        except queue.Full:
            threading.Thread(
                target=self.internal_msg_queue.put, args=(mi,), daemon=True
            ).start()

    def notify_txs_available(self) -> None:
        """Mempool → consensus: txs exist (for CreateEmptyBlocks=false).

        Never block: with the builtin app this fires ON the consensus
        thread itself (commit → mempool update/recheck callbacks), whose
        queue has no other consumer — a blocking put on a full queue
        would deadlock the node (same hazard send_internal documents).

        A full queue DROPS the notification instead of parking a thread
        on it: the signal is level-triggered (the mempool still holds
        txs, so the next height's mempool update re-fires it), and a
        queue already packed with peer messages will wake the consensus
        loop anyway. send_internal keeps its goroutine-mirroring thread
        fallback — votes and proposals are edge-triggered and MUST land."""
        mi = MsgInfo(None, "@txs")
        try:
            self.peer_msg_queue.put_nowait(mi)
        except queue.Full:
            pass

    # -- the serialized event loop ------------------------------------------

    def _receive_routine(self) -> None:
        while self.is_running():
            mi = None
            try:
                mi = self.internal_msg_queue.get_nowait()
                internal = True
            except queue.Empty:
                internal = False
            if mi is None:
                try:
                    ti = self.ticker.tock_chan.get_nowait()
                    # timeouts are replayed after a crash — log the real
                    # TimeoutInfo (state.go:790), not just an event
                    self.wal.write(ti)
                    with self._mtx:
                        self._handle_timeout(ti)
                    continue
                except queue.Empty:
                    pass
                try:
                    mi = self.peer_msg_queue.get(timeout=0.01)
                    internal = False
                except queue.Empty:
                    continue
            if mi.msg is None:  # txs-available poke
                with self._mtx:
                    self._handle_txs_available()
                continue
            if internal:
                # own proposals/votes/parts must hit disk before the network
                self.wal.write_sync(mi)
                with self._mtx:
                    self._handle_msg(mi)
                continue
            # micro-batching (north star, SURVEY §7 "latency vs throughput"):
            # drain whatever else is already queued, batch-verify all the
            # drained vote signatures in ONE BatchVerifier call (pure
            # function, no state), then run the exact serial discipline per
            # message: WAL-write it, process it. Interleaving is preserved —
            # in particular #ENDHEIGHT lands between the message that
            # finalized the commit and the next one, exactly as unbatched
            # (crash replay depends on that ordering).
            batch = self._drain_peer_queue(mi)
            self._batch_preverify_votes(batch)
            for m in batch:
                if m.msg is None:  # txs-available poke drained mid-batch
                    with self._mtx:
                        self._handle_txs_available()
                    continue
                self.wal.write(m)
                with self._mtx:
                    self._handle_msg(m)

    MAX_QUEUE_DRAIN = 1024

    def _drain_peer_queue(self, first: MsgInfo) -> list:
        """first + everything already sitting in the peer queue (bounded).
        Order is preserved exactly — the WAL and the handlers see the same
        sequence a serial loop would have."""
        batch = [first]
        while len(batch) < self.MAX_QUEUE_DRAIN:
            try:
                nxt = self.peer_msg_queue.get_nowait()
            except queue.Empty:
                break
            batch.append(nxt)  # txs pokes (msg=None) stay in order
        return batch

    def _resolve_vote_target(self, vote: Vote):
        """The VoteSet this vote would land in (mirrors _add_vote's routing)
        or None when it can't be known without processing."""
        rs = self.rs
        if (
            vote.height + 1 == rs.height
            and vote.type == SIGNED_MSG_TYPE_PRECOMMIT
        ):
            return rs.last_commit
        if vote.height == rs.height and rs.votes is not None:
            return rs.votes._get_vote_set(vote.round, vote.type)
        return None

    def _batch_preverify_votes(self, batch: list) -> None:
        """One BatchVerifier call covering every drained vote whose target
        set and validator resolve cleanly; verified votes carry a marker
        that lets VoteSet._add_vote skip its serial signature check. Any
        vote that doesn't resolve (or fails) goes through the normal serial
        path unchanged."""
        entries = []  # (vote, chain_id, pub_key)
        with self._mtx:
            for m in batch:
                if not isinstance(m.msg, VoteMessage) or m.msg.vote is None:
                    continue
                vote = m.msg.vote
                if vote.validator_index < 0 or not vote.signature:
                    reason = (
                        "negative_index"
                        if vote.validator_index < 0
                        else "empty_signature"
                    )
                    self.metrics.preverify_dropped.with_labels(
                        reason=reason
                    ).add()
                    self.logger.debug(
                        "vote excluded from batch preverification",
                        reason=reason,
                        height=vote.height,
                        round=vote.round,
                        validator_index=vote.validator_index,
                    )
                    continue
                vs = self._resolve_vote_target(vote)
                if vs is None:
                    continue
                addr, val = vs.val_set.get_by_index(vote.validator_index)
                if val is None or addr != vote.validator_address:
                    continue
                entries.append((vote, vs.chain_id, val.pub_key))
        if len(entries) < 2:
            return  # nothing to batch; serial path handles singletons
        bv = cryptobatch.new_batch_verifier(
            self.crypto_backend, subsystem="consensus"
        )
        for vote, chain_id, pub_key in entries:
            bv.add(pub_key, vote.sign_bytes(chain_id), vote.signature)
        self.n_batch_verify_calls += 1
        _, mask = bv.verify()
        for (vote, chain_id, pub_key), ok in zip(entries, mask):
            if ok:
                vote.sig_batch_verified = (chain_id, pub_key.bytes())

    def _handle_msg(self, mi: MsgInfo) -> None:
        msg, peer_id = mi.msg, mi.peer_id
        try:
            if isinstance(msg, ProposalMessage):
                self._set_proposal(msg.proposal)
            elif isinstance(msg, BlockPartMessage):
                self._add_proposal_block_part(msg, peer_id)
            elif isinstance(msg, VoteMessage):
                self._try_add_vote(msg.vote, peer_id)
            else:
                self.logger.error("unknown msg type", type=str(type(msg)))
        except Exception as e:  # reference logs and moves on
            self.logger.error(
                "failed to process message",
                height=self.rs.height,
                round=self.rs.round,
                err=str(e),
            )

    def _handle_timeout(self, ti: TimeoutInfo) -> None:
        rs = self.rs
        if (
            ti.height != rs.height
            or ti.round < rs.round
            or (ti.round == rs.round and ti.step < rs.step)
        ):
            return
        if ti.step == RoundStepType.NEW_HEIGHT:
            self._enter_new_round(ti.height, 0)
        elif ti.step == RoundStepType.NEW_ROUND:
            self._enter_propose(ti.height, 0)
        elif ti.step == RoundStepType.PROPOSE:
            self.event_bus.publish_event_timeout_propose(
                EventDataRoundState(rs.height, rs.round, rs.step.short())
            )
            self._enter_prevote(ti.height, ti.round)
        elif ti.step == RoundStepType.PREVOTE_WAIT:
            self.event_bus.publish_event_timeout_wait(
                EventDataRoundState(rs.height, rs.round, rs.step.short())
            )
            self._enter_precommit(ti.height, ti.round)
        elif ti.step == RoundStepType.PRECOMMIT_WAIT:
            self.event_bus.publish_event_timeout_wait(
                EventDataRoundState(rs.height, rs.round, rs.step.short())
            )
            self._enter_precommit(ti.height, ti.round)
            self._enter_new_round(ti.height, ti.round + 1)

    def _handle_txs_available(self) -> None:
        """Reference: handleTxsAvailable :947-972."""
        rs = self.rs
        if rs.round != 0:  # only the first round of a height waits on txs (:953)
            return
        if rs.step == RoundStepType.NEW_HEIGHT:
            # still in the commit window from the prior block: preserve the
            # remaining timeout_commit (+1ms), don't truncate it (:964)
            remaining = max(rs.start_time - time.monotonic(), 0.0) + 0.001
            self._schedule_timeout(
                remaining, rs.height, 0, RoundStepType.NEW_ROUND
            )
        elif rs.step == RoundStepType.NEW_ROUND:
            # commit window elapsed; we were only waiting for txs (:967)
            self._enter_propose(rs.height, 0)

    # -- state transitions ---------------------------------------------------

    def update_to_state(self, state: SMState) -> None:
        """Reference: updateToState :1700 — reset round state for the next
        height after a commit (or at boot)."""
        rs = self.rs
        if rs.commit_round > -1 and 0 < rs.height and rs.height != state.last_block_height:
            raise RuntimeError(
                f"updateToState expected state height {rs.height} but got "
                f"{state.last_block_height}"
            )
        if self.state is not None and not self.state.is_empty():
            if self.state.last_block_height > 0 and (
                self.state.last_block_height + 1 != rs.height
            ):
                raise RuntimeError("inconsistent cs.state.LastBlockHeight+1 vs cs.Height")
            if state.last_block_height <= self.state.last_block_height:
                # ignore duplicate/older state
                self._new_step()
                return

        validators = state.validators
        if state.last_block_height == 0:  # genesis
            last_precommits = None
        elif rs.commit_round > -1 and rs.votes is not None:
            precommits = rs.votes.precommits(rs.commit_round)
            if precommits is None or not precommits.has_two_thirds_majority():
                raise RuntimeError("wanted to form a commit, but precommits lack majority")
            last_precommits = precommits
        else:
            last_precommits = self.rs.last_commit

        height = state.last_block_height + 1
        if height == 1:
            height = state.initial_height

        rs.height = height
        rs.round = 0
        rs.step = RoundStepType.NEW_HEIGHT
        self.metrics.height.set(height)
        if rs.commit_time == 0:
            rs.start_time = time.monotonic() + self.config.commit_time()
        else:
            rs.start_time = rs.commit_time + self.config.commit_time()
        rs.validators = validators
        rs.proposal = None
        rs.proposal_block = None
        rs.proposal_block_parts = None
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        rs.valid_round = -1
        rs.valid_block = None
        rs.valid_block_parts = None
        rs.votes = HeightVoteSet(state.chain_id, height, validators)
        rs.commit_round = -1
        rs.last_commit = last_precommits
        rs.last_validators = state.last_validators
        rs.triggered_timeout_precommit = False
        self.state = state
        self._new_step()

    def _reconstruct_last_commit_if_needed(self, state: SMState) -> None:
        """Reference: reconstructLastCommit — rebuild LastCommit votes from
        the block store's seen commit after a restart."""
        if state.last_block_height == 0:
            return
        if self.block_store is None:
            return
        seen = self.block_store.load_seen_commit(state.last_block_height)
        if seen is None:
            return
        from cometbft_tpu.types.block import commit_to_vote_set

        try:
            vote_set = commit_to_vote_set(
                state.chain_id, seen, state.last_validators
            )
        except Exception:
            return
        self.rs.last_commit = vote_set

    def _new_step(self) -> None:
        self.n_steps += 1
        rs = self.rs
        self.metrics.mark_step(rs.step.short())
        self.event_bus.publish_event_new_round_step(
            EventDataRoundState(rs.height, rs.round, rs.step.short())
        )
        if self.on_new_round_step is not None:
            self.on_new_round_step(rs)

    def _schedule_round0(self, rs: RoundState) -> None:
        sleep = max(rs.start_time - time.monotonic(), 0.0)
        self._schedule_timeout(sleep, rs.height, 0, RoundStepType.NEW_HEIGHT)

    def _schedule_timeout(
        self, duration_s: float, height: int, round_: int, step: RoundStepType
    ) -> None:
        self.ticker.schedule_timeout(
            TimeoutInfo(duration_s, height, round_, int(step))
        )

    def _enter_new_round(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.step != RoundStepType.NEW_HEIGHT
        ):
            return
        self.logger.debug("entering new round", height=height, round=round_)

        validators = rs.validators
        if rs.round < round_:
            validators = validators.copy()
            validators.increment_proposer_priority(round_ - rs.round)
        self.metrics.rounds.set(round_)
        rs.round = round_
        rs.step = RoundStepType.NEW_ROUND
        rs.validators = validators
        if round_ != 0:
            rs.proposal = None
            rs.proposal_block = None
            rs.proposal_block_parts = None
        rs.votes.set_round(round_ + 1)
        rs.triggered_timeout_precommit = False

        self.event_bus.publish_event_new_round(
            EventDataNewRound(
                height, round_, rs.step.short(),
                validators.proposer.address if validators.proposer else b"",
            )
        )
        self._new_step()

        # reference config.WaitForTxs(): empty blocks off OR rate-limited
        # by the interval knob (which is otherwise a no-op)
        wait_for_txs = (
            (
                not self.config.create_empty_blocks
                or self.config.create_empty_blocks_interval_ns > 0
            )
            and round_ == 0
            and not self._need_proof_block(height)
        )
        if wait_for_txs:
            if self.config.create_empty_blocks_interval_ns > 0:
                self._schedule_timeout(
                    self.config.create_empty_blocks_interval_ns / 1e9,
                    height, round_, RoundStepType.NEW_ROUND,
                )
            if self.tx_notifier is not None and self.tx_notifier.txs_available():
                self._enter_propose(height, round_)
        else:
            self._enter_propose(height, round_)

    def _need_proof_block(self, height: int) -> bool:
        if self.state is None or height == self.state.initial_height:
            return True
        if self.block_store is None:
            return False
        meta = self.block_store.load_block_meta(height - 1)
        if meta is None:
            return True
        return self.state.app_hash != meta.header.app_hash

    def _enter_propose(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and RoundStepType.PROPOSE <= rs.step
        ):
            return
        rs.round = round_
        rs.step = RoundStepType.PROPOSE
        self._new_step()

        self._schedule_timeout(
            self.config.propose_timeout(round_), height, round_,
            RoundStepType.PROPOSE,
        )

        if self.priv_validator is not None and self.priv_validator_pub_key is not None:
            address = self.priv_validator_pub_key.address()
            if rs.validators.has_address(address) and self.is_proposer(address):
                self._decide_proposal(height, round_)

        if self._is_proposal_complete():
            self._enter_prevote(height, rs.round)

    def _proposal_commit(self, height: int):
        """The last-commit a proposal at `height` must carry, or None
        when it cannot be formed yet (:1131's selection; shared with the
        maverick's equivocating proposal builder in misbehavior.py)."""
        if height == (self.state.initial_height if self.state else 1):
            return Commit(0, 0, BlockID(), [])
        rs = self.rs
        if rs.last_commit is not None and rs.last_commit.has_two_thirds_majority():
            return rs.last_commit.make_commit()
        return None

    def _decide_proposal(self, height: int, round_: int) -> None:
        """Reference: defaultDecideProposal :1131."""
        rs = self.rs
        if rs.valid_block is not None:
            block, block_parts = rs.valid_block, rs.valid_block_parts
        else:
            commit = self._proposal_commit(height)
            if commit is None:
                self.logger.error("propose step; cannot propose without commit")
                return
            proposer_addr = self.priv_validator_pub_key.address()
            block, block_parts = self.block_exec.create_proposal_block(
                height, self.state, commit, proposer_addr
            )

        block_id = BlockID(block.hash(), block_parts.header())
        proposal = Proposal(
            height=height,
            round=round_,
            pol_round=rs.valid_round,
            block_id=block_id,
            timestamp=Timestamp.now(),
        )
        try:
            self.priv_validator.sign_proposal(self.state.chain_id, proposal)
        except Exception as e:
            self.logger.error("propose step; failed signing proposal", err=str(e))
            return

        self.send_internal(ProposalMessage(proposal))
        for i in range(block_parts.total()):
            part = block_parts.get_part(i)
            self.send_internal(BlockPartMessage(height, round_, part))
        self.logger.info("signed proposal", height=height, round=round_)

    def _is_proposal_complete(self) -> bool:
        rs = self.rs
        if rs.proposal is None or rs.proposal_block is None:
            return False
        if rs.proposal.pol_round < 0:
            return True
        prevotes = rs.votes.prevotes(rs.proposal.pol_round)
        return prevotes is not None and prevotes.has_two_thirds_majority()

    def _enter_prevote(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and RoundStepType.PREVOTE <= rs.step
        ):
            return
        rs.round = round_
        rs.step = RoundStepType.PREVOTE
        self._new_step()
        self._do_prevote(height, round_)

    def _do_prevote(self, height: int, round_: int) -> None:
        """Reference: defaultDoPrevote :1259."""
        rs = self.rs
        if rs.locked_block is not None:
            self._sign_add_vote(
                SIGNED_MSG_TYPE_PREVOTE,
                rs.locked_block.hash(),
                rs.locked_block_parts.header(),
            )
            return
        if rs.proposal_block is None:
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", None)
            return
        try:
            self.block_exec.validate_block(self.state, rs.proposal_block)
        except Exception as e:
            self.logger.error("prevote step: ProposalBlock is invalid", err=str(e))
            self._sign_add_vote(SIGNED_MSG_TYPE_PREVOTE, b"", None)
            return
        self._sign_add_vote(
            SIGNED_MSG_TYPE_PREVOTE,
            rs.proposal_block.hash(),
            rs.proposal_block_parts.header(),
        )

    def _enter_prevote_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and RoundStepType.PREVOTE_WAIT <= rs.step
        ):
            return
        prevotes = rs.votes.prevotes(round_)
        if prevotes is None or not prevotes.has_two_thirds_any():
            return
        rs.round = round_
        rs.step = RoundStepType.PREVOTE_WAIT
        self._new_step()
        self._schedule_timeout(
            self.config.prevote_timeout(round_), height, round_,
            RoundStepType.PREVOTE_WAIT,
        )

    def _enter_precommit(self, height: int, round_: int) -> None:
        """Reference: enterPrecommit :1329 — the lock/unlock decision."""
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and RoundStepType.PRECOMMIT <= rs.step
        ):
            return
        rs.round = round_
        rs.step = RoundStepType.PRECOMMIT
        self._new_step()

        prevotes = rs.votes.prevotes(round_)
        block_id, ok = (prevotes.two_thirds_majority() if prevotes else (None, False))

        if not ok:
            self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", None)
            return

        self.event_bus.publish_event_polka(
            EventDataRoundState(rs.height, rs.round, rs.step.short())
        )

        if block_id.is_zero():
            # +2/3 prevoted nil: unlock and precommit nil
            if rs.locked_block is not None:
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self.event_bus.publish_event_unlock(
                    EventDataRoundState(rs.height, rs.round, rs.step.short())
                )
            self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", None)
            return

        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.locked_round = round_
            self.event_bus.publish_event_relock(
                EventDataRoundState(rs.height, rs.round, rs.step.short())
            )
            self._sign_add_vote(
                SIGNED_MSG_TYPE_PRECOMMIT, block_id.hash, block_id.part_set_header
            )
            return

        if rs.proposal_block is not None and rs.proposal_block.hash() == block_id.hash:
            try:
                self.block_exec.validate_block(self.state, rs.proposal_block)
            except Exception as e:
                raise RuntimeError(f"precommit step: +2/3 prevoted for an invalid block: {e}")
            rs.locked_round = round_
            rs.locked_block = rs.proposal_block
            rs.locked_block_parts = rs.proposal_block_parts
            self.event_bus.publish_event_lock(
                EventDataRoundState(rs.height, rs.round, rs.step.short())
            )
            self._sign_add_vote(
                SIGNED_MSG_TYPE_PRECOMMIT, block_id.hash, block_id.part_set_header
            )
            return

        # +2/3 prevoted for a block we don't have: unlock, fetch parts, nil
        rs.locked_round = -1
        rs.locked_block = None
        rs.locked_block_parts = None
        if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
            block_id.part_set_header
        ):
            rs.proposal_block = None
            rs.proposal_block_parts = PartSet.from_header(block_id.part_set_header)
        self.event_bus.publish_event_unlock(
            EventDataRoundState(rs.height, rs.round, rs.step.short())
        )
        self._sign_add_vote(SIGNED_MSG_TYPE_PRECOMMIT, b"", None)

    def _enter_precommit_wait(self, height: int, round_: int) -> None:
        rs = self.rs
        if rs.height != height or round_ < rs.round or (
            rs.round == round_ and rs.triggered_timeout_precommit
        ):
            return
        precommits = rs.votes.precommits(round_)
        if precommits is None or not precommits.has_two_thirds_any():
            return
        rs.triggered_timeout_precommit = True
        self._new_step()
        self._schedule_timeout(
            self.config.precommit_timeout(round_), height, round_,
            RoundStepType.PRECOMMIT_WAIT,
        )

    def _enter_commit(self, height: int, commit_round: int) -> None:
        rs = self.rs
        if rs.height != height or RoundStepType.COMMIT <= rs.step:
            return
        rs.step = RoundStepType.COMMIT
        rs.commit_round = commit_round
        rs.commit_time = time.monotonic()
        self._new_step()

        precommits = rs.votes.precommits(commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok:
            raise RuntimeError("RunActionCommit() expects +2/3 precommits")

        if rs.locked_block is not None and rs.locked_block.hash() == block_id.hash:
            rs.proposal_block = rs.locked_block
            rs.proposal_block_parts = rs.locked_block_parts
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            if rs.proposal_block_parts is None or not rs.proposal_block_parts.has_header(
                block_id.part_set_header
            ):
                rs.proposal_block = None
                rs.proposal_block_parts = PartSet.from_header(
                    block_id.part_set_header
                )
                if self.on_valid_block is not None:
                    self.on_valid_block(rs)
        self._try_finalize_commit(height)

    def _try_finalize_commit(self, height: int) -> None:
        rs = self.rs
        if rs.height != height:
            return
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, ok = precommits.two_thirds_majority()
        if not ok or block_id.is_zero():
            return
        if rs.proposal_block is None or rs.proposal_block.hash() != block_id.hash:
            return
        self._finalize_commit(height)

    def _finalize_commit(self, height: int) -> None:
        """Reference: finalizeCommit :1574 — the persistence choreography."""
        from cometbft_tpu.libs import fail

        rs = self.rs
        block, block_parts = rs.proposal_block, rs.proposal_block_parts
        precommits = rs.votes.precommits(rs.commit_round)
        block_id, _ = precommits.two_thirds_majority()

        if not block_parts.has_header(block_id.part_set_header):
            raise RuntimeError("expected ProposalBlockParts header to match commit header")
        if block.hash() != block_id.hash:
            raise RuntimeError("cannot finalize commit; proposal block does not hash to commit hash")
        self.block_exec.validate_block(self.state, block)

        fail.fail()  # before block save
        if self.block_store is not None and self.block_store.height() < block.header.height:
            seen_commit = precommits.make_commit()
            self.block_store.save_block(block, block_parts, seen_commit)
        fail.fail()  # block saved, WAL ENDHEIGHT not yet written

        self.wal.write_sync(EndHeightMessage(height))
        fail.fail()  # ENDHEIGHT written, ApplyBlock not yet run

        state_copy = self.state.copy()
        state_copy, retain_height = self.block_exec.apply_block(
            state_copy, block_id, block
        )
        fail.fail()  # ApplyBlock done

        self._record_metrics(height, block)

        if retain_height > 0 and self.block_store is not None:
            try:
                base = self.block_store.base()
                pruned = self.block_store.prune_blocks(retain_height)
                self.logger.info("pruned blocks", pruned=pruned, retain_height=retain_height)
                # the reference prunes the state artifacts over the same
                # span (consensus/state.go:1717 PruneStates) — without
                # this the per-height validators/params/ABCI-responses
                # grow forever on a pruning chain
                if 0 < base < retain_height:
                    self.block_exec.store().prune_states(base, retain_height)
            except Exception as e:
                self.logger.error("failed to prune blocks", err=str(e))

        self.update_to_state(state_copy)
        self._schedule_round0(self.rs)

    def _record_metrics(self, height: int, block) -> None:
        """Reference: recordMetrics (consensus/state.go:1729-1808)."""
        m = self.metrics
        state = self.state
        m.validators.set(state.validators.size())
        m.validators_power.set(state.validators.total_voting_power())

        if height > state.initial_height and state.last_validators is not None:
            # absent = no signature at all; a nil vote still counts as
            # present (recordMetrics uses commitSig.Absent())
            missing, missing_power = 0, 0
            vals = state.last_validators.validators
            sigs = block.last_commit.signatures
            for i, val in enumerate(vals):
                if i < len(sigs) and sigs[i].is_absent():
                    missing += 1
                    missing_power += val.voting_power
            m.missing_validators.set(missing)
            m.missing_validators_power.set(missing_power)

        byz, byz_power = 0, 0
        for ev in block.evidence:
            addr = getattr(
                getattr(ev, "vote_a", None), "validator_address", None
            )
            if addr is not None:
                _, val = state.validators.get_by_address(addr)
                if val is not None:
                    byz += 1
                    byz_power += val.voting_power
        m.byzantine_validators.set(byz)
        m.byzantine_validators_power.set(byz_power)

        if height > 1 and self.block_store is not None:
            prev = self.block_store.load_block_meta(height - 1)
            if prev is not None:
                dt = (
                    block.header.time.seconds - prev.header.time.seconds
                ) + (block.header.time.nanos - prev.header.time.nanos) / 1e9
                m.block_interval_seconds.observe(dt)

        self._record_prevote_delays(m)

        num_txs = len(block.data.txs)
        m.num_txs.set(num_txs)
        m.total_txs.add(num_txs)
        if self.block_store is not None:
            meta = self.block_store.load_block_meta(height)
            if meta is not None:
                m.block_size_bytes.set(meta.block_size)
        m.committed_height.set(height)

    def _record_prevote_delays(self, m) -> None:
        """Reference: calculatePrevoteMessageDelayMetrics (:2310) — walk
        the commit round's prevotes in timestamp order; the vote that tips
        cumulative power over 2/3 sets the quorum delay, and a 100%-
        prevoted round also sets the full delay."""
        rs = self.rs
        if rs.proposal is None or rs.votes is None or rs.commit_round < 0:
            return
        prevotes = rs.votes.prevotes(rs.commit_round)
        if prevotes is None:
            return
        cast = []
        for v in prevotes.list_votes():
            _, val = rs.validators.get_by_address(v.validator_address)
            if val is not None:
                cast.append((v, val.voting_power))
        if not cast:
            return
        cast.sort(key=lambda e: (e[0].timestamp.seconds, e[0].timestamp.nanos))
        total = rs.validators.total_voting_power()
        prop_ts = rs.proposal.timestamp

        def delay(ts):
            return (ts.seconds - prop_ts.seconds) + (
                ts.nanos - prop_ts.nanos
            ) / 1e9

        cumulative = 0
        quorum_set = False
        for vote, power in cast:
            cumulative += power
            if not quorum_set and cumulative * 3 > total * 2:
                m.quorum_prevote_delay.set(delay(vote.timestamp))
                quorum_set = True
        if cumulative == total:
            m.full_prevote_delay.set(delay(cast[-1][0].timestamp))

    # -- proposals -----------------------------------------------------------

    def _set_proposal(self, proposal: Proposal) -> None:
        """Reference: defaultSetProposal :1817."""
        rs = self.rs
        if rs.proposal is not None:
            return
        if proposal.height != rs.height or proposal.round != rs.round:
            return
        if proposal.pol_round < -1 or (
            proposal.pol_round >= 0 and proposal.pol_round >= proposal.round
        ):
            raise ValueError("error invalid proposal POL round")
        proposer = rs.validators.proposer
        if proposer is None:
            return
        if not proposer.pub_key.verify_signature(
            proposal.sign_bytes(self.state.chain_id), proposal.signature
        ):
            raise ValueError("error invalid proposal signature")
        rs.proposal = proposal
        if rs.proposal_block_parts is None:
            rs.proposal_block_parts = PartSet.from_header(
                proposal.block_id.part_set_header
            )
        self.logger.info("received proposal", proposal_height=proposal.height)

    def _add_proposal_block_part(self, msg: BlockPartMessage, peer_id: str) -> bool:
        """Reference: addProposalBlockPart :1856."""
        rs = self.rs
        if msg.height != rs.height:
            return False
        if rs.proposal_block_parts is None:
            return False
        added = rs.proposal_block_parts.add_part(msg.part)
        self.metrics.block_gossip_parts_received.add(1)
        if not added:
            return False
        self.metrics.block_parts.add(1)
        if rs.proposal_block_parts.is_complete():
            from cometbft_tpu.types.block import Block

            data = rs.proposal_block_parts.get_reader()
            rs.proposal_block = Block.decode(data)
            self.event_bus.publish_event_complete_proposal(
                EventDataCompleteProposal(
                    rs.height, rs.round, rs.step.short(),
                    BlockID(rs.proposal_block.hash(), rs.proposal_block_parts.header()),
                )
            )
            self._handle_complete_proposal(msg.height)
        return True

    def _handle_complete_proposal(self, height: int) -> None:
        """Reference: handleCompleteProposal :1925."""
        rs = self.rs
        prevotes = rs.votes.prevotes(rs.round)
        block_id, has_two_thirds = (
            prevotes.two_thirds_majority() if prevotes else (None, False)
        )
        if has_two_thirds and not block_id.is_zero() and rs.valid_round < rs.round:
            if rs.proposal_block.hash() == block_id.hash:
                rs.valid_round = rs.round
                rs.valid_block = rs.proposal_block
                rs.valid_block_parts = rs.proposal_block_parts

        if rs.step <= RoundStepType.PROPOSE and self._is_proposal_complete():
            self._enter_prevote(height, rs.round)
            if has_two_thirds:
                self._enter_precommit(height, rs.round)
        elif rs.step == RoundStepType.COMMIT:
            self._try_finalize_commit(height)

    # -- votes ---------------------------------------------------------------

    def _try_add_vote(self, vote: Vote, peer_id: str) -> bool:
        try:
            return self._add_vote(vote, peer_id)
        except ErrVoteConflictingVotes as e:
            if self.priv_validator_pub_key is not None and (
                vote.validator_address == self.priv_validator_pub_key.address()
            ):
                self.logger.error(
                    "found conflicting vote from ourselves; did you unsafe_reset a validator?",
                )
                return False
            if self.evpool is not None:
                self.evpool.report_conflicting_votes(e.vote_a, e.vote_b)
            self.logger.debug("found and sent conflicting votes to the evidence pool")
            return False

    def _add_vote(self, vote: Vote, peer_id: str) -> bool:
        """Reference: addVote :2009."""
        rs = self.rs
        # A precommit for the previous height (late precommits)
        if (
            vote.height + 1 == rs.height
            and vote.type == SIGNED_MSG_TYPE_PRECOMMIT
        ):
            if rs.step != RoundStepType.NEW_HEIGHT:
                return False
            if rs.last_commit is None:
                return False
            added, _ = rs.last_commit.add_vote(vote)
            if not added:
                return False
            self.event_bus.publish_event_vote(EventDataVote(vote))
            if self.on_has_vote is not None:
                self.on_has_vote(vote)
            if (
                self.config.skip_timeout_commit
                and rs.last_commit.has_all()
            ):
                self._enter_new_round(rs.height, 0)
            return True

        if vote.height != rs.height:
            return False

        added, err = rs.votes.add_vote(vote, peer_id)
        if not added:
            return False
        self.event_bus.publish_event_vote(EventDataVote(vote))
        if self.on_has_vote is not None:
            self.on_has_vote(vote)

        if vote.type == SIGNED_MSG_TYPE_PREVOTE:
            self._on_prevote_added(vote)
        elif vote.type == SIGNED_MSG_TYPE_PRECOMMIT:
            self._on_precommit_added(vote)
        return True

    def _on_prevote_added(self, vote: Vote) -> None:
        rs = self.rs
        prevotes = rs.votes.prevotes(vote.round)
        block_id, ok = prevotes.two_thirds_majority()
        if ok:
            # unlock on a later polka for a different block (:2074)
            if (
                rs.locked_block is not None
                and rs.locked_round < vote.round
                and vote.round <= rs.round
                and rs.locked_block.hash() != block_id.hash
            ):
                rs.locked_round = -1
                rs.locked_block = None
                rs.locked_block_parts = None
                self.event_bus.publish_event_unlock(
                    EventDataRoundState(rs.height, rs.round, rs.step.short())
                )
            # track the valid block (:2090)
            if not block_id.is_zero() and rs.valid_round < vote.round and (
                vote.round == rs.round
            ):
                if rs.proposal_block is not None and (
                    rs.proposal_block.hash() == block_id.hash
                ):
                    rs.valid_round = vote.round
                    rs.valid_block = rs.proposal_block
                    rs.valid_block_parts = rs.proposal_block_parts
                else:
                    rs.proposal_block = None
                    if rs.proposal_block_parts is None or (
                        not rs.proposal_block_parts.has_header(
                            block_id.part_set_header
                        )
                    ):
                        rs.proposal_block_parts = PartSet.from_header(
                            block_id.part_set_header
                        )
                self.event_bus.publish_event_valid_block(
                    EventDataRoundState(rs.height, rs.round, rs.step.short())
                )
                if self.on_valid_block is not None:
                    self.on_valid_block(rs)

        # transition (:2110)
        if rs.round < vote.round and prevotes.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
        elif rs.round == vote.round and RoundStepType.PREVOTE <= rs.step:
            if ok and (self._is_proposal_complete() or block_id.is_zero()):
                self._enter_precommit(rs.height, vote.round)
            elif prevotes.has_two_thirds_any():
                self._enter_prevote_wait(rs.height, vote.round)
        elif rs.proposal is not None and 0 <= rs.proposal.pol_round and (
            rs.proposal.pol_round == vote.round
        ):
            if self._is_proposal_complete():
                self._enter_prevote(rs.height, rs.round)

    def _on_precommit_added(self, vote: Vote) -> None:
        rs = self.rs
        precommits = rs.votes.precommits(vote.round)
        block_id, ok = precommits.two_thirds_majority()
        if ok:
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit(rs.height, vote.round)
            if not block_id.is_zero():
                self._enter_commit(rs.height, vote.round)
                if self.config.skip_timeout_commit and precommits.has_all():
                    self._enter_new_round(rs.height, 0)
            else:
                self._enter_precommit_wait(rs.height, vote.round)
        elif rs.round <= vote.round and precommits.has_two_thirds_any():
            self._enter_new_round(rs.height, vote.round)
            self._enter_precommit_wait(rs.height, vote.round)

    def _sign_vote(self, msg_type: int, hash_: bytes, header) -> Optional[Vote]:
        rs = self.rs
        if self.priv_validator_pub_key is None:
            return None
        addr = self.priv_validator_pub_key.address()
        val_idx, _ = rs.validators.get_by_address(addr)
        if val_idx < 0:
            return None
        from cometbft_tpu.types.part_set import PartSetHeader

        vote = Vote(
            type=msg_type,
            height=rs.height,
            round=rs.round,
            block_id=BlockID(hash_, header if header is not None else PartSetHeader()),
            timestamp=self._vote_time(),
            validator_address=addr,
            validator_index=val_idx,
        )
        try:
            self.priv_validator.sign_vote(self.state.chain_id, vote)
            return vote
        except Exception as e:
            self.logger.error("failed signing vote", err=str(e))
            return None

    def _vote_time(self) -> Timestamp:
        """Reference: voteTime :2220-2236 — now, but never before the
        candidate block's time + time_iota. The locked block takes
        precedence over the proposal block (else-if, not fall-through)."""
        now = Timestamp.now()
        min_time = now
        if self.state is not None:
            iota_ns = self.state.consensus_params.block.time_iota_ms * 1_000_000
            if self.rs.locked_block is not None:
                min_time = self.rs.locked_block.header.time.add_ns(iota_ns)
            elif self.rs.proposal_block is not None:
                min_time = self.rs.proposal_block.header.time.add_ns(iota_ns)
        return now if min_time <= now else min_time

    def _sign_add_vote(self, msg_type: int, hash_: bytes, header) -> Optional[Vote]:
        if self.priv_validator is None or self.priv_validator_pub_key is None:
            return None
        if not self.rs.validators.has_address(self.priv_validator_pub_key.address()):
            return None
        vote = self._sign_vote(msg_type, hash_, header)
        if vote is not None:
            self.send_internal(VoteMessage(vote))
            self.metrics.validator_last_signed_height.set(vote.height)
        return vote
