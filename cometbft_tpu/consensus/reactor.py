"""Consensus reactor — gossips the consensus protocol over four channels.

Reference: consensus/reactor.go — channels State=0x20, Data=0x21, Vote=0x22,
VoteSetBits=0x23 (:26-29); per-peer gossip threads for block data
(gossipDataRoutine :564, incl. catch-up from the block store :671), votes
(gossipVotesRoutine :723) and maj23 queries (queryMaj23Routine :856);
broadcast of round-step/valid-block/has-vote on the state channel via the
consensus state's internal hooks (subscribeToBroadcastEvents :435).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    HasVoteMessage,
    NewRoundStepMessage,
    NewValidBlockMessage,
    ProposalMessage,
    ProposalPOLMessage,
    VoteMessage,
    VoteSetBitsMessage,
    VoteSetMaj23Message,
    decode_consensus_message,
    encode_consensus_message,
)
from cometbft_tpu.consensus.round_state import RoundState, RoundStepType
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.libs.log import Logger
from cometbft_tpu.p2p.base_reactor import Reactor
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.peer import Peer
from cometbft_tpu.types.block import Commit
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Vote,
)

STATE_CHANNEL = 0x20
DATA_CHANNEL = 0x21
VOTE_CHANNEL = 0x22
VOTE_SET_BITS_CHANNEL = 0x23

from cometbft_tpu.types.keys import PEER_STATE_KEY  # shared with mempool/evidence
PEER_GOSSIP_SLEEP = 0.1  # config/config.go:983 PeerGossipSleepDuration
PEER_QUERY_MAJ23_SLEEP = 2.0  # config/config.go:984
VOTES_TO_BECOME_GOOD_PEER = 10000
BLOCKS_TO_BECOME_GOOD_PEER = 10000


class CommitVoteReader:
    """Adapts a stored Commit to the vote-set reader shape pick_send_vote
    needs (reference: Commit implements VoteSetReader, types/block.go)."""

    def __init__(self, commit: Commit):
        self._commit = commit
        self.height = commit.height
        self.round = commit.round
        self.signed_msg_type = SIGNED_MSG_TYPE_PRECOMMIT

    def size(self) -> int:
        return len(self._commit.signatures)

    def is_commit(self) -> bool:
        return True

    def bit_array(self) -> BitArray:
        ba = BitArray(len(self._commit.signatures))
        for i, cs in enumerate(self._commit.signatures):
            ba.set_index(i, not cs.is_absent())
        return ba

    def get_by_index(self, idx: int) -> Optional[Vote]:
        if self._commit.signatures[idx].is_absent():
            return None
        return self._commit.get_vote(idx)


class VoteSetReader:
    """Uniform view over a live VoteSet (which is already reader-shaped)."""

    @staticmethod
    def wrap(vs):
        return vs  # VoteSet already exposes the needed surface


@dataclass
class PeerRoundState:
    """consensus/types/peer_round_state.go."""

    height: int = 0
    round: int = -1
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time: float = 0.0
    proposal: bool = False
    proposal_block_part_set_header: PartSetHeader = field(
        default_factory=PartSetHeader
    )
    proposal_block_parts: Optional[BitArray] = None
    proposal_pol_round: int = -1
    proposal_pol: Optional[BitArray] = None
    prevotes: Optional[BitArray] = None
    precommits: Optional[BitArray] = None
    last_commit_round: int = -1
    last_commit: Optional[BitArray] = None
    catchup_commit_round: int = -1
    catchup_commit: Optional[BitArray] = None


def compare_hrs(h1, r1, s1, h2, r2, s2) -> int:
    """Reference: consensus/types/peer_round_state.go CompareHRS."""
    if (h1, r1, int(s1)) < (h2, r2, int(s2)):
        return -1
    if (h1, r1, int(s1)) == (h2, r2, int(s2)):
        return 0
    return 1


class PeerState:
    """Known consensus state of one peer (reactor.go:1040 PeerState)."""

    def __init__(self, peer: Peer):
        self.peer = peer
        self._mtx = threading.RLock()
        self.prs = PeerRoundState()
        self.stats_votes = 0
        self.stats_block_parts = 0

    def get_round_state(self) -> PeerRoundState:
        with self._mtx:
            import copy

            return copy.copy(self.prs)

    def get_height(self) -> int:
        with self._mtx:
            return self.prs.height

    # -- setters ------------------------------------------------------------

    def set_has_proposal(self, proposal) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != proposal.height or prs.round != proposal.round:
                return
            if prs.proposal:
                return
            prs.proposal = True
            if prs.proposal_block_parts is not None:
                return  # already set by NewValidBlockMessage
            prs.proposal_block_part_set_header = proposal.block_id.part_set_header
            prs.proposal_block_parts = BitArray(
                proposal.block_id.part_set_header.total
            )
            prs.proposal_pol_round = proposal.pol_round
            prs.proposal_pol = None

    def init_proposal_block_parts(self, header: PartSetHeader) -> None:
        with self._mtx:
            if self.prs.proposal_block_parts is not None:
                return
            self.prs.proposal_block_part_set_header = header
            self.prs.proposal_block_parts = BitArray(header.total)

    def set_has_proposal_block_part(
        self, height: int, round_: int, index: int
    ) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != height or prs.round != round_:
                return
            if prs.proposal_block_parts is not None:
                prs.proposal_block_parts.set_index(index, True)

    def set_has_vote(self, vote: Vote) -> None:
        with self._mtx:
            self._set_has_vote(
                vote.height, vote.round, vote.type, vote.validator_index
            )

    def _set_has_vote(self, height, round_, vote_type, index) -> None:
        ba = self._get_vote_bit_array(height, round_, vote_type)
        if ba is not None:
            ba.set_index(index, True)

    def _get_vote_bit_array(self, height, round_, vote_type):
        prs = self.prs
        if prs.height == height:
            if prs.round == round_:
                return (
                    prs.prevotes
                    if vote_type == SIGNED_MSG_TYPE_PREVOTE
                    else prs.precommits
                )
            if prs.catchup_commit_round == round_:
                if vote_type == SIGNED_MSG_TYPE_PRECOMMIT:
                    return prs.catchup_commit
                return None
            if prs.proposal_pol_round == round_:
                if vote_type == SIGNED_MSG_TYPE_PREVOTE:
                    return prs.proposal_pol
                return None
            return None
        if prs.height == height + 1:
            if prs.last_commit_round == round_:
                if vote_type == SIGNED_MSG_TYPE_PRECOMMIT:
                    return prs.last_commit
                return None
            return None
        return None

    def ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        with self._mtx:
            self._ensure_vote_bit_arrays(height, num_validators)

    def _ensure_vote_bit_arrays(self, height: int, num_validators: int) -> None:
        prs = self.prs
        if prs.height == height:
            if prs.prevotes is None:
                prs.prevotes = BitArray(num_validators)
            if prs.precommits is None:
                prs.precommits = BitArray(num_validators)
            if prs.catchup_commit is None:
                prs.catchup_commit = BitArray(num_validators)
            if prs.proposal_pol is None:
                prs.proposal_pol = BitArray(num_validators)
        elif prs.height == height + 1:
            if prs.last_commit is None:
                prs.last_commit = BitArray(num_validators)

    def _ensure_catchup_commit_round(self, height, round_, num_validators):
        prs = self.prs
        if prs.height != height:
            return
        if prs.catchup_commit_round == round_:
            return
        prs.catchup_commit_round = round_
        if round_ == prs.round:
            prs.catchup_commit = prs.precommits
        else:
            prs.catchup_commit = BitArray(num_validators)

    # -- vote picking -------------------------------------------------------

    def pick_send_vote(self, votes) -> bool:
        """Pick a random vote the peer lacks, send it (reactor.go:1200)."""
        picked = self.pick_vote_to_send(votes)
        if picked is None:
            return False
        msg = encode_consensus_message(VoteMessage(vote=picked))
        if self.peer.send(VOTE_CHANNEL, msg):
            self.set_has_vote(picked)
            return True
        return False

    def pick_vote_to_send(self, votes) -> Optional[Vote]:
        with self._mtx:
            if votes is None or votes.size() == 0:
                return None
            height, round_ = votes.height, votes.round
            vote_type, size = votes.signed_msg_type, votes.size()
            if getattr(votes, "is_commit", lambda: False)():
                self._ensure_catchup_commit_round(height, round_, size)
            self._ensure_vote_bit_arrays(height, size)
            ps_votes = self._get_vote_bit_array(height, round_, vote_type)
            if ps_votes is None:
                return None
            idx = votes.bit_array().sub(ps_votes).pick_random()
            if idx is None:
                return None
            return votes.get_by_index(idx)

    # -- message appliers ---------------------------------------------------

    def apply_new_round_step(self, msg: NewRoundStepMessage) -> None:
        with self._mtx:
            prs = self.prs
            if (
                compare_hrs(
                    msg.height, msg.round, msg.step,
                    prs.height, prs.round, prs.step,
                )
                <= 0
            ):
                return
            ps_height, ps_round = prs.height, prs.round
            ps_catchup_round = prs.catchup_commit_round
            ps_catchup_commit = prs.catchup_commit
            last_precommits = prs.precommits

            prs.height = msg.height
            prs.round = msg.round
            prs.step = RoundStepType(msg.step)
            prs.start_time = time.monotonic() - msg.seconds_since_start_time
            if ps_height != msg.height or ps_round != msg.round:
                prs.proposal = False
                prs.proposal_block_part_set_header = PartSetHeader()
                prs.proposal_block_parts = None
                prs.proposal_pol_round = -1
                prs.proposal_pol = None
                prs.prevotes = None
                prs.precommits = None
            if (
                ps_height == msg.height
                and ps_round != msg.round
                and msg.round == ps_catchup_round
            ):
                prs.precommits = ps_catchup_commit
            if ps_height != msg.height:
                if ps_height + 1 == msg.height and ps_round == msg.last_commit_round:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = last_precommits
                else:
                    prs.last_commit_round = msg.last_commit_round
                    prs.last_commit = None
                prs.catchup_commit_round = -1
                prs.catchup_commit = None

    def apply_new_valid_block(self, msg: NewValidBlockMessage) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != msg.height:
                return
            if prs.round != msg.round and not msg.is_commit:
                return
            prs.proposal_block_part_set_header = msg.block_part_set_header
            prs.proposal_block_parts = msg.block_parts

    def apply_proposal_pol(self, msg: ProposalPOLMessage) -> None:
        with self._mtx:
            prs = self.prs
            if prs.height != msg.height:
                return
            if prs.proposal_pol_round != msg.proposal_pol_round:
                return
            prs.proposal_pol = msg.proposal_pol

    def apply_has_vote(self, msg: HasVoteMessage) -> None:
        with self._mtx:
            if self.prs.height != msg.height:
                return
            self._set_has_vote(msg.height, msg.round, msg.type, msg.index)

    def apply_vote_set_bits(
        self, msg: VoteSetBitsMessage, our_votes: Optional[BitArray]
    ) -> None:
        with self._mtx:
            ba = self._get_vote_bit_array(msg.height, msg.round, msg.type)
            if ba is None:
                return
            if our_votes is not None and msg.votes is not None:
                # have = ourVotes | (theirVotes & msgVotes)
                other_votes = ba.sub(our_votes)
                has_votes = other_votes.or_(msg.votes)
                ba.update(has_votes)
            elif msg.votes is not None:
                ba.update(msg.votes)

    def record_vote(self) -> int:
        with self._mtx:
            self.stats_votes += 1
            return self.stats_votes

    def record_block_part(self) -> int:
        with self._mtx:
            self.stats_block_parts += 1
            return self.stats_block_parts


class ConsensusReactor(Reactor):
    def __init__(
        self,
        cons_state: ConsensusState,
        wait_sync: bool = False,
        gossip_sleep: float = PEER_GOSSIP_SLEEP,
        query_maj23_sleep: float = PEER_QUERY_MAJ23_SLEEP,
        logger: Optional[Logger] = None,
    ):
        super().__init__("ConsensusReactor", logger)
        self.cons = cons_state
        self._wait_sync = wait_sync
        self._wait_sync_mtx = threading.Lock()
        self.gossip_sleep = gossip_sleep
        self.query_maj23_sleep = query_maj23_sleep

    # -- reactor interface --------------------------------------------------

    def get_channels(self) -> List[ChannelDescriptor]:
        return [
            ChannelDescriptor(
                id=STATE_CHANNEL, priority=6,
                send_queue_capacity=100,
            ),
            ChannelDescriptor(
                id=DATA_CHANNEL, priority=10,
                send_queue_capacity=100,
            ),
            ChannelDescriptor(
                id=VOTE_CHANNEL, priority=7,
                send_queue_capacity=100,
            ),
            ChannelDescriptor(
                id=VOTE_SET_BITS_CHANNEL, priority=1,
                send_queue_capacity=2,
            ),
        ]

    def on_start(self) -> None:
        self._subscribe_broadcast_hooks()
        if not self.wait_sync():
            if not self.cons.is_running():
                self.cons.start()

    def on_stop(self) -> None:
        self._unsubscribe_broadcast_hooks()
        if self.cons.is_running():
            self.cons.stop()

    def wait_sync(self) -> bool:
        with self._wait_sync_mtx:
            return self._wait_sync

    def switch_to_consensus(self, state, skip_wal: bool = False) -> None:
        """Called by blocksync when caught up (reactor.go:108)."""
        self.cons.metrics.fast_syncing.set(0)
        self.cons.metrics.state_syncing.set(0)
        self.cons.update_to_state(state)
        with self._wait_sync_mtx:
            self._wait_sync = False
        self.cons.start()
        # let peers know where we are
        rs = self.cons.get_round_state()
        self._broadcast_new_round_step(rs)

    # -- peer lifecycle -----------------------------------------------------

    def init_peer(self, peer: Peer) -> Peer:
        peer.set(PEER_STATE_KEY, PeerState(peer))
        return peer

    def add_peer(self, peer: Peer) -> None:
        if not self.is_running():
            return
        ps: PeerState = peer.get(PEER_STATE_KEY)
        for fn in (
            self._gossip_data_routine,
            self._gossip_votes_routine,
            self._query_maj23_routine,
        ):
            threading.Thread(
                target=fn, args=(peer, ps), daemon=True,
                name=f"cons-gossip-{peer.id()[:6]}",
            ).start()
        if not self.wait_sync():
            self._send_new_round_step(peer)

    # -- receive ------------------------------------------------------------

    def receive(self, ch_id: int, peer: Peer, msg_bytes: bytes) -> None:
        if not self.is_running():
            return
        try:
            msg = decode_consensus_message(msg_bytes)
        except Exception as exc:  # noqa: BLE001
            assert self.switch is not None
            self.switch.stop_peer_for_error(peer, exc)
            return
        ps: PeerState = peer.get(PEER_STATE_KEY)
        if ps is None:
            return

        if ch_id == STATE_CHANNEL:
            self._receive_state(msg, peer, ps)
        elif ch_id == DATA_CHANNEL:
            if self.wait_sync():
                return
            self._receive_data(msg, peer, ps)
        elif ch_id == VOTE_CHANNEL:
            if self.wait_sync():
                return
            if isinstance(msg, VoteMessage):
                cs = self.cons
                with cs._mtx:
                    height = cs.rs.height
                    val_size = cs.rs.validators.size()
                    last_commit_size = (
                        cs.rs.last_commit.size() if cs.rs.last_commit else 0
                    )
                ps.ensure_vote_bit_arrays(height, val_size)
                ps.ensure_vote_bit_arrays(height - 1, last_commit_size)
                ps.set_has_vote(msg.vote)
                self.cons.send_peer_message(msg, peer.id())
        elif ch_id == VOTE_SET_BITS_CHANNEL:
            if self.wait_sync():
                return
            if isinstance(msg, VoteSetBitsMessage):
                cs = self.cons
                with cs._mtx:
                    height, votes = cs.rs.height, cs.rs.votes
                if height == msg.height and votes is not None:
                    if msg.type == SIGNED_MSG_TYPE_PREVOTE:
                        vs = votes.prevotes(msg.round)
                    else:
                        vs = votes.precommits(msg.round)
                    our = (
                        vs.bit_array_by_block_id(msg.block_id)
                        if vs is not None
                        else None
                    )
                    ps.apply_vote_set_bits(msg, our)
                else:
                    ps.apply_vote_set_bits(msg, None)

    def _receive_state(self, msg, peer: Peer, ps: PeerState) -> None:
        if isinstance(msg, NewRoundStepMessage):
            ps.apply_new_round_step(msg)
        elif isinstance(msg, NewValidBlockMessage):
            ps.apply_new_valid_block(msg)
        elif isinstance(msg, HasVoteMessage):
            ps.apply_has_vote(msg)
        elif isinstance(msg, VoteSetMaj23Message):
            cs = self.cons
            with cs._mtx:
                height, votes = cs.rs.height, cs.rs.votes
            if height != msg.height or votes is None:
                return
            votes.set_peer_maj23(msg.round, msg.type, peer.id(), msg.block_id)
            if msg.type == SIGNED_MSG_TYPE_PREVOTE:
                vs = votes.prevotes(msg.round)
            else:
                vs = votes.precommits(msg.round)
            our = (
                vs.bit_array_by_block_id(msg.block_id) if vs is not None else None
            )
            reply = VoteSetBitsMessage(
                height=msg.height,
                round=msg.round,
                type=msg.type,
                block_id=msg.block_id,
                votes=our,
            )
            peer.try_send(
                VOTE_SET_BITS_CHANNEL, encode_consensus_message(reply)
            )

    def _receive_data(self, msg, peer: Peer, ps: PeerState) -> None:
        if isinstance(msg, ProposalMessage):
            ps.set_has_proposal(msg.proposal)
            self.cons.send_peer_message(msg, peer.id())
        elif isinstance(msg, ProposalPOLMessage):
            ps.apply_proposal_pol(msg)
        elif isinstance(msg, BlockPartMessage):
            ps.set_has_proposal_block_part(
                msg.height, msg.round, msg.part.index
            )
            if ps.record_block_part() % BLOCKS_TO_BECOME_GOOD_PEER == 0:
                assert self.switch is not None
                self.switch.mark_peer_as_good(peer)
            self.cons.send_peer_message(msg, peer.id())

    # -- broadcast hooks ----------------------------------------------------

    def _subscribe_broadcast_hooks(self) -> None:
        self.cons.on_new_round_step = self._broadcast_new_round_step
        self.cons.on_valid_block = self._broadcast_new_valid_block
        self.cons.on_has_vote = self._broadcast_has_vote

    def _unsubscribe_broadcast_hooks(self) -> None:
        self.cons.on_new_round_step = None
        self.cons.on_valid_block = None
        self.cons.on_has_vote = None

    def _make_round_step_message(self, rs: RoundState) -> NewRoundStepMessage:
        last_commit_round = (
            rs.last_commit.round if rs.last_commit is not None else -1
        )
        return NewRoundStepMessage(
            height=rs.height,
            round=rs.round,
            step=int(rs.step),
            seconds_since_start_time=max(
                int(time.monotonic() - rs.start_time), 0
            ),
            last_commit_round=last_commit_round,
        )

    def _broadcast_new_round_step(self, rs: RoundState) -> None:
        if self.switch is None:
            return
        msg = encode_consensus_message(self._make_round_step_message(rs))
        self.switch.broadcast(STATE_CHANNEL, msg)

    def _broadcast_new_valid_block(self, rs: RoundState) -> None:
        if self.switch is None or rs.proposal_block_parts is None:
            return
        msg = NewValidBlockMessage(
            height=rs.height,
            round=rs.round,
            block_part_set_header=rs.proposal_block_parts.header(),
            block_parts=rs.proposal_block_parts.bit_array(),
            is_commit=rs.step == RoundStepType.COMMIT,
        )
        self.switch.broadcast(STATE_CHANNEL, encode_consensus_message(msg))

    def _broadcast_has_vote(self, vote: Vote) -> None:
        if self.switch is None:
            return
        msg = HasVoteMessage(
            height=vote.height,
            round=vote.round,
            type=vote.type,
            index=vote.validator_index,
        )
        self.switch.broadcast(STATE_CHANNEL, encode_consensus_message(msg))

    def _send_new_round_step(self, peer: Peer) -> None:
        rs = self.cons.get_round_state()
        msg = encode_consensus_message(self._make_round_step_message(rs))
        peer.send(STATE_CHANNEL, msg)

    # -- gossip routines ----------------------------------------------------

    def _gossip_data_routine(self, peer: Peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cons.get_round_state()
            prs = ps.get_round_state()

            # send proposal block parts the peer lacks
            if (
                rs.proposal_block_parts is not None
                and rs.proposal_block_parts.has_header(
                    prs.proposal_block_part_set_header
                )
                and prs.proposal_block_parts is not None
            ):
                idx = (
                    rs.proposal_block_parts.bit_array()
                    .sub(prs.proposal_block_parts)
                    .pick_random()
                )
                if idx is not None:
                    part = rs.proposal_block_parts.get_part(idx)
                    if part is not None:
                        msg = BlockPartMessage(
                            height=rs.height, round=rs.round, part=part
                        )
                        if peer.send(
                            DATA_CHANNEL, encode_consensus_message(msg)
                        ):
                            ps.set_has_proposal_block_part(
                                prs.height, prs.round, idx
                            )
                        continue

            # peer on an earlier height we have: catch it up from the store
            store = self.cons.block_store
            base = store.base() if store is not None else 0
            if (
                store is not None
                and base > 0
                and 0 < prs.height < rs.height
                and prs.height >= base
            ):
                if prs.proposal_block_parts is None:
                    meta = store.load_block_meta(prs.height)
                    if meta is not None:
                        ps.init_proposal_block_parts(
                            meta.block_id.part_set_header
                        )
                    else:
                        time.sleep(self.gossip_sleep)
                    continue
                self._gossip_data_for_catchup(rs, prs, ps, peer)
                continue

            if rs.height != prs.height or rs.round != prs.round:
                time.sleep(self.gossip_sleep)
                continue

            # send the Proposal (+POL) itself
            if rs.proposal is not None and not prs.proposal:
                msg = ProposalMessage(proposal=rs.proposal)
                if peer.send(DATA_CHANNEL, encode_consensus_message(msg)):
                    ps.set_has_proposal(rs.proposal)
                if rs.proposal.pol_round >= 0 and rs.votes is not None:
                    pol = rs.votes.prevotes(rs.proposal.pol_round)
                    if pol is not None:
                        pol_msg = ProposalPOLMessage(
                            height=rs.height,
                            proposal_pol_round=rs.proposal.pol_round,
                            proposal_pol=pol.bit_array(),
                        )
                        peer.send(
                            DATA_CHANNEL, encode_consensus_message(pol_msg)
                        )
                continue

            time.sleep(self.gossip_sleep)

    def _gossip_data_for_catchup(self, rs, prs, ps: PeerState, peer: Peer):
        """reactor.go:671 gossipDataForCatchup."""
        store = self.cons.block_store
        idx = prs.proposal_block_parts.not_().pick_random()
        if idx is None:
            time.sleep(self.gossip_sleep)
            return
        meta = store.load_block_meta(prs.height)
        if meta is None or not (
            meta.block_id.part_set_header == prs.proposal_block_part_set_header
        ):
            time.sleep(self.gossip_sleep)
            return
        part = store.load_block_part(prs.height, idx)
        if part is None:
            time.sleep(self.gossip_sleep)
            return
        msg = BlockPartMessage(height=prs.height, round=prs.round, part=part)
        if peer.send(DATA_CHANNEL, encode_consensus_message(msg)):
            ps.set_has_proposal_block_part(prs.height, prs.round, idx)
        else:
            time.sleep(self.gossip_sleep)

    def _gossip_votes_routine(self, peer: Peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cons.get_round_state()
            prs = ps.get_round_state()

            if rs.height == prs.height:
                if self._gossip_votes_for_height(rs, prs, ps):
                    continue

            # peer lagging by one: send our last commit votes
            if (
                prs.height != 0
                and rs.height == prs.height + 1
                and rs.last_commit is not None
            ):
                if ps.pick_send_vote(rs.last_commit):
                    continue

            # peer lagging by 2+: send the stored commit
            store = self.cons.block_store
            base = store.base() if store is not None else 0
            if (
                store is not None
                and base > 0
                and prs.height != 0
                and rs.height >= prs.height + 2
                and prs.height >= base
            ):
                commit = store.load_block_commit(prs.height)
                if commit is not None and ps.pick_send_vote(
                    CommitVoteReader(commit)
                ):
                    continue

            time.sleep(self.gossip_sleep)

    def _gossip_votes_for_height(self, rs, prs, ps: PeerState) -> bool:
        """reactor.go:797 gossipVotesForHeight."""
        votes = rs.votes
        if votes is None:
            return False
        # last commit to a peer still in NewHeight
        if prs.step == RoundStepType.NEW_HEIGHT and rs.last_commit is not None:
            if ps.pick_send_vote(rs.last_commit):
                return True
        # POL prevotes
        if (
            prs.step <= RoundStepType.PROPOSE
            and prs.round != -1
            and prs.round <= rs.round
            and prs.proposal_pol_round != -1
        ):
            pol = votes.prevotes(prs.proposal_pol_round)
            if pol is not None and ps.pick_send_vote(pol):
                return True
        # prevotes
        if (
            prs.step <= RoundStepType.PREVOTE_WAIT
            and prs.round != -1
            and prs.round <= rs.round
        ):
            vs = votes.prevotes(prs.round)
            if vs is not None and ps.pick_send_vote(vs):
                return True
        # precommits
        if (
            prs.step <= RoundStepType.PRECOMMIT_WAIT
            and prs.round != -1
            and prs.round <= rs.round
        ):
            vs = votes.precommits(prs.round)
            if vs is not None and ps.pick_send_vote(vs):
                return True
        # prevotes again (valid-block mechanism)
        if prs.round != -1 and prs.round <= rs.round:
            vs = votes.prevotes(prs.round)
            if vs is not None and ps.pick_send_vote(vs):
                return True
        # POL prevotes again
        if prs.proposal_pol_round != -1:
            pol = votes.prevotes(prs.proposal_pol_round)
            if pol is not None and ps.pick_send_vote(pol):
                return True
        return False

    def _query_maj23_routine(self, peer: Peer, ps: PeerState) -> None:
        while peer.is_running() and self.is_running():
            rs = self.cons.get_round_state()
            prs = ps.get_round_state()
            if rs.height == prs.height and rs.votes is not None:
                # prevotes
                vs = rs.votes.prevotes(prs.round)
                if vs is not None:
                    maj23, ok = vs.two_thirds_majority()
                    if ok:
                        self._send_maj23(
                            peer, prs.height, prs.round,
                            SIGNED_MSG_TYPE_PREVOTE, maj23,
                        )
                # precommits
                vs = rs.votes.precommits(prs.round)
                if vs is not None:
                    maj23, ok = vs.two_thirds_majority()
                    if ok:
                        self._send_maj23(
                            peer, prs.height, prs.round,
                            SIGNED_MSG_TYPE_PRECOMMIT, maj23,
                        )
                # POL prevotes
                if prs.proposal_pol_round >= 0:
                    vs = rs.votes.prevotes(prs.proposal_pol_round)
                    if vs is not None:
                        maj23, ok = vs.two_thirds_majority()
                        if ok:
                            self._send_maj23(
                                peer, prs.height, prs.proposal_pol_round,
                                SIGNED_MSG_TYPE_PREVOTE, maj23,
                            )
            # catchup commit
            store = self.cons.block_store
            if (
                store is not None
                and prs.catchup_commit_round != -1
                and 0 < prs.height <= store.height()
                and prs.height >= store.base()
            ):
                commit = store.load_block_commit(prs.height)
                if commit is not None:
                    self._send_maj23(
                        peer, prs.height, commit.round,
                        SIGNED_MSG_TYPE_PRECOMMIT, commit.block_id,
                    )
            time.sleep(self.query_maj23_sleep)

    def _send_maj23(self, peer, height, round_, vote_type, block_id) -> None:
        msg = VoteSetMaj23Message(
            height=height, round=round_, type=vote_type, block_id=block_id
        )
        peer.try_send(STATE_CHANNEL, encode_consensus_message(msg))
