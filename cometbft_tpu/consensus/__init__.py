"""consensus — the Tendermint state machine, WAL, replay, and gossip types.

Reference layout: consensus/state.go (algorithm), consensus/types/
(RoundState, HeightVoteSet), consensus/wal.go (+libs/autofile),
consensus/replay.go (crash recovery + ABCI handshake),
consensus/ticker.go (timeout scheduling).
"""

from cometbft_tpu.consensus.round_state import (  # noqa: F401
    HeightVoteSet,
    RoundState,
    RoundStepType,
)
