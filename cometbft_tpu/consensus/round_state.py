"""Round state + per-height vote bookkeeping.

Reference: consensus/types/round_state.go:67-94 (RoundState),
consensus/types/height_vote_set.go:41-50 (HeightVoteSet — one prevote and
one precommit VoteSet per round, with a peer-catchup round limit).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional, Tuple

from cometbft_tpu.proto.gogo import Timestamp, ZERO_TIME
from cometbft_tpu.types.block import Block, BlockID, Commit
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.validator_set import ValidatorSet
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    Vote,
)
from cometbft_tpu.types.vote_set import VoteSet


class RoundStepType(IntEnum):
    """consensus/types/round_state.go:12-40."""

    NEW_HEIGHT = 1
    NEW_ROUND = 2
    PROPOSE = 3
    PREVOTE = 4
    PREVOTE_WAIT = 5
    PRECOMMIT = 6
    PRECOMMIT_WAIT = 7
    COMMIT = 8

    def short(self) -> str:
        return {
            1: "NewHeight", 2: "NewRound", 3: "Propose", 4: "Prevote",
            5: "PrevoteWait", 6: "Precommit", 7: "PrecommitWait", 8: "Commit",
        }[int(self)]


@dataclass
class RoundState:
    height: int = 0
    round: int = 0
    step: RoundStepType = RoundStepType.NEW_HEIGHT
    start_time: float = 0.0
    commit_time: float = 0.0
    validators: Optional[ValidatorSet] = None
    proposal: Optional[Proposal] = None
    proposal_block: Optional[Block] = None
    proposal_block_parts: Optional[object] = None  # PartSet
    locked_round: int = -1
    locked_block: Optional[Block] = None
    locked_block_parts: Optional[object] = None
    valid_round: int = -1
    valid_block: Optional[Block] = None
    valid_block_parts: Optional[object] = None
    votes: Optional["HeightVoteSet"] = None
    commit_round: int = -1
    last_commit: Optional[VoteSet] = None
    last_validators: Optional[ValidatorSet] = None
    triggered_timeout_precommit: bool = False

    def step_str(self) -> str:
        return f"{self.height}/{self.round}/{self.step.short()}"


class HeightVoteSet:
    """Keeps prevote/precommit VoteSets for every round of one height.

    Peers can only make us create up to 2 extra catch-up rounds
    (reference: height_vote_set.go SetPeerMaj23 round limit).
    """

    MAX_CATCHUP_ROUNDS = 2  # height_vote_set.go:26

    def __init__(self, chain_id: str, height: int, val_set: ValidatorSet):
        self.chain_id = chain_id
        self._mtx = threading.RLock()
        self.reset(height, val_set)

    def reset(self, height: int, val_set: ValidatorSet) -> None:
        with self._mtx:
            self.height = height
            self.val_set = val_set
            self._round_vote_sets: Dict[int, Tuple[VoteSet, VoteSet]] = {}
            self._peer_catchup_rounds: Dict[str, List[int]] = {}
            self._add_round(0)
            self.round = 0

    def _add_round(self, round_: int) -> None:
        if round_ in self._round_vote_sets:
            return
        prevotes = VoteSet(
            self.chain_id, self.height, round_, SIGNED_MSG_TYPE_PREVOTE, self.val_set
        )
        precommits = VoteSet(
            self.chain_id, self.height, round_, SIGNED_MSG_TYPE_PRECOMMIT,
            self.val_set,
        )
        self._round_vote_sets[round_] = (prevotes, precommits)

    def set_round(self, round_: int) -> None:
        """Create vote sets up to round_ + 1 (reference allows future round
        +1 for gossip)."""
        with self._mtx:
            for r in range(self.round, round_ + 2):
                self._add_round(r)
            self.round = round_

    def add_vote(self, vote: Vote, peer_id: str) -> Tuple[bool, Optional[str]]:
        with self._mtx:
            if not _is_vote_type_valid(vote.type):
                return False, f"invalid vote type {vote.type}"
            vs = self._get_vote_set(vote.round, vote.type)
            if vs is None:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) < self.MAX_CATCHUP_ROUNDS:
                    self._add_round(vote.round)
                    vs = self._get_vote_set(vote.round, vote.type)
                    rounds.append(vote.round)
                else:
                    return False, "peer has sent a vote that does not match our round for more than one round"
            return vs.add_vote(vote)

    def prevotes(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, SIGNED_MSG_TYPE_PREVOTE)

    def precommits(self, round_: int) -> Optional[VoteSet]:
        with self._mtx:
            return self._get_vote_set(round_, SIGNED_MSG_TYPE_PRECOMMIT)

    def _get_vote_set(self, round_: int, type_: int) -> Optional[VoteSet]:
        pair = self._round_vote_sets.get(round_)
        if pair is None:
            return None
        return pair[0] if type_ == SIGNED_MSG_TYPE_PREVOTE else pair[1]

    def pol_info(self) -> Tuple[int, Optional[BlockID]]:
        """Last round with a prevote +2/3 (proof-of-lock), searching from
        the current round down (reference: POLInfo)."""
        with self._mtx:
            for r in range(self.round, -1, -1):
                vs = self._get_vote_set(r, SIGNED_MSG_TYPE_PREVOTE)
                if vs is not None:
                    block_id, ok = vs.two_thirds_majority()
                    if ok:
                        return r, block_id
            return -1, None

    def set_peer_maj23(
        self, round_: int, type_: int, peer_id: str, block_id: BlockID
    ) -> None:
        with self._mtx:
            if not _is_vote_type_valid(type_):
                return
            vs = self._get_vote_set(round_, type_)
            if vs is None:
                rounds = self._peer_catchup_rounds.setdefault(peer_id, [])
                if len(rounds) >= self.MAX_CATCHUP_ROUNDS:
                    return
                self._add_round(round_)
                vs = self._get_vote_set(round_, type_)
                rounds.append(round_)
            vs.set_peer_maj23(peer_id, block_id)


def _is_vote_type_valid(t: int) -> bool:
    return t in (SIGNED_MSG_TYPE_PREVOTE, SIGNED_MSG_TYPE_PRECOMMIT)
