"""Consensus gossip + WAL message codecs.

Field numbers per proto/tendermint/consensus/types.proto (Message oneof
:80-92) and wal.proto (WALMessage oneof, TimedWALMessage). These are the
payloads of p2p channels 0x20-0x23 and of WAL records, so wire layout
matters; in-memory they are plain dataclasses.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.proto.gogo import Timestamp, ZERO_TIME
from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.part_set import Part, PartSetHeader
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import Vote


# Generous upper bound on decoded bitmap size: covers vote bitmaps (validator
# count) and part-set bitmaps (max block bytes / 64 KiB parts) with orders of
# magnitude to spare, while capping what a hostile 12-byte message can make us
# allocate (bits is attacker-controlled and drives a [0]*(bits//64) alloc).
MAX_BIT_ARRAY_BITS = 1 << 24


def _encode_bit_array(ba: Optional[BitArray]) -> bytes:
    """proto libs.bits.BitArray {int64 bits=1, repeated uint64 elems=2}.

    Elems are emitted packed (wire type 2), matching gogoproto's proto3
    default for repeated scalars, and unconditionally — zero elems are data
    (an all-zero bitmap must round-trip to its full length).
    """
    if ba is None:
        return b""
    out = protoio.field_varint(1, ba.size)
    elems = ba.elems()
    if elems:
        packed = b"".join(protoio.encode_varint(e) for e in elems)
        out += protoio.field_bytes(2, packed)
    return out


def _decode_bit_array(data: bytes) -> Optional[BitArray]:
    r = protoio.WireReader(data)
    bits, elems = 0, []
    while not r.at_end():
        f, wt = r.read_tag()
        if f == 1:
            bits = r.read_varint()
        elif f == 2 and wt == protoio.WIRE_BYTES:
            # packed repeated uint64 (gogoproto/proto3 default)
            pr = protoio.WireReader(r.read_bytes())
            while not pr.at_end():
                elems.append(pr.read_uvarint())
        elif f == 2:
            elems.append(r.read_uvarint())
        else:
            r.skip(wt)
    if bits == 0:
        return None
    if bits < 0 or bits > MAX_BIT_ARRAY_BITS:
        raise ValueError(f"bit array size {bits} out of range")
    want = (bits + 63) // 64
    if not elems:
        # an encoder that omits zero fields sends an all-zero bitmap as
        # bits-only; anything partially present is ambiguous (interior zero
        # elems shift the map) and stays a hard error in from_elems
        elems = [0] * want
    return BitArray.from_elems(bits, elems)


@dataclass
class NewRoundStepMessage:
    height: int = 0
    round: int = 0
    step: int = 0
    seconds_since_start_time: int = 0
    last_commit_round: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        if self.step:
            out += protoio.field_varint(3, self.step)
        if self.seconds_since_start_time:
            out += protoio.field_varint(4, self.seconds_since_start_time)
        if self.last_commit_round:
            out += protoio.field_varint(5, self.last_commit_round)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "NewRoundStepMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.step = r.read_varint()
            elif f == 4:
                out.seconds_since_start_time = r.read_varint()
            elif f == 5:
                out.last_commit_round = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class NewValidBlockMessage:
    height: int = 0
    round: int = 0
    block_part_set_header: PartSetHeader = field(default_factory=PartSetHeader)
    block_parts: Optional[BitArray] = None
    is_commit: bool = False

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        out += protoio.field_message(3, self.block_part_set_header.encode())
        if self.block_parts is not None:
            out += protoio.field_message(4, _encode_bit_array(self.block_parts))
        if self.is_commit:
            out += protoio.field_varint(5, 1)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "NewValidBlockMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.block_part_set_header = PartSetHeader.decode(r.read_bytes())
            elif f == 4:
                out.block_parts = _decode_bit_array(r.read_bytes())
            elif f == 5:
                out.is_commit = bool(r.read_varint())
            else:
                r.skip(wt)
        return out


@dataclass
class ProposalMessage:
    proposal: Proposal = field(default_factory=Proposal)

    def encode(self) -> bytes:
        return protoio.field_message(1, self.proposal.encode())

    @classmethod
    def decode(cls, data: bytes) -> "ProposalMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.proposal = Proposal.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class ProposalPOLMessage:
    height: int = 0
    proposal_pol_round: int = 0
    proposal_pol: Optional[BitArray] = None

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.proposal_pol_round:
            out += protoio.field_varint(2, self.proposal_pol_round)
        out += protoio.field_message(3, _encode_bit_array(self.proposal_pol))
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ProposalPOLMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.proposal_pol_round = r.read_varint()
            elif f == 3:
                out.proposal_pol = _decode_bit_array(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class BlockPartMessage:
    height: int = 0
    round: int = 0
    part: Optional[Part] = None

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        if self.part is not None:
            out += protoio.field_message(3, self.part.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "BlockPartMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.part = Part.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class VoteMessage:
    vote: Optional[Vote] = None

    def encode(self) -> bytes:
        if self.vote is None:
            return b""
        return protoio.field_message(1, self.vote.encode())

    @classmethod
    def decode(cls, data: bytes) -> "VoteMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.vote = Vote.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class HasVoteMessage:
    height: int = 0
    round: int = 0
    type: int = 0
    index: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        if self.type:
            out += protoio.field_varint(3, self.type)
        if self.index:
            out += protoio.field_varint(4, self.index)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "HasVoteMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.type = r.read_varint()
            elif f == 4:
                out.index = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class VoteSetMaj23Message:
    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = field(default_factory=BlockID)

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        if self.type:
            out += protoio.field_varint(3, self.type)
        out += protoio.field_message(4, self.block_id.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "VoteSetMaj23Message":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.type = r.read_varint()
            elif f == 4:
                out.block_id = BlockID.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class VoteSetBitsMessage:
    height: int = 0
    round: int = 0
    type: int = 0
    block_id: BlockID = field(default_factory=BlockID)
    votes: Optional[BitArray] = None

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.round:
            out += protoio.field_varint(2, self.round)
        if self.type:
            out += protoio.field_varint(3, self.type)
        out += protoio.field_message(4, self.block_id.encode())
        out += protoio.field_message(5, _encode_bit_array(self.votes))
        return out

    @classmethod
    def decode(cls, data: bytes) -> "VoteSetBitsMessage":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.round = r.read_varint()
            elif f == 3:
                out.type = r.read_varint()
            elif f == 4:
                out.block_id = BlockID.decode(r.read_bytes())
            elif f == 5:
                out.votes = _decode_bit_array(r.read_bytes())
            else:
                r.skip(wt)
        return out


_MESSAGE_FIELDS = {
    "new_round_step": (1, NewRoundStepMessage),
    "new_valid_block": (2, NewValidBlockMessage),
    "proposal": (3, ProposalMessage),
    "proposal_pol": (4, ProposalPOLMessage),
    "block_part": (5, BlockPartMessage),
    "vote": (6, VoteMessage),
    "has_vote": (7, HasVoteMessage),
    "vote_set_maj23": (8, VoteSetMaj23Message),
    "vote_set_bits": (9, VoteSetBitsMessage),
}
_MESSAGE_BY_TYPE = {cls: (name, num) for name, (num, cls) in _MESSAGE_FIELDS.items()}
_MESSAGE_BY_NUM = {num: (name, cls) for name, (num, cls) in _MESSAGE_FIELDS.items()}


def encode_consensus_message(msg) -> bytes:
    """Message oneof envelope."""
    name, num = _MESSAGE_BY_TYPE[type(msg)]
    return protoio.field_message(num, msg.encode())


def decode_consensus_message(data: bytes):
    r = protoio.WireReader(data)
    result = None
    while not r.at_end():
        f, wt = r.read_tag()
        if f in _MESSAGE_BY_NUM:
            _, cls = _MESSAGE_BY_NUM[f]
            result = cls.decode(r.read_bytes())
        else:
            r.skip(wt)
    if result is None:
        raise ValueError("empty consensus Message")
    return result


# --- WAL messages ----------------------------------------------------------


@dataclass
class MsgInfo:
    """A consensus message + its origin peer ('' = internal)."""

    msg: object = None
    peer_id: str = ""


@dataclass
class TimeoutInfo:
    duration_s: float = 0.0
    height: int = 0
    round: int = 0
    step: int = 0

    def __str__(self) -> str:
        return f"{self.duration_s}s ; {self.height}/{self.round}/{self.step}"


@dataclass
class EndHeightMessage:
    """WAL #ENDHEIGHT marker (wal.proto EndHeight)."""

    height: int = 0


@dataclass
class EventDataRoundStateWAL:
    height: int = 0
    round: int = 0
    step: str = ""


def encode_wal_message(msg) -> bytes:
    """WALMessage oneof (wal.proto): event=1, msg_info=2, timeout=3, end=4."""
    if isinstance(msg, EventDataRoundStateWAL):
        body = b""
        if msg.height:
            body += protoio.field_varint(1, msg.height)
        if msg.round:
            body += protoio.field_varint(2, msg.round)
        if msg.step:
            body += protoio.field_string(3, msg.step)
        return protoio.field_message(1, body)
    if isinstance(msg, MsgInfo):
        body = protoio.field_message(1, encode_consensus_message(msg.msg))
        if msg.peer_id:
            body += protoio.field_string(2, msg.peer_id)
        return protoio.field_message(2, body)
    if isinstance(msg, TimeoutInfo):
        ns = int(msg.duration_s * 1_000_000_000)
        dur = protoio.field_varint(1, ns // 1_000_000_000)
        if ns % 1_000_000_000:
            dur += protoio.field_varint(2, ns % 1_000_000_000)
        body = protoio.field_message(1, dur)
        if msg.height:
            body += protoio.field_varint(2, msg.height)
        if msg.round:
            body += protoio.field_varint(3, msg.round)
        if msg.step:
            body += protoio.field_varint(4, msg.step)
        return protoio.field_message(3, body)
    if isinstance(msg, EndHeightMessage):
        body = protoio.field_varint(1, msg.height) if msg.height else b""
        return protoio.field_message(4, body)
    raise TypeError(f"unknown WAL message {type(msg)}")


def decode_wal_message(data: bytes):
    r = protoio.WireReader(data)
    result = None
    while not r.at_end():
        f, wt = r.read_tag()
        if f == 1:
            body = protoio.WireReader(r.read_bytes())
            out = EventDataRoundStateWAL()
            while not body.at_end():
                bf, bwt = body.read_tag()
                if bf == 1:
                    out.height = body.read_varint()
                elif bf == 2:
                    out.round = body.read_varint()
                elif bf == 3:
                    out.step = body.read_string()
                else:
                    body.skip(bwt)
            result = out
        elif f == 2:
            body = protoio.WireReader(r.read_bytes())
            out = MsgInfo()
            while not body.at_end():
                bf, bwt = body.read_tag()
                if bf == 1:
                    out.msg = decode_consensus_message(body.read_bytes())
                elif bf == 2:
                    out.peer_id = body.read_string()
                else:
                    body.skip(bwt)
            result = out
        elif f == 3:
            body = protoio.WireReader(r.read_bytes())
            out = TimeoutInfo()
            while not body.at_end():
                bf, bwt = body.read_tag()
                if bf == 1:
                    dr = protoio.WireReader(body.read_bytes())
                    secs = nanos = 0
                    while not dr.at_end():
                        df, dwt = dr.read_tag()
                        if df == 1:
                            secs = dr.read_varint()
                        elif df == 2:
                            nanos = dr.read_varint()
                        else:
                            dr.skip(dwt)
                    out.duration_s = secs + nanos / 1_000_000_000
                elif bf == 2:
                    out.height = body.read_varint()
                elif bf == 3:
                    out.round = body.read_varint()
                elif bf == 4:
                    out.step = body.read_varint()
                else:
                    body.skip(bwt)
            result = out
        elif f == 4:
            body = protoio.WireReader(r.read_bytes())
            out = EndHeightMessage()
            while not body.at_end():
                bf, bwt = body.read_tag()
                if bf == 1:
                    out.height = body.read_varint()
                else:
                    body.skip(bwt)
            result = out
        else:
            r.skip(wt)
    if result is None:
        raise ValueError("empty WALMessage")
    return result
