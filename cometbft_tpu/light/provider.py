"""Light block providers.

Reference: light/provider/provider.go (interface), light/provider/mock
(deterministic in-memory provider used across the reference's
client/detector tests), and light/provider/http (RPC-backed LightBlock
source — HTTPProvider below rides cometbft_tpu.rpc.client).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from cometbft_tpu.light.errors import (
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    ErrNoResponse,
)
from cometbft_tpu.types.light_block import LightBlock


class Provider:
    def light_block(self, height: int) -> LightBlock:
        """Return the light block at `height` (0 = latest). Raises
        ErrLightBlockNotFound / ErrHeightTooHigh / ErrNoResponse."""
        raise NotImplementedError

    def report_evidence(self, ev) -> None:
        raise NotImplementedError

    def id(self) -> str:
        return repr(self)


class MockProvider(Provider):
    """Serves a fixed map of height → LightBlock (light/provider/mock)."""

    def __init__(self, chain_id: str, blocks: Dict[int, LightBlock]):
        self.chain_id = chain_id
        self._blocks = dict(blocks)
        self.evidence: List[object] = []

    def latest_height(self) -> int:
        return max(self._blocks) if self._blocks else 0

    def light_block(self, height: int) -> LightBlock:
        if not self._blocks:
            raise ErrLightBlockNotFound()
        if height == 0:
            height = self.latest_height()
        if height > self.latest_height():
            raise ErrHeightTooHigh()
        lb = self._blocks.get(height)
        if lb is None:
            raise ErrLightBlockNotFound()
        return lb

    def report_evidence(self, ev) -> None:
        self.evidence.append(ev)

    def add(self, lb: LightBlock) -> None:
        self._blocks[lb.height] = lb

    def id(self) -> str:
        return f"mock-{self.chain_id}"


class BlockStoreProvider(Provider):
    """Serves light blocks straight from a node's own stores — used by
    statesync's state provider and in-process light clients
    (reference analog: light/provider/http against a local node)."""

    def __init__(self, chain_id: str, block_store, state_store):
        self.chain_id = chain_id
        self._block_store = block_store
        self._state_store = state_store

    def light_block(self, height: int) -> LightBlock:
        from cometbft_tpu.types.light_block import SignedHeader

        if height == 0:
            height = self._block_store.height()
        if height > self._block_store.height():
            raise ErrHeightTooHigh()
        meta = self._block_store.load_block_meta(height)
        commit = self._block_store.load_block_commit(height)
        if meta is None or commit is None:
            raise ErrLightBlockNotFound()
        try:
            vals = self._state_store.load_validators(height)
        except Exception as exc:
            raise ErrLightBlockNotFound() from exc
        return LightBlock(
            signed_header=SignedHeader(meta.header, commit),
            validator_set=vals,
        )

    def report_evidence(self, ev) -> None:
        pass  # a local node learns about evidence through its own pool

    def consensus_params(self, height: int):
        """Serve consensus params for statesync's state provider
        (reference analog: light/rpc/client.go ConsensusParams)."""
        return self._state_store.load_consensus_params(height)

    def id(self) -> str:
        return f"blockstore-{self.chain_id}"


class HTTPProvider(Provider):
    """Light blocks from a full node's JSON-RPC (light/provider/http).

    `server` is a base URL or host:port; light_block stitches /commit and
    /validators (paged) into a LightBlock."""

    def __init__(self, chain_id: str, server: str, timeout: float = 10.0):
        from cometbft_tpu.rpc.client import HTTPClient

        self.chain_id = chain_id
        self._client = HTTPClient(server, timeout=timeout)

    def light_block(self, height: int) -> LightBlock:
        from cometbft_tpu.rpc.client import (
            RPCClientError,
            parse_commit,
            parse_header,
            parse_validators,
        )
        from cometbft_tpu.types.light_block import SignedHeader

        try:
            res = self._client.commit(height or None)
            sh = res["signed_header"]
            header = parse_header(sh["header"])
            commit = parse_commit(sh["commit"])
            if height and header.height != height:
                # a faulty primary answering with a different (but
                # self-consistent) height must not slip through
                # (light/provider/http height check)
                raise ErrLightBlockNotFound()
            h = header.height
            items = []
            for page in range(1, 101):  # reference maxPages = 100
                vres = self._client.validators(h, page=page, per_page=100)
                got = vres["validators"]
                if not got:
                    break
                items.extend(got)
                if len(items) >= int(vres["total"]):
                    break
            else:
                raise ErrNoResponse("validator set exceeds 100 pages")
            vals = parse_validators(items)
        except (ErrLightBlockNotFound, ErrHeightTooHigh, ErrNoResponse):
            raise
        except RPCClientError as exc:
            # mirror light/provider/http error classification
            text = exc.message + exc.data
            if "must be less than or equal" in text:
                raise ErrHeightTooHigh() from exc
            if "not found" in text:
                raise ErrLightBlockNotFound() from exc
            raise ErrNoResponse(str(exc)) from exc
        except Exception as exc:  # network-level: URLError, timeout, ...
            raise ErrNoResponse(str(exc)) from exc
        return LightBlock(
            signed_header=SignedHeader(header, commit), validator_set=vals
        )

    def consensus_params(self, height: int):
        from cometbft_tpu.types.params import ConsensusParams

        res = self._client.consensus_params(height or None)
        return ConsensusParams.from_json(res["consensus_params"])

    def report_evidence(self, ev) -> None:
        pass  # broadcast_evidence route — future work

    def id(self) -> str:
        return f"http-{self._client.base_url}"
