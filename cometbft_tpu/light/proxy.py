"""Light-client verifying RPC proxy.

Reference: light/proxy + light/rpc — a JSON-RPC server that fronts an
untrusted full node: block/commit/validators responses are checked
against light-client-verified headers before they reach the caller, so a
lying primary cannot feed a wallet forged data. Routes without
verifiable content (status, broadcast_tx_*) pass through annotated.
"""

from __future__ import annotations

import json
from typing import Optional

from cometbft_tpu.libs.log import Logger, new_nop_logger
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.rpc.client import (
    HTTPClient,
    parse_commit,
    parse_header,
    parse_validators,
)


def _now() -> Timestamp:
    import time

    ns = time.time_ns()
    return Timestamp(ns // 1_000_000_000, ns % 1_000_000_000)


class ErrProxyVerification(Exception):
    """The primary's response contradicts the verified light block."""


class LightProxy:
    """Wraps a light client + primary RPC into a verifying JSON-RPC server
    (light/rpc/client.go semantics for the verified routes)."""

    def __init__(
        self,
        light_client,  # light.client.Client
        primary: HTTPClient,
        logger: Optional[Logger] = None,
    ):
        self._lc = light_client
        self._primary = primary
        self.logger = logger or new_nop_logger()

    # -- verified routes -------------------------------------------------------

    def block(self, height: int) -> dict:
        """Primary's block, cross-checked against the verified header:
        the header must hash to the verified block hash AND the body must
        hash to the header's commitments — txs to data_hash, last_commit
        to last_commit_hash — so a forged body under a genuine header is
        also refused (light/rpc/client.go Block + ValidateBasic)."""
        import base64

        from cometbft_tpu.crypto import merkle
        from cometbft_tpu.types.tx import Txs

        res = self._primary.block(height)
        verified = self._lc.verify_light_block_at_height(height, _now())
        got_header = parse_header(res["block"]["header"])
        want_hash = verified.signed_header.header.hash()
        if got_header.hash() != want_hash:
            raise ErrProxyVerification(
                f"primary's block at height {height} does not match the "
                f"verified header"
            )
        if bytes.fromhex(res["block_id"]["hash"]) != want_hash:
            raise ErrProxyVerification("primary's block_id hash mismatch")
        # body commitments (the verified header pins these hashes)
        txs = Txs(
            base64.b64decode(t) for t in res["block"]["data"].get("txs") or []
        )
        if txs.hash() != got_header.data_hash:
            raise ErrProxyVerification(
                "primary's transactions do not hash to the header's "
                "data_hash"
            )
        last_commit = res["block"].get("last_commit")
        if height > 1:
            if last_commit is None:
                # omission is forgery too: the verified header commits to
                # a real last_commit at every height after the first
                raise ErrProxyVerification(
                    "primary omitted last_commit for a height > 1"
                )
            got_commit = parse_commit(last_commit)
            if got_commit.hash() != got_header.last_commit_hash:
                raise ErrProxyVerification(
                    "primary's last_commit does not hash to the header's "
                    "last_commit_hash"
                )
        ev_list = [
            base64.b64decode(e)
            for e in (res["block"].get("evidence") or {}).get("evidence")
            or []
        ]
        if merkle.hash_from_byte_slices(ev_list) != got_header.evidence_hash:
            raise ErrProxyVerification(
                "primary's evidence does not hash to the header's "
                "evidence_hash"
            )
        return res

    def commit(self, height: int) -> dict:
        res = self._primary.commit(height)
        verified = self._lc.verify_light_block_at_height(height, _now())
        got = parse_commit(res["signed_header"]["commit"])
        want = verified.signed_header.commit
        if got.block_id.hash != want.block_id.hash:
            raise ErrProxyVerification(
                f"primary's commit at height {height} is for a different "
                f"block"
            )
        got_header = parse_header(res["signed_header"]["header"])
        if got_header.hash() != verified.signed_header.header.hash():
            raise ErrProxyVerification("primary's header mismatch in commit")
        return res

    def validators(self, height: int) -> dict:
        verified = self._lc.verify_light_block_at_height(height, _now())
        items = []
        res = None
        for page in range(1, 101):  # provider-style page cap
            res = self._primary.validators(height, page=page, per_page=100)
            got_page = res["validators"]
            if not got_page:
                break
            items.extend(got_page)
            if len(items) >= int(res["total"]):
                break
        else:
            raise ErrProxyVerification("validator set exceeds 100 pages")
        got = parse_validators(items)
        if got.hash() != verified.validator_set.hash():
            raise ErrProxyVerification(
                f"primary's validator set at height {height} does not hash "
                f"to the verified validators_hash"
            )
        return {
            "block_height": str(height),
            "validators": items,
            "count": str(len(items)),
            "total": str(len(items)),
        }

    # -- passthrough -----------------------------------------------------------

    def status(self) -> dict:
        return self._primary.status()

    def broadcast_tx_sync(self, tx: bytes) -> dict:
        return self._primary.broadcast_tx_sync(tx)

    def broadcast_tx_commit(self, tx: bytes) -> dict:
        return self._primary.broadcast_tx_commit(tx)

    # -- JSON-RPC surface ------------------------------------------------------

    def _handle(self, payload: dict) -> dict:
        import base64

        method = payload.get("method", "")
        params = payload.get("params") or {}
        rid = payload.get("id", 0)
        try:
            if method == "block":
                result = self.block(int(params["height"]))
            elif method == "commit":
                result = self.commit(int(params["height"]))
            elif method == "validators":
                result = self.validators(int(params["height"]))
            elif method == "status":
                result = self.status()
            elif method in ("broadcast_tx_sync", "broadcast_tx_commit"):
                tx = base64.b64decode(params["tx"])
                result = getattr(self, method)(tx)
            else:
                return {
                    "jsonrpc": "2.0",
                    "id": rid,
                    "error": {
                        "code": -32601,
                        "message": f"method {method} not available on the "
                        f"verifying proxy",
                    },
                }
            return {"jsonrpc": "2.0", "id": rid, "result": result}
        except ErrProxyVerification as exc:
            return {
                "jsonrpc": "2.0",
                "id": rid,
                "error": {"code": -32100, "message": f"VERIFICATION FAILED: {exc}"},
            }
        except Exception as exc:  # noqa: BLE001
            return {
                "jsonrpc": "2.0",
                "id": rid,
                "error": {"code": -32603, "message": str(exc)},
            }

    def serve(self, host: str, port: int) -> int:
        """Serve JSON-RPC over HTTP POST."""
        import http.server
        import threading

        proxy = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                try:
                    payload = json.loads(self.rfile.read(length))
                except ValueError:
                    self.send_error(400)
                    return
                body = json.dumps(proxy._handle(payload)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="light-proxy", daemon=True
        )
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if getattr(self, "_httpd", None) is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
