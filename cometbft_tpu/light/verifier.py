"""Pure light-client verification functions.

Reference: light/verifier.go — VerifyAdjacent (:93), VerifyNonAdjacent
(:32), Verify (:135), VerifyBackwards (:221), HeaderExpired (:207),
ValidateTrustLevel (:196). Signature checks route through the
batch-verification boundary via ValidatorSet.verify_commit_light /
verify_commit_light_trusting, so the TPU backend accelerates both the
2/3 check on the new set and the 1/3 trusting check on the old set.
The `backend` parameter accepts anything `crypto.batch.Backend` does —
a backend name, a BackendSpec, or the node's VerifyScheduler, in which
case light-client signature lanes coalesce with verification traffic
from other subsystems into shared TPU dispatches.

Durations are nanoseconds; `now` is a proto Timestamp.
"""

from __future__ import annotations

from cometbft_tpu.crypto.batch import Backend

from cometbft_tpu.light.errors import (
    ErrInvalidHeader,
    ErrNewValSetCantBeTrusted,
    ErrOldHeaderExpired,
)
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import Header
from cometbft_tpu.types.light_block import SignedHeader
from cometbft_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    Fraction,
    ValidatorSet,
)

DEFAULT_TRUST_LEVEL = Fraction(1, 3)


def validate_trust_level(lvl: Fraction) -> None:
    """Trust level must be in [1/3, 1] (verifier.go:196)."""
    if (
        lvl.numerator * 3 < lvl.denominator
        or lvl.numerator > lvl.denominator
        or lvl.denominator == 0
    ):
        raise ValueError(
            f"trustLevel must be within [1/3, 1], given {lvl.numerator}/"
            f"{lvl.denominator}"
        )


def header_expired(h: SignedHeader, trusting_period_ns: int, now: Timestamp) -> bool:
    """verifier.go:207 — expired when time + trustingPeriod <= now."""
    expiration_ns = h.header.time.to_unix_ns() + trusting_period_ns
    return expiration_ns <= now.to_unix_ns()


def _verify_new_header_and_vals(
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusted_header: SignedHeader,
    now: Timestamp,
    max_clock_drift_ns: int,
) -> None:
    """verifier.go:160 verifyNewHeaderAndVals."""
    try:
        untrusted_header.validate_basic(trusted_header.header.chain_id)
    except ValueError as exc:
        raise ValueError(f"untrustedHeader.ValidateBasic failed: {exc}") from exc

    if untrusted_header.height <= trusted_header.height:
        raise ValueError(
            f"expected new header height {untrusted_header.height} to be "
            f"greater than one of old header {trusted_header.height}"
        )
    if (
        untrusted_header.header.time.to_unix_ns()
        <= trusted_header.header.time.to_unix_ns()
    ):
        raise ValueError(
            f"expected new header time {untrusted_header.header.time} to be "
            f"after old header time {trusted_header.header.time}"
        )
    if (
        untrusted_header.header.time.to_unix_ns()
        >= now.to_unix_ns() + max_clock_drift_ns
    ):
        raise ValueError(
            f"new header has a time from the future "
            f"{untrusted_header.header.time} (now: {now})"
        )
    if untrusted_header.header.validators_hash != untrusted_vals.hash():
        raise ValueError(
            f"expected new header validators "
            f"({untrusted_header.header.validators_hash.hex()}) to match "
            f"those that were supplied ({untrusted_vals.hash().hex()}) at "
            f"height {untrusted_header.height}"
        )


def verify_adjacent(
    trusted_header: SignedHeader,  # height X
    untrusted_header: SignedHeader,  # height X+1
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: Timestamp,
    max_clock_drift_ns: int,
    backend: Backend = None,
) -> None:
    """verifier.go:93 VerifyAdjacent."""
    if untrusted_header.height != trusted_header.height + 1:
        raise ValueError("headers must be adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted_header.header.time.add_ns(trusting_period_ns), now
        )
    try:
        _verify_new_header_and_vals(
            untrusted_header, untrusted_vals, trusted_header, now,
            max_clock_drift_ns,
        )
    except ValueError as exc:
        raise ErrInvalidHeader(exc) from exc

    if (
        untrusted_header.header.validators_hash
        != trusted_header.header.next_validators_hash
    ):
        raise ErrInvalidHeader(
            f"expected old header next validators "
            f"({trusted_header.header.next_validators_hash.hex()}) to match "
            f"those from new header "
            f"({untrusted_header.header.validators_hash.hex()})"
        )

    try:
        untrusted_vals.verify_commit_light(
            trusted_header.header.chain_id,
            untrusted_header.commit.block_id,
            untrusted_header.height,
            untrusted_header.commit,
            backend=backend,
        )
    except Exception as exc:
        raise ErrInvalidHeader(exc) from exc


def verify_non_adjacent(
    trusted_header: SignedHeader,  # height X
    trusted_vals: ValidatorSet,  # height X or X+1
    untrusted_header: SignedHeader,  # height Y
    untrusted_vals: ValidatorSet,  # height Y
    trusting_period_ns: int,
    now: Timestamp,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    backend: Backend = None,
) -> None:
    """verifier.go:32 VerifyNonAdjacent."""
    if untrusted_header.height == trusted_header.height + 1:
        raise ValueError("headers must be non adjacent in height")
    if header_expired(trusted_header, trusting_period_ns, now):
        raise ErrOldHeaderExpired(
            trusted_header.header.time.add_ns(trusting_period_ns), now
        )
    try:
        _verify_new_header_and_vals(
            untrusted_header, untrusted_vals, trusted_header, now,
            max_clock_drift_ns,
        )
    except ValueError as exc:
        raise ErrInvalidHeader(exc) from exc

    # 1/3+ of the last-trusted validators must have signed the new header
    try:
        trusted_vals.verify_commit_light_trusting(
            trusted_header.header.chain_id,
            untrusted_header.commit,
            trust_level,
            backend=backend,
        )
    except ErrNotEnoughVotingPowerSigned as exc:
        raise ErrNewValSetCantBeTrusted(exc) from exc

    # 2/3+ of the new set must have signed (LAST check: untrustedVals is
    # attacker-sized in the non-adjacent case — DOS ordering, verifier.go:69)
    try:
        untrusted_vals.verify_commit_light(
            trusted_header.header.chain_id,
            untrusted_header.commit.block_id,
            untrusted_header.height,
            untrusted_header.commit,
            backend=backend,
        )
    except Exception as exc:
        raise ErrInvalidHeader(exc) from exc


def verify(
    trusted_header: SignedHeader,
    trusted_vals: ValidatorSet,
    untrusted_header: SignedHeader,
    untrusted_vals: ValidatorSet,
    trusting_period_ns: int,
    now: Timestamp,
    max_clock_drift_ns: int,
    trust_level: Fraction = DEFAULT_TRUST_LEVEL,
    backend: Backend = None,
) -> None:
    """verifier.go:135 Verify — dispatch on adjacency."""
    if untrusted_header.height != trusted_header.height + 1:
        verify_non_adjacent(
            trusted_header, trusted_vals, untrusted_header, untrusted_vals,
            trusting_period_ns, now, max_clock_drift_ns, trust_level, backend,
        )
    else:
        verify_adjacent(
            trusted_header, untrusted_header, untrusted_vals,
            trusting_period_ns, now, max_clock_drift_ns, backend,
        )


def verify_backwards(untrusted_header: Header, trusted_header: Header) -> None:
    """verifier.go:221 VerifyBackwards — walk the LastBlockID chain."""
    try:
        untrusted_header.validate_basic()
    except ValueError as exc:
        raise ErrInvalidHeader(exc) from exc
    if untrusted_header.chain_id != trusted_header.chain_id:
        raise ErrInvalidHeader("header belongs to another chain")
    if (
        untrusted_header.time.to_unix_ns()
        >= trusted_header.time.to_unix_ns()
    ):
        raise ErrInvalidHeader(
            f"expected older header time {untrusted_header.time} to be "
            f"before new header time {trusted_header.time}"
        )
    if untrusted_header.hash() != trusted_header.last_block_id.hash:
        raise ErrInvalidHeader(
            f"older header hash {untrusted_header.hash().hex()} does not "
            f"match trusted header's last block "
            f"{trusted_header.last_block_id.hash.hex()}"
        )
