"""Light client — trusted store + primary/witness providers + bisection.

Reference: light/client.go — NewClient w/ TrustOptions (:174),
VerifyLightBlockAtHeight (:474), verifySequential (:613), verifySkipping
bisection (:706), Update (:436), backwards verification (:933), witness
cross-checks + divergence detection (light/detector.go:28,116,217) that
produce LightClientAttackEvidence and report it to both sides.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional, Tuple

from cometbft_tpu.libs.log import Logger, new_nop_logger
from cometbft_tpu.light import verifier
from cometbft_tpu.light.errors import (
    ErrHeightTooHigh,
    ErrLightBlockNotFound,
    ErrLightClientAttack,
    ErrNewValSetCantBeTrusted,
    ErrNoResponse,
    ErrVerificationFailed,
)
from cometbft_tpu.light.provider import Provider
from cometbft_tpu.light.store import DBStore
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.evidence import LightClientAttackEvidence
from cometbft_tpu.types.light_block import LightBlock
from cometbft_tpu.types.validator_set import Fraction

DEFAULT_PRUNING_SIZE = 1000
DEFAULT_MAX_CLOCK_DRIFT_NS = 10 * 1_000_000_000
# pivot = trusted + (new - trusted) * 1/2  (client.go verifySkipping*)
_SKIP_NUMERATOR, _SKIP_DENOMINATOR = 1, 2


@dataclass
class TrustOptions:
    """Reference: light.TrustOptions — period + (height, hash) root of trust."""

    period_ns: int
    height: int
    hash: bytes

    def validate(self) -> None:
        if self.period_ns <= 0:
            raise ValueError("trusting period must be greater than zero")
        if self.height <= 0:
            raise ValueError("trusted height must be greater than zero")
        if len(self.hash) != 32:
            raise ValueError("expected a 32-byte trusted header hash")


class Client:
    def __init__(
        self,
        chain_id: str,
        trust_options: TrustOptions,
        primary: Provider,
        witnesses: List[Provider],
        trusted_store: DBStore,
        trust_level: Fraction = verifier.DEFAULT_TRUST_LEVEL,
        max_clock_drift_ns: int = DEFAULT_MAX_CLOCK_DRIFT_NS,
        pruning_size: int = DEFAULT_PRUNING_SIZE,
        sequential: bool = False,
        crypto_backend: Optional[str] = None,
        logger: Optional[Logger] = None,
    ):
        verifier.validate_trust_level(trust_level)
        trust_options.validate()
        self.chain_id = chain_id
        self.trusting_period_ns = trust_options.period_ns
        self.trust_level = trust_level
        self.max_clock_drift_ns = max_clock_drift_ns
        self.pruning_size = pruning_size
        self.sequential = sequential
        self.crypto_backend = crypto_backend
        self.logger = logger or new_nop_logger()
        self._mtx = threading.Lock()
        self.primary = primary
        self.witnesses = list(witnesses)
        self.store = trusted_store
        latest = self.store.latest_light_block()
        if latest is None:
            self._initialize(trust_options)
        else:
            self._check_restored_store(latest, trust_options)

    # -- initialization ------------------------------------------------------

    def _check_restored_store(
        self, latest: LightBlock, opts: TrustOptions
    ) -> None:
        """client.go:303 checkTrustedHeaderUsingOptions — a restored store
        must be revalidated against the caller's root of trust; a silent
        skip would keep trusting a chain from a possibly-compromised
        earlier primary. No interactive confirmation here: mismatches and
        rollbacks that Go asks the operator about are hard errors."""
        if opts.height > latest.height:
            # trust root is ahead of the store: the primary must agree with
            # what we stored
            primary_hash = self._light_block_from_primary(
                latest.height
            ).signed_header.header.hash()
        elif opts.height == latest.height:
            primary_hash = opts.hash
        else:
            # trust root below stored latest: roll the store back to it
            stored = self.store.light_block(opts.height)
            if stored is not None and (
                stored.signed_header.header.hash() == opts.hash
            ):
                for h in range(opts.height + 1, latest.height + 1):
                    self.store.delete_light_block(h)
                return
            if stored is not None:
                raise ValueError(
                    "restored trusted store conflicts with TrustOptions at "
                    f"height {opts.height}"
                )
            # bisection never stored that height: wipe and re-sync from the
            # caller's root of trust (Go: Cleanup after confirmation)
            for h in list(
                range(self.store.first_height(), latest.height + 1)
            ):
                self.store.delete_light_block(h)
            self._initialize(opts)
            return
        if primary_hash != latest.signed_header.header.hash():
            raise ValueError(
                "restored trusted store hash does not match the root of "
                "trust; refusing to continue (wipe the store to re-sync)"
            )

    def _initialize(self, opts: TrustOptions) -> None:
        """client.go:362 initializeWithTrustOptions — fetch the root-of-trust
        block from the primary, check the hash, check 2/3 signed it."""
        lb = self._light_block_from_primary(opts.height)
        if lb.signed_header.header.hash() != opts.hash:
            raise ValueError(
                f"expected header's hash {opts.hash.hex()}, but got "
                f"{lb.signed_header.header.hash().hex()}"
            )
        lb.validator_set.verify_commit_light(
            self.chain_id,
            lb.signed_header.commit.block_id,
            lb.height,
            lb.signed_header.commit,
            backend=self.crypto_backend,
        )
        # cross-check the root of trust with every witness (detector.go:1131)
        self._compare_first_header_with_witnesses(lb)
        self._update_trusted_light_block(lb)

    # -- accessors -----------------------------------------------------------

    def trusted_light_block(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block(height)

    def last_trusted_height(self) -> int:
        return self.store.latest_height()

    def first_trusted_height(self) -> int:
        return self.store.first_height()

    # -- the core API ---------------------------------------------------------

    def update(self, now: Timestamp) -> Optional[LightBlock]:
        """Fetch + verify the primary's latest block if newer than our
        latest trusted (client.go:436). Verifies the block it fetched —
        no second fetch, no TOCTOU against a flapping primary."""
        with self._mtx:
            last = self.store.latest_light_block()
            if last is None:
                raise RuntimeError("no trusted state")
            latest = self._light_block_from_primary(0)
            if latest.height <= last.height:
                return None
            self._verify_light_block(latest, now)
            return latest

    def verify_light_block_at_height(
        self, height: int, now: Timestamp
    ) -> LightBlock:
        """client.go:474 VerifyLightBlockAtHeight."""
        if height <= 0:
            raise ValueError("height must be positive")
        with self._mtx:
            lb = self.store.light_block(height)
            if lb is not None:
                return lb
            latest = self.store.latest_light_block()
            if latest is not None and height < latest.height:
                # below our latest trusted: walk hashes backwards
                return self._backwards(latest, height)
            new_block = self._light_block_from_primary(height)
            self._verify_light_block(new_block, now)
            return new_block

    def _verify_light_block(self, new_block: LightBlock, now: Timestamp) -> None:
        """client.go:558 — pick sequential/skipping from the nearest trusted
        block at a lower height, then run witness cross-checks."""
        closest = self._closest_trusted_below(new_block.height)
        if closest is None:
            raise RuntimeError("no trusted state below requested height")
        if self.sequential:
            trace = self._verify_sequential(closest, new_block, now)
        else:
            trace = self._verify_skipping_against_primary(closest, new_block, now)
        # witness cross-examination on the verified header
        self._detect_divergence(trace, now)
        self._update_trusted_light_block(new_block)

    def _closest_trusted_below(self, height: int) -> Optional[LightBlock]:
        return self.store.light_block_before(height)

    # -- verification strategies ----------------------------------------------

    def _verify_sequential(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> List[LightBlock]:
        """client.go:613 — verify every height in (trusted, new]."""
        verified = trusted
        trace = [trusted]
        for height in range(trusted.height + 1, new_block.height + 1):
            inter = (
                new_block
                if height == new_block.height
                else self._light_block_from_primary(height)
            )
            verifier.verify_adjacent(
                verified.signed_header,
                inter.signed_header,
                inter.validator_set,
                self.trusting_period_ns,
                now,
                self.max_clock_drift_ns,
                backend=self.crypto_backend,
            )
            verified = inter
            trace.append(inter)
        return trace

    def _verify_skipping(
        self,
        source: Provider,
        trusted: LightBlock,
        new_block: LightBlock,
        now: Timestamp,
    ) -> List[LightBlock]:
        """client.go:706 verifySkipping — bisection."""
        block_cache = [new_block]
        depth = 0
        verified = trusted
        trace = [trusted]
        while True:
            target = block_cache[depth]
            try:
                verifier.verify(
                    verified.signed_header,
                    verified.validator_set,
                    target.signed_header,
                    target.validator_set,
                    self.trusting_period_ns,
                    now,
                    self.max_clock_drift_ns,
                    self.trust_level,
                    backend=self.crypto_backend,
                )
            except ErrNewValSetCantBeTrusted as exc:
                # too big a validator power shift — bisect
                if depth == len(block_cache) - 1:
                    pivot = (
                        verified.height
                        + (target.height - verified.height)
                        * _SKIP_NUMERATOR
                        // _SKIP_DENOMINATOR
                    )
                    try:
                        interim = source.light_block(pivot)
                    except (ErrLightBlockNotFound, ErrNoResponse, ErrHeightTooHigh):
                        raise exc
                    except Exception as provider_err:
                        raise ErrVerificationFailed(
                            verified.height, pivot, provider_err
                        ) from provider_err
                    block_cache.append(interim)
                depth += 1
                continue
            except Exception as exc:
                raise ErrVerificationFailed(
                    verified.height, target.height, exc
                ) from exc
            # verified
            if depth == 0:
                trace.append(new_block)
                return trace
            verified = target
            block_cache = block_cache[:depth]
            depth = 0
            trace.append(verified)

    def _verify_skipping_against_primary(
        self, trusted: LightBlock, new_block: LightBlock, now: Timestamp
    ) -> List[LightBlock]:
        return self._verify_skipping(self.primary, trusted, new_block, now)

    def _backwards(self, latest: LightBlock, height: int) -> LightBlock:
        """client.go:933 — follow LastBlockID hashes down to `height`."""
        trusted = latest
        while trusted.height > height:
            interim = self._light_block_from_primary(trusted.height - 1)
            verifier.verify_backwards(
                interim.signed_header.header, trusted.signed_header.header
            )
            trusted = interim
        self.store.save_light_block(trusted)
        return trusted

    # -- witness cross-checks (light/detector.go) -------------------------------

    def _compare_first_header_with_witnesses(self, lb: LightBlock) -> None:
        """detector.go:1131 — the root of trust must match every witness."""
        bad: List[Provider] = []
        for witness in list(self.witnesses):
            try:
                w_lb = witness.light_block(lb.height)
            except Exception:
                bad.append(witness)
                continue
            if w_lb.signed_header.header.hash() != lb.signed_header.header.hash():
                raise ErrLightClientAttack(
                    f"witness {witness.id()} has a different header at the "
                    f"root-of-trust height {lb.height}"
                )
        self._remove_witnesses(bad)

    def _detect_divergence(
        self, primary_trace: List[LightBlock], now: Timestamp
    ) -> None:
        """detector.go:28 detectDivergence — last traced header vs every
        witness; on conflict, examine and build attack evidence. Witnesses
        are collected and removed by identity AFTER the sweep — removal
        inside the loop (or by index) corrupts which witness gets dropped."""
        if not self.witnesses:
            return
        last = primary_trace[-1]
        bad: List[Provider] = []
        conflicts: List[Tuple[LightBlock, Provider]] = []
        for witness in list(self.witnesses):
            try:
                w_lb = witness.light_block(last.height)
            except (ErrLightBlockNotFound, ErrHeightTooHigh, ErrNoResponse):
                continue  # benign: witness is behind
            except Exception:
                bad.append(witness)
                continue
            if (
                w_lb.signed_header.header.hash()
                == last.signed_header.header.hash()
            ):
                continue
            conflicts.append((w_lb, witness))
        self._remove_witnesses(bad)
        for w_lb, witness in conflicts:
            self._handle_conflicting_headers(primary_trace, w_lb, witness, now)

    def _handle_conflicting_headers(
        self,
        primary_trace: List[LightBlock],
        challenging_block: LightBlock,
        witness: Provider,
        now: Timestamp,
    ) -> None:
        """detector.go:217 — decide which side is lying by verifying the
        witness's chain from the common trusted root; if the witness's
        block verifies, both chains are validly signed → an attack."""
        common, trusted_block = self._examine_against_trace(
            primary_trace, challenging_block, witness, now
        )
        if trusted_block is None:
            # witness couldn't prove its chain: drop it
            self.logger.info(
                "removing witness that could not prove its chain",
                witness=witness.id(),
            )
            self._remove_witnesses([witness])
            return
        # both sides verifiably signed conflicting blocks → evidence
        ev_against_primary = _new_attack_evidence(
            conflicted=primary_trace[-1],
            trusted=trusted_block,
            common=common,
        )
        witness.report_evidence(ev_against_primary)
        ev_against_witness = _new_attack_evidence(
            conflicted=challenging_block,
            trusted=primary_trace[-1],
            common=common,
        )
        self.primary.report_evidence(ev_against_witness)
        raise ErrLightClientAttack(
            f"header at height {challenging_block.height} diverges between "
            f"primary and witness {witness.id()}"
        )

    def _examine_against_trace(
        self,
        primary_trace: List[LightBlock],
        challenging_block: LightBlock,
        witness: Provider,
        now: Timestamp,
    ) -> Tuple[Optional[LightBlock], Optional[LightBlock]]:
        """detector.go:290 — find the last common (trusted) block in the
        trace, then try to verify the witness's conflicting block from it.
        Returns (common_block, verified_witness_block) or (_, None)."""
        common = primary_trace[0]
        for lb in primary_trace:
            try:
                w_lb = witness.light_block(lb.height)
            except Exception:
                return common, None
            if w_lb.signed_header.header.hash() == lb.signed_header.header.hash():
                common = lb
            else:
                break
        try:
            self._verify_skipping(witness, common, challenging_block, now)
        except Exception:
            return common, None
        return common, challenging_block

    def _remove_witnesses(self, witnesses: List[Provider]) -> None:
        for w in witnesses:
            try:
                self.witnesses.remove(w)
            except ValueError:
                pass  # already gone

    # -- store plumbing ---------------------------------------------------------

    def _update_trusted_light_block(self, lb: LightBlock) -> None:
        self.store.save_light_block(lb)
        if self.pruning_size and self.store.size() > self.pruning_size:
            self.store.prune(self.pruning_size)

    def _light_block_from_primary(self, height: int) -> LightBlock:
        lb = self.primary.light_block(height)
        lb.validate_basic(self.chain_id)
        return lb


def _new_attack_evidence(
    conflicted: LightBlock, trusted: LightBlock, common: LightBlock
) -> LightClientAttackEvidence:
    """detector.go:408 newLightClientAttackEvidence — lunatic attacks pin
    the common height; equivocation/amnesia use the conflicting height."""
    ev = LightClientAttackEvidence(conflicting_block=conflicted)
    if _conflicting_header_is_invalid(conflicted, trusted):
        ev.common_height = common.height
        ev.timestamp = common.signed_header.header.time
        ev.total_voting_power = common.validator_set.total_voting_power()
    else:
        ev.common_height = trusted.height
        ev.timestamp = trusted.signed_header.header.time
        ev.total_voting_power = trusted.validator_set.total_voting_power()
    ev.byzantine_validators = _byzantine_validators(
        ev, common.validator_set, trusted
    )
    return ev


def _conflicting_header_is_invalid(
    conflicted: LightBlock, trusted: LightBlock
) -> bool:
    """types/evidence.go ConflictingHeaderIsInvalid — a lunatic attack
    fabricates header fields that honest validators never produced."""
    t = trusted.signed_header.header
    c = conflicted.signed_header.header
    return not (
        t.validators_hash == c.validators_hash
        and t.next_validators_hash == c.next_validators_hash
        and t.consensus_hash == c.consensus_hash
        and t.app_hash == c.app_hash
        and t.last_results_hash == c.last_results_hash
    )


def _byzantine_validators(
    ev: LightClientAttackEvidence, common_vals, trusted: LightBlock
):
    """types/evidence.go GetByzantineValidators — lunatic: common-set
    validators who signed the conflicting block; equivocation: validators
    who signed both blocks."""
    out = []
    sh = ev.conflicting_block.signed_header
    if _conflicting_header_is_invalid(ev.conflicting_block, trusted):
        for i, sig in enumerate(sh.commit.signatures):
            if not sig.for_block():
                continue
            _, val = common_vals.get_by_address(sig.validator_address)
            if val is not None:
                out.append(val)
    elif trusted.signed_header.commit.round == sh.commit.round:
        trusted_by_addr = {
            s.validator_address: True
            for s in trusted.signed_header.commit.signatures
            if s.for_block()
        }
        for sig in sh.commit.signatures:
            if not sig.for_block():
                continue
            if sig.validator_address in trusted_by_addr:
                _, val = ev.conflicting_block.validator_set.get_by_address(
                    sig.validator_address
                )
                if val is not None:
                    out.append(val)
    out.sort(key=lambda v: v.address)
    return out
