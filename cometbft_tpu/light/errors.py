"""Light client error taxonomy (reference: light/errors.go)."""

from __future__ import annotations


class ErrOldHeaderExpired(Exception):
    """The trusted header is outside the trusting period."""

    def __init__(self, expired_at, now):
        super().__init__(
            f"old header has expired at {expired_at} (now: {now}); "
            f"can't verify"
        )
        self.expired_at = expired_at
        self.now = now


class ErrInvalidHeader(Exception):
    """The new header is invalid (wraps the reason)."""

    def __init__(self, reason):
        super().__init__(f"invalid header: {reason}")
        self.reason = reason


class ErrNewValSetCantBeTrusted(Exception):
    """< trustLevel of the trusted validator set signed the new header —
    bisection must insert a pivot (not a hard failure)."""

    def __init__(self, reason):
        super().__init__(
            f"can't trust new val set: {reason}"
        )
        self.reason = reason


class ErrVerificationFailed(Exception):
    """Bisection failed hard between two heights."""

    def __init__(self, from_height: int, to_height: int, reason):
        super().__init__(
            f"verify from #{from_height} to #{to_height} failed: {reason}"
        )
        self.from_height = from_height
        self.to_height = to_height
        self.reason = reason


class ErrLightClientAttack(Exception):
    """Conflicting, validly-signed headers detected — divergence between
    the primary and a witness."""


class ErrLightBlockNotFound(Exception):
    """Provider has no block at the requested height."""


class ErrNoResponse(Exception):
    """Provider did not respond."""


class ErrHeightTooHigh(Exception):
    """Requested height above the provider's chain tip."""
