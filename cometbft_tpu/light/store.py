"""Trusted light block store.

Reference: light/store/db — persisted trusted light blocks keyed by
height, with first/latest lookups and pruning to a target size.
"""

from __future__ import annotations

import struct
import threading
from typing import Optional

from cometbft_tpu.libs.db import DB
from cometbft_tpu.types.light_block import LightBlock

_PREFIX = b"lb/"


def _key(height: int) -> bytes:
    return _PREFIX + struct.pack(">Q", height)


class DBStore:
    def __init__(self, db: DB):
        self._db = db
        self._mtx = threading.Lock()

    def save_light_block(self, lb: LightBlock) -> None:
        if lb.height <= 0:
            raise ValueError("lightBlock.Height <= 0")
        with self._mtx:
            self._db.set_sync(_key(lb.height), lb.encode())

    def delete_light_block(self, height: int) -> None:
        with self._mtx:
            self._db.delete_sync(_key(height))

    def light_block(self, height: int) -> Optional[LightBlock]:
        if height <= 0:
            raise ValueError("height <= 0")
        raw = self._db.get(_key(height))
        if raw is None:
            return None
        return LightBlock.decode(raw)

    def light_block_before(self, height: int) -> Optional[LightBlock]:
        """The stored block with the greatest height < `height` (the Go
        store's LightBlockBefore) — one reverse scan, not an O(height)
        walk of point lookups."""
        for _, raw in self._db.reverse_iterator(_PREFIX, _key(height)):
            return LightBlock.decode(raw)
        return None

    def latest_light_block(self) -> Optional[LightBlock]:
        for _, raw in self._db.reverse_iterator(
            _PREFIX, _key(0xFFFFFFFFFFFFFFFF)
        ):
            return LightBlock.decode(raw)
        return None

    def latest_height(self) -> int:
        for key, _ in self._db.reverse_iterator(
            _PREFIX, _key(0xFFFFFFFFFFFFFFFF)
        ):
            return struct.unpack(">Q", key[len(_PREFIX):])[0]
        return 0

    def first_height(self) -> int:
        for key, _ in self._db.prefix_iterator(_PREFIX):
            return struct.unpack(">Q", key[len(_PREFIX):])[0]
        return 0

    def size(self) -> int:
        return sum(1 for _ in self._db.prefix_iterator(_PREFIX))

    def prune(self, target_size: int) -> None:
        """Remove oldest blocks until `target_size` remain (store/db.go).
        Keys iterate in ascending height order (big-endian), so the first
        `excess` keys are exactly the oldest blocks."""
        with self._mtx:
            keys = [key for key, _ in self._db.prefix_iterator(_PREFIX)]
            for key in keys[: max(len(keys) - target_size, 0)]:
                self._db.delete(key)
