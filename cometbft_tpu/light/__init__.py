"""Light client — header verification without executing the chain.

Reference: light/ — pure verifier (verifier.go), bisection client with a
trusted store and primary/witness providers (client.go), divergence
detection producing LightClientAttackEvidence (detector.go).
"""

from cometbft_tpu.light.client import Client, TrustOptions
from cometbft_tpu.light.errors import (
    ErrHeightTooHigh,
    ErrInvalidHeader,
    ErrLightBlockNotFound,
    ErrLightClientAttack,
    ErrNewValSetCantBeTrusted,
    ErrNoResponse,
    ErrOldHeaderExpired,
    ErrVerificationFailed,
)
from cometbft_tpu.light.provider import (
    BlockStoreProvider,
    MockProvider,
    Provider,
)
from cometbft_tpu.light.store import DBStore
from cometbft_tpu.light.verifier import (
    DEFAULT_TRUST_LEVEL,
    header_expired,
    validate_trust_level,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)

__all__ = [
    "BlockStoreProvider",
    "Client",
    "DBStore",
    "DEFAULT_TRUST_LEVEL",
    "ErrHeightTooHigh",
    "ErrInvalidHeader",
    "ErrLightBlockNotFound",
    "ErrLightClientAttack",
    "ErrNewValSetCantBeTrusted",
    "ErrNoResponse",
    "ErrOldHeaderExpired",
    "ErrVerificationFailed",
    "MockProvider",
    "Provider",
    "TrustOptions",
    "header_expired",
    "validate_trust_level",
    "verify",
    "verify_adjacent",
    "verify_backwards",
    "verify_non_adjacent",
]
