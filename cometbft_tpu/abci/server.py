"""ABCI socket server — the app side of an out-of-process connection.

Reference: abci/server/socket_server.go (listener + per-connection
read/dispatch/write loop over length-prefixed proto frames).
"""

from __future__ import annotations

import os
import socket
import threading
from typing import Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.application import Application, dispatch_request
from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.service import BaseService


class SocketServer(BaseService):
    def __init__(self, addr: str, app: Application):
        super().__init__("ABCIServer")
        self._addr = addr
        self._app = app
        self._app_mtx = threading.Lock()
        self._listener: Optional[socket.socket] = None
        self._conns = []

    @property
    def addr(self) -> str:
        return self._addr

    def on_start(self) -> None:
        if self._addr.startswith("unix://"):
            path = self._addr[len("unix://") :]
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._listener.bind(path)
        else:
            addr = self._addr
            if addr.startswith("tcp://"):
                addr = addr[len("tcp://") :]
            host, _, port = addr.rpartition(":")
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host or "127.0.0.1", int(port)))
            if int(port) == 0:
                self._addr = "tcp://%s:%d" % self._listener.getsockname()
        self._listener.listen(16)
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def on_stop(self) -> None:
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while self.is_running():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn: socket.socket) -> None:
        rfile = conn.makefile("rb")
        wfile = conn.makefile("wb")
        while self.is_running():
            try:
                data = protoio.read_delimited(rfile)
            except (OSError, EOFError, ValueError):
                return
            req = abci.Request.decode(data)
            with self._app_mtx:
                res = dispatch_request(self._app, req)
            try:
                protoio.write_delimited(wfile, res.encode())
                if req.kind == "flush":
                    wfile.flush()
            except OSError:
                return
