"""The canonical test app: a key-value store behind ABCI.

Reference behavior: abci/example/kvstore/kvstore.go (tx "key=value" or raw
bytes; app hash = 8-byte varint of the kv-pair count; /key and /hash query
paths) and persistent_kvstore.go (validator-set changes via
"val:<pubkey-b64>!<power>" txs, tracked through BeginBlock/EndBlock).
"""

from __future__ import annotations

import base64
import json
from typing import Dict, List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.application import BaseApplication
from cometbft_tpu.libs.db import DB, MemDB
from cometbft_tpu.proto.keys import PublicKeyProto

PROTOCOL_VERSION = 0x1

_STATE_KEY = b"stateKey"
_KV_PREFIX = b"kvPairKey:"
VALIDATOR_SET_CHANGE_PREFIX = "val:"
_VALIDATOR_PREFIX = b"val:"

CODE_TYPE_ENCODING_ERROR = 1
CODE_TYPE_BAD_NONCE = 2
CODE_TYPE_UNAUTHORIZED = 3


def _put_varint(n: int) -> bytes:
    """Go binary.PutVarint into an 8-byte buffer (zigzag varint, padded)."""
    from cometbft_tpu.libs.protoio import encode_varint_zigzag

    raw = encode_varint_zigzag(n)
    return raw + b"\x00" * (8 - len(raw))


class _State:
    def __init__(self, db: DB):
        self.db = db
        self.size = 0
        self.height = 0
        self.app_hash = b""
        raw = db.get(_STATE_KEY)
        if raw:
            data = json.loads(raw)
            self.size = data.get("size", 0)
            self.height = data.get("height", 0)
            self.app_hash = base64.b64decode(data.get("app_hash", ""))

    def save(self) -> None:
        self.db.set(
            _STATE_KEY,
            json.dumps(
                {
                    "size": self.size,
                    "height": self.height,
                    "app_hash": base64.b64encode(self.app_hash).decode(),
                }
            ).encode(),
        )


class KVStoreApplication(BaseApplication):
    def __init__(self, db: Optional[DB] = None):
        self.state = _State(db or MemDB())
        self.retain_blocks = 0  # > 0 → request pruning via RetainHeight

    def info(self, req):
        return abci.ResponseInfo(
            data=json.dumps({"size": self.state.size}),
            version="0.17.0",
            app_version=PROTOCOL_VERSION,
            last_block_height=self.state.height,
            last_block_app_hash=self.state.app_hash,
        )

    def check_tx(self, req):
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK, gas_wanted=1)

    def deliver_tx(self, req):
        parts = req.tx.split(b"=", 1)
        if len(parts) == 2:
            key, value = parts
        else:
            key, value = req.tx, req.tx
        existed = self.state.db.has(_KV_PREFIX + key)
        self.state.db.set(_KV_PREFIX + key, value)
        if not existed:
            self.state.size += 1
        events = [
            abci.Event(
                type="app",
                attributes=[
                    abci.EventAttribute(b"creator", b"Cosmoshi Netowoko", True),
                    abci.EventAttribute(b"key", key, True),
                    abci.EventAttribute(b"index_key", b"index is working", True),
                    abci.EventAttribute(b"noindex_key", b"index is working", False),
                ],
            )
        ]
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK, events=events)

    def commit(self):
        app_hash = _put_varint(self.state.size)
        self.state.app_hash = app_hash
        self.state.height += 1
        self.state.save()
        resp = abci.ResponseCommit(data=app_hash)
        if self.retain_blocks > 0 and self.state.height >= self.retain_blocks:
            resp.retain_height = self.state.height - self.retain_blocks + 1
        return resp

    def query(self, req):
        if req.path == "/hash":
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                value=str(self.state.height).encode(),
                height=self.state.height,
            )
        value = self.state.db.get(_KV_PREFIX + req.data)
        return abci.ResponseQuery(
            code=abci.CODE_TYPE_OK,
            log="exists" if value is not None else "does not exist",
            key=req.data,
            value=value or b"",
            height=self.state.height,
        )


class PersistentKVStoreApplication(KVStoreApplication):
    """kvstore + validator-set updates — the e2e/consensus test app.

    Validator txs: "val:<base64 ed25519 pubkey>!<power>". InitChain seeds
    the set; EndBlock returns accumulated updates; BeginBlock records
    byzantine validators by zeroing their power (reference:
    persistent_kvstore.go).
    """

    def __init__(self, db: Optional[DB] = None):
        super().__init__(db)
        self._val_updates: List[abci.ValidatorUpdate] = []
        self._val_addr_to_pubkey: Dict[bytes, PublicKeyProto] = {}
        self._load_validators()

    # -- validators ---------------------------------------------------------

    def _val_key(self, pubkey_bytes: bytes) -> bytes:
        return _VALIDATOR_PREFIX + base64.b64encode(pubkey_bytes)

    def _load_validators(self) -> None:
        from cometbft_tpu.crypto import ed25519

        for key, raw in self.state.db.prefix_iterator(_VALIDATOR_PREFIX):
            update = abci.ValidatorUpdate.decode(raw)
            pk = update.pub_key
            addr = ed25519.PubKeyEd25519(pk.data).address()
            self._val_addr_to_pubkey[addr] = pk

    def validators(self) -> List[abci.ValidatorUpdate]:
        out = []
        for _, raw in self.state.db.prefix_iterator(_VALIDATOR_PREFIX):
            out.append(abci.ValidatorUpdate.decode(raw))
        return out

    def update_validator(self, v: abci.ValidatorUpdate) -> abci.ResponseDeliverTx:
        from cometbft_tpu.crypto import ed25519

        pubkey_bytes = v.pub_key.data
        key = self._val_key(pubkey_bytes)
        addr = ed25519.PubKeyEd25519(pubkey_bytes).address()
        if v.power == 0:
            if not self.state.db.has(key):
                return abci.ResponseDeliverTx(
                    code=CODE_TYPE_UNAUTHORIZED,
                    log="Cannot remove non-existent validator",
                )
            self.state.db.delete(key)
            self._val_addr_to_pubkey.pop(addr, None)
        else:
            self.state.db.set(key, v.encode())
            self._val_addr_to_pubkey[addr] = v.pub_key
        self._val_updates.append(v)
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    @staticmethod
    def make_val_set_change_tx(pubkey_b64: str, power: int) -> bytes:
        return f"{VALIDATOR_SET_CHANGE_PREFIX}{pubkey_b64}!{power}".encode()

    def _exec_validator_tx(self, tx: bytes) -> abci.ResponseDeliverTx:
        body = tx[len(VALIDATOR_SET_CHANGE_PREFIX) :].decode()
        if "!" not in body:
            return abci.ResponseDeliverTx(
                code=CODE_TYPE_ENCODING_ERROR,
                log="Expected 'pubkey!power'",
            )
        pubkey_b64, power_str = body.rsplit("!", 1)
        try:
            pubkey = base64.b64decode(pubkey_b64)
            power = int(power_str)
        except Exception:
            return abci.ResponseDeliverTx(
                code=CODE_TYPE_ENCODING_ERROR, log="bad pubkey or power"
            )
        return self.update_validator(
            abci.ValidatorUpdate(PublicKeyProto("ed25519", pubkey), power)
        )

    # -- abci ---------------------------------------------------------------

    def init_chain(self, req):
        for v in req.validators:
            r = self.update_validator(v)
            if r.code != abci.CODE_TYPE_OK:
                raise ValueError(f"error updating validators: {r.log}")
        self._val_updates = []
        return abci.ResponseInitChain()

    def begin_block(self, req):
        self._val_updates = []
        for ev in req.byzantine_validators:
            if ev.type == abci.EVIDENCE_TYPE_DUPLICATE_VOTE:
                pk = self._val_addr_to_pubkey.get(ev.validator.address)
                if pk is not None:
                    self.update_validator(
                        abci.ValidatorUpdate(pk, ev.validator.power - 1)
                    )
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req):
        if req.tx.startswith(_VALIDATOR_PREFIX):
            return self._exec_validator_tx(req.tx)
        return super().deliver_tx(req)

    def check_tx(self, req):
        if req.tx.startswith(_VALIDATOR_PREFIX):
            body = req.tx[len(VALIDATOR_SET_CHANGE_PREFIX) :].decode(
                errors="replace"
            )
            if "!" not in body:
                return abci.ResponseCheckTx(
                    code=CODE_TYPE_ENCODING_ERROR, log="Expected 'pubkey!power'"
                )
        return super().check_tx(req)

    def end_block(self, req):
        return abci.ResponseEndBlock(validator_updates=list(self._val_updates))

    def query(self, req):
        if req.path == "/val":
            pk = self._val_addr_to_pubkey.get(req.data)
            if pk is None:
                return abci.ResponseQuery(code=abci.CODE_TYPE_OK, value=b"")
            return abci.ResponseQuery(
                code=abci.CODE_TYPE_OK,
                key=req.data,
                value=abci.ValidatorUpdate(pk, 0).encode(),
            )
        return super().query(req)


class SnapshotKVStoreApplication(PersistentKVStoreApplication):
    """kvstore + state-sync snapshots — the statesync test app.

    Reference model: test/e2e/app/{app.go,snapshots.go} — the app state is
    serialized to JSON at every `snapshot_interval`-th commit, chunks are
    fixed-size slices of that JSON, and restore concatenates the chunks and
    imports them wholesale (app.go:240-257). Snapshot hash = sha256 of the
    serialized state.
    """

    def __init__(
        self,
        db: Optional[DB] = None,
        snapshot_interval: int = 0,
        chunk_size: int = 1_000_000,
    ):
        super().__init__(db)
        self.snapshot_interval = snapshot_interval
        self.chunk_size = chunk_size
        self._snapshots: List[abci.Snapshot] = []
        self._snapshot_data: Dict[int, bytes] = {}  # height → serialized state
        self._restore_snapshot: Optional[abci.Snapshot] = None
        self._restore_chunks: List[bytes] = []

    # -- export / import ----------------------------------------------------

    def _export_state(self) -> bytes:
        pairs = {}
        for key, value in self.state.db.prefix_iterator(_KV_PREFIX):
            pairs[base64.b64encode(key[len(_KV_PREFIX):]).decode()] = (
                base64.b64encode(value).decode()
            )
        vals = {}
        for key, raw in self.state.db.prefix_iterator(_VALIDATOR_PREFIX):
            vals[key[len(_VALIDATOR_PREFIX):].decode()] = base64.b64encode(
                raw
            ).decode()
        return json.dumps(
            {
                "height": self.state.height,
                "size": self.state.size,
                "app_hash": base64.b64encode(self.state.app_hash).decode(),
                "pairs": pairs,
                "validators": vals,
            },
            sort_keys=True,
        ).encode()

    def _import_state(self, height: int, data: bytes) -> None:
        doc = json.loads(data)
        if doc["height"] != height:
            raise ValueError(
                f"snapshot height mismatch: {doc['height']} != {height}"
            )
        for key, value in doc["pairs"].items():
            self.state.db.set(
                _KV_PREFIX + base64.b64decode(key), base64.b64decode(value)
            )
        for key, raw in doc["validators"].items():
            self.state.db.set(
                _VALIDATOR_PREFIX + key.encode(), base64.b64decode(raw)
            )
        self.state.height = doc["height"]
        self.state.size = doc["size"]
        self.state.app_hash = base64.b64decode(doc["app_hash"])
        self.state.save()
        self._load_validators()

    # -- abci snapshot connection -------------------------------------------

    def commit(self):
        resp = super().commit()
        if (
            self.snapshot_interval > 0
            and self.state.height % self.snapshot_interval == 0
        ):
            import hashlib
            import math

            data = self._export_state()
            self._snapshot_data[self.state.height] = data
            self._snapshots.append(
                abci.Snapshot(
                    height=self.state.height,
                    format=1,
                    chunks=max(1, math.ceil(len(data) / self.chunk_size)),
                    hash=hashlib.sha256(data).digest(),
                )
            )
            # only the most recent snapshots are ever advertised
            # (statesync RECENT_SNAPSHOTS) — prune the rest
            from cometbft_tpu.statesync.snapshots import RECENT_SNAPSHOTS

            while len(self._snapshots) > RECENT_SNAPSHOTS:
                old = self._snapshots.pop(0)
                self._snapshot_data.pop(old.height, None)
        return resp

    def list_snapshots(self, req):
        return abci.ResponseListSnapshots(snapshots=list(self._snapshots))

    def load_snapshot_chunk(self, req):
        data = self._snapshot_data.get(req.height)
        if data is None or req.format != 1:
            return abci.ResponseLoadSnapshotChunk(chunk=b"")
        start = req.chunk * self.chunk_size
        return abci.ResponseLoadSnapshotChunk(
            chunk=data[start : start + self.chunk_size]
        )

    def offer_snapshot(self, req):
        if self._restore_snapshot is not None:
            # an abandoned partial restore (e.g. the syncer timed out on
            # chunks and moved to another snapshot) must not poison every
            # future offer — drop the stale attempt and take the new one
            self._restore_snapshot = None
            self._restore_chunks = []
        if req.snapshot is None or req.snapshot.format != 1:
            return abci.ResponseOfferSnapshot(
                result=abci.OFFER_SNAPSHOT_REJECT_FORMAT
            )
        self._restore_snapshot = req.snapshot
        self._restore_chunks = []
        return abci.ResponseOfferSnapshot(result=abci.OFFER_SNAPSHOT_ACCEPT)

    def apply_snapshot_chunk(self, req):
        if self._restore_snapshot is None:
            raise RuntimeError("no restore in progress")
        self._restore_chunks.append(req.chunk)
        if len(self._restore_chunks) == self._restore_snapshot.chunks:
            self._import_state(
                self._restore_snapshot.height, b"".join(self._restore_chunks)
            )
            self._restore_snapshot = None
            self._restore_chunks = []
        return abci.ResponseApplySnapshotChunk(
            result=abci.APPLY_CHUNK_ACCEPT
        )
