"""ABCI message types, wire-compatible with the reference.

Field numbers per /root/reference/proto/tendermint/abci/types.proto
(Request oneof :23-41, Response oneof :134-153, misc :330-415). Messages
are plain dataclasses with hand-rolled proto encode/decode over
libs.protoio — the same approach the rest of the wire layer uses (no
protoc dependency; layouts asserted against golden vectors in tests).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from cometbft_tpu.libs import protoio
from cometbft_tpu.proto.gogo import Timestamp, ZERO_TIME
from cometbft_tpu.proto.keys import PublicKeyProto

CODE_TYPE_OK = 0

# CheckTxType enum
CHECK_TX_TYPE_NEW = 0
CHECK_TX_TYPE_RECHECK = 1

# EvidenceType enum
EVIDENCE_TYPE_UNKNOWN = 0
EVIDENCE_TYPE_DUPLICATE_VOTE = 1
EVIDENCE_TYPE_LIGHT_CLIENT_ATTACK = 2

# ResponseOfferSnapshot.Result
OFFER_SNAPSHOT_UNKNOWN = 0
OFFER_SNAPSHOT_ACCEPT = 1
OFFER_SNAPSHOT_ABORT = 2
OFFER_SNAPSHOT_REJECT = 3
OFFER_SNAPSHOT_REJECT_FORMAT = 4
OFFER_SNAPSHOT_REJECT_SENDER = 5

# ResponseApplySnapshotChunk.Result
APPLY_CHUNK_UNKNOWN = 0
APPLY_CHUNK_ACCEPT = 1
APPLY_CHUNK_ABORT = 2
APPLY_CHUNK_RETRY = 3
APPLY_CHUNK_RETRY_SNAPSHOT = 4
APPLY_CHUNK_REJECT_SNAPSHOT = 5


def _decode_repeated(data: bytes, factory):
    out = []
    r = protoio.WireReader(data)
    while not r.at_end():
        f, wt = r.read_tag()
        if f == 1:
            out.append(factory(r.read_bytes()))
        else:
            r.skip(wt)
    return out


# --- misc -------------------------------------------------------------------


@dataclass
class EventAttribute:
    key: bytes = b""
    value: bytes = b""
    index: bool = False

    def encode(self) -> bytes:
        out = protoio.field_bytes(1, self.key) + protoio.field_bytes(2, self.value)
        if self.index:
            out += protoio.field_varint(3, 1)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "EventAttribute":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.key = r.read_bytes()
            elif f == 2:
                out.value = r.read_bytes()
            elif f == 3:
                out.index = bool(r.read_varint())
            else:
                r.skip(wt)
        return out


@dataclass
class Event:
    type: str = ""
    attributes: List[EventAttribute] = field(default_factory=list)

    def encode(self) -> bytes:
        out = protoio.field_string(1, self.type)
        for a in self.attributes:
            out += protoio.field_message(2, a.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Event":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.type = r.read_string()
            elif f == 2:
                out.attributes.append(EventAttribute.decode(r.read_bytes()))
            else:
                r.skip(wt)
        return out


def encode_events(events: List[Event], field_num: int) -> bytes:
    return b"".join(protoio.field_message(field_num, e.encode()) for e in events)


@dataclass
class Validator:
    """abci.Validator — address + power (no pubkey)."""

    address: bytes = b""
    power: int = 0

    def encode(self) -> bytes:
        return protoio.field_bytes(1, self.address) + protoio.field_varint(
            3, self.power
        )

    @classmethod
    def decode(cls, data: bytes) -> "Validator":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.address = r.read_bytes()
            elif f == 3:
                out.power = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class ValidatorUpdate:
    pub_key: PublicKeyProto = field(
        default_factory=lambda: PublicKeyProto("ed25519", b"")
    )
    power: int = 0

    def encode(self) -> bytes:
        return protoio.field_message(1, self.pub_key.encode()) + protoio.field_varint(
            2, self.power
        )

    @classmethod
    def decode(cls, data: bytes) -> "ValidatorUpdate":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.pub_key = PublicKeyProto.decode(r.read_bytes())
            elif f == 2:
                out.power = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class VoteInfo:
    validator: Validator = field(default_factory=Validator)
    signed_last_block: bool = False

    def encode(self) -> bytes:
        out = protoio.field_message(1, self.validator.encode())
        if self.signed_last_block:
            out += protoio.field_varint(2, 1)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "VoteInfo":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.validator = Validator.decode(r.read_bytes())
            elif f == 2:
                out.signed_last_block = bool(r.read_varint())
            else:
                r.skip(wt)
        return out


@dataclass
class LastCommitInfo:
    round: int = 0
    votes: List[VoteInfo] = field(default_factory=list)

    def encode(self) -> bytes:
        out = b""
        if self.round:
            out += protoio.field_varint(1, self.round)
        for v in self.votes:
            out += protoio.field_message(2, v.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "LastCommitInfo":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.round = r.read_varint()
            elif f == 2:
                out.votes.append(VoteInfo.decode(r.read_bytes()))
            else:
                r.skip(wt)
        return out


@dataclass
class Misbehavior:
    """abci.Evidence (types.proto:384-398)."""

    type: int = EVIDENCE_TYPE_UNKNOWN
    validator: Validator = field(default_factory=Validator)
    height: int = 0
    time: Timestamp = ZERO_TIME
    total_voting_power: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.type:
            out += protoio.field_varint(1, self.type)
        out += protoio.field_message(2, self.validator.encode())
        if self.height:
            out += protoio.field_varint(3, self.height)
        out += protoio.field_message(4, self.time.encode())
        if self.total_voting_power:
            out += protoio.field_varint(5, self.total_voting_power)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Misbehavior":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.type = r.read_varint()
            elif f == 2:
                out.validator = Validator.decode(r.read_bytes())
            elif f == 3:
                out.height = r.read_varint()
            elif f == 4:
                out.time = Timestamp.decode(r.read_bytes())
            elif f == 5:
                out.total_voting_power = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class Snapshot:
    height: int = 0
    format: int = 0
    chunks: int = 0
    hash: bytes = b""
    metadata: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.format:
            out += protoio.field_varint(2, self.format)
        if self.chunks:
            out += protoio.field_varint(3, self.chunks)
        out += protoio.field_bytes(4, self.hash)
        out += protoio.field_bytes(5, self.metadata)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "Snapshot":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.format = r.read_varint()
            elif f == 3:
                out.chunks = r.read_varint()
            elif f == 4:
                out.hash = r.read_bytes()
            elif f == 5:
                out.metadata = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class RollappParams:
    """Fork-specific (types.proto:400-403)."""

    da: str = ""
    drs_version: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.da:
            out += protoio.field_string(1, self.da)
        if self.drs_version:
            out += protoio.field_varint(2, self.drs_version)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RollappParams":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.da = r.read_string()
            elif f == 2:
                out.drs_version = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class TxResult:
    """abci.TxResult — indexing payload (types.proto:348-354)."""

    height: int = 0
    index: int = 0
    tx: bytes = b""
    result: "ResponseDeliverTx" = None  # type: ignore[assignment]

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.index:
            out += protoio.field_varint(2, self.index)
        out += protoio.field_bytes(3, self.tx)
        res = self.result if self.result is not None else ResponseDeliverTx()
        out += protoio.field_message(4, res.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "TxResult":
        r = protoio.WireReader(data)
        out = cls(result=ResponseDeliverTx())
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.index = r.read_varint()
            elif f == 3:
                out.tx = r.read_bytes()
            elif f == 4:
                out.result = ResponseDeliverTx.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


# --- ABCI consensus params (distinct from types.ConsensusParams:
#     BlockParams here has no time_iota_ms — types.proto:310-323) -----------


@dataclass
class AbciBlockParams:
    max_bytes: int = 0
    max_gas: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.max_bytes:
            out += protoio.field_varint(1, self.max_bytes)
        if self.max_gas:
            out += protoio.field_varint(2, self.max_gas)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "AbciBlockParams":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.max_bytes = r.read_varint()
            elif f == 2:
                out.max_gas = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class AbciConsensusParams:
    """abci.ConsensusParams — every section optional (nullable)."""

    block: Optional[AbciBlockParams] = None
    evidence: Optional[object] = None  # types.EvidenceParams
    validator: Optional[object] = None  # types.ValidatorParams
    version: Optional[object] = None  # types.VersionParams

    def encode(self) -> bytes:
        out = b""
        if self.block is not None:
            out += protoio.field_message(1, self.block.encode())
        if self.evidence is not None:
            out += protoio.field_message(2, self.evidence.encode())
        if self.validator is not None:
            out += protoio.field_message(3, self.validator.encode())
        if self.version is not None:
            out += protoio.field_message(4, self.version.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "AbciConsensusParams":
        from cometbft_tpu.types.params import (
            EvidenceParams,
            ValidatorParams,
            VersionParams,
        )

        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.block = AbciBlockParams.decode(r.read_bytes())
            elif f == 2:
                out.evidence = EvidenceParams.decode(r.read_bytes())
            elif f == 3:
                out.validator = ValidatorParams.decode(r.read_bytes())
            elif f == 4:
                out.version = VersionParams.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


# --- requests ---------------------------------------------------------------


@dataclass
class RequestEcho:
    message: str = ""

    def encode(self) -> bytes:
        return protoio.field_string(1, self.message)

    @classmethod
    def decode(cls, data: bytes) -> "RequestEcho":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.message = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestFlush:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestFlush":
        return cls()


@dataclass
class RequestInfo:
    version: str = ""
    block_version: int = 0
    p2p_version: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.version:
            out += protoio.field_string(1, self.version)
        if self.block_version:
            out += protoio.field_varint(2, self.block_version)
        if self.p2p_version:
            out += protoio.field_varint(3, self.p2p_version)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestInfo":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.version = r.read_string()
            elif f == 2:
                out.block_version = r.read_varint()
            elif f == 3:
                out.p2p_version = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestSetOption:
    key: str = ""
    value: str = ""

    def encode(self) -> bytes:
        out = b""
        if self.key:
            out += protoio.field_string(1, self.key)
        if self.value:
            out += protoio.field_string(2, self.value)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestSetOption":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.key = r.read_string()
            elif f == 2:
                out.value = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestInitChain:
    time: Timestamp = ZERO_TIME
    chain_id: str = ""
    consensus_params: Optional[AbciConsensusParams] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_state_bytes: bytes = b""
    initial_height: int = 0
    genesis_checksum: str = ""  # fork extension (types.proto:69)

    def encode(self) -> bytes:
        out = protoio.field_message(1, self.time.encode())
        if self.chain_id:
            out += protoio.field_string(2, self.chain_id)
        if self.consensus_params is not None:
            out += protoio.field_message(3, self.consensus_params.encode())
        for v in self.validators:
            out += protoio.field_message(4, v.encode())
        out += protoio.field_bytes(5, self.app_state_bytes)
        if self.initial_height:
            out += protoio.field_varint(6, self.initial_height)
        if self.genesis_checksum:
            out += protoio.field_string(7, self.genesis_checksum)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestInitChain":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.time = Timestamp.decode(r.read_bytes())
            elif f == 2:
                out.chain_id = r.read_string()
            elif f == 3:
                out.consensus_params = AbciConsensusParams.decode(r.read_bytes())
            elif f == 4:
                out.validators.append(ValidatorUpdate.decode(r.read_bytes()))
            elif f == 5:
                out.app_state_bytes = r.read_bytes()
            elif f == 6:
                out.initial_height = r.read_varint()
            elif f == 7:
                out.genesis_checksum = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestQuery:
    data: bytes = b""
    path: str = ""
    height: int = 0
    prove: bool = False

    def encode(self) -> bytes:
        out = protoio.field_bytes(1, self.data)
        if self.path:
            out += protoio.field_string(2, self.path)
        if self.height:
            out += protoio.field_varint(3, self.height)
        if self.prove:
            out += protoio.field_varint(4, 1)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestQuery":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.data = r.read_bytes()
            elif f == 2:
                out.path = r.read_string()
            elif f == 3:
                out.height = r.read_varint()
            elif f == 4:
                out.prove = bool(r.read_varint())
            else:
                r.skip(wt)
        return out


@dataclass
class RequestBeginBlock:
    hash: bytes = b""
    header: object = None  # types.Header (non-null on the wire)
    last_commit_info: LastCommitInfo = field(default_factory=LastCommitInfo)
    byzantine_validators: List[Misbehavior] = field(default_factory=list)

    def encode(self) -> bytes:
        from cometbft_tpu.types.block import Header

        out = protoio.field_bytes(1, self.hash)
        hdr = self.header if self.header is not None else Header()
        out += protoio.field_message(2, hdr.encode())
        out += protoio.field_message(3, self.last_commit_info.encode())
        for e in self.byzantine_validators:
            out += protoio.field_message(4, e.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestBeginBlock":
        from cometbft_tpu.types.block import Header

        r = protoio.WireReader(data)
        out = cls(header=Header())
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.hash = r.read_bytes()
            elif f == 2:
                out.header = Header.decode(r.read_bytes())
            elif f == 3:
                out.last_commit_info = LastCommitInfo.decode(r.read_bytes())
            elif f == 4:
                out.byzantine_validators.append(Misbehavior.decode(r.read_bytes()))
            else:
                r.skip(wt)
        return out


@dataclass
class RequestCheckTx:
    tx: bytes = b""
    type: int = CHECK_TX_TYPE_NEW

    def encode(self) -> bytes:
        out = protoio.field_bytes(1, self.tx)
        if self.type:
            out += protoio.field_varint(2, self.type)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestCheckTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.tx = r.read_bytes()
            elif f == 2:
                out.type = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestDeliverTx:
    tx: bytes = b""

    def encode(self) -> bytes:
        return protoio.field_bytes(1, self.tx)

    @classmethod
    def decode(cls, data: bytes) -> "RequestDeliverTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.tx = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestEndBlock:
    height: int = 0

    def encode(self) -> bytes:
        return protoio.field_varint(1, self.height) if self.height else b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestEndBlock":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestCommit:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestCommit":
        return cls()


@dataclass
class RequestListSnapshots:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "RequestListSnapshots":
        return cls()


@dataclass
class RequestOfferSnapshot:
    snapshot: Optional[Snapshot] = None
    app_hash: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.snapshot is not None:
            out += protoio.field_message(1, self.snapshot.encode())
        out += protoio.field_bytes(2, self.app_hash)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestOfferSnapshot":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.snapshot = Snapshot.decode(r.read_bytes())
            elif f == 2:
                out.app_hash = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestLoadSnapshotChunk:
    height: int = 0
    format: int = 0
    chunk: int = 0

    def encode(self) -> bytes:
        out = b""
        if self.height:
            out += protoio.field_varint(1, self.height)
        if self.format:
            out += protoio.field_varint(2, self.format)
        if self.chunk:
            out += protoio.field_varint(3, self.chunk)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestLoadSnapshotChunk":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.height = r.read_varint()
            elif f == 2:
                out.format = r.read_varint()
            elif f == 3:
                out.chunk = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class RequestApplySnapshotChunk:
    index: int = 0
    chunk: bytes = b""
    sender: str = ""

    def encode(self) -> bytes:
        out = b""
        if self.index:
            out += protoio.field_varint(1, self.index)
        out += protoio.field_bytes(2, self.chunk)
        if self.sender:
            out += protoio.field_string(3, self.sender)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "RequestApplySnapshotChunk":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.index = r.read_varint()
            elif f == 2:
                out.chunk = r.read_bytes()
            elif f == 3:
                out.sender = r.read_string()
            else:
                r.skip(wt)
        return out


# --- responses --------------------------------------------------------------


@dataclass
class ResponseException:
    error: str = ""

    def encode(self) -> bytes:
        return protoio.field_string(1, self.error) if self.error else b""

    @classmethod
    def decode(cls, data: bytes) -> "ResponseException":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.error = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseEcho:
    message: str = ""

    def encode(self) -> bytes:
        return protoio.field_string(1, self.message) if self.message else b""

    @classmethod
    def decode(cls, data: bytes) -> "ResponseEcho":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.message = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseFlush:
    def encode(self) -> bytes:
        return b""

    @classmethod
    def decode(cls, data: bytes) -> "ResponseFlush":
        return cls()


@dataclass
class ResponseInfo:
    data: str = ""
    version: str = ""
    app_version: int = 0
    last_block_height: int = 0
    last_block_app_hash: bytes = b""

    def encode(self) -> bytes:
        out = b""
        if self.data:
            out += protoio.field_string(1, self.data)
        if self.version:
            out += protoio.field_string(2, self.version)
        if self.app_version:
            out += protoio.field_varint(3, self.app_version)
        if self.last_block_height:
            out += protoio.field_varint(4, self.last_block_height)
        out += protoio.field_bytes(5, self.last_block_app_hash)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseInfo":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.data = r.read_string()
            elif f == 2:
                out.version = r.read_string()
            elif f == 3:
                out.app_version = r.read_varint()
            elif f == 4:
                out.last_block_height = r.read_varint()
            elif f == 5:
                out.last_block_app_hash = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseSetOption:
    code: int = 0
    log: str = ""
    info: str = ""

    def encode(self) -> bytes:
        out = b""
        if self.code:
            out += protoio.field_varint(1, self.code)
        if self.log:
            out += protoio.field_string(3, self.log)
        if self.info:
            out += protoio.field_string(4, self.info)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseSetOption":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.code = r.read_varint()
            elif f == 3:
                out.log = r.read_string()
            elif f == 4:
                out.info = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseInitChain:
    consensus_params: Optional[AbciConsensusParams] = None
    validators: List[ValidatorUpdate] = field(default_factory=list)
    app_hash: bytes = b""
    rollapp_params: Optional[RollappParams] = None  # fork extension
    genesis_bridge_data_bytes: bytes = b""  # fork extension

    def encode(self) -> bytes:
        out = b""
        if self.consensus_params is not None:
            out += protoio.field_message(1, self.consensus_params.encode())
        for v in self.validators:
            out += protoio.field_message(2, v.encode())
        out += protoio.field_bytes(3, self.app_hash)
        if self.rollapp_params is not None:
            out += protoio.field_message(4, self.rollapp_params.encode())
        out += protoio.field_bytes(5, self.genesis_bridge_data_bytes)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseInitChain":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.consensus_params = AbciConsensusParams.decode(r.read_bytes())
            elif f == 2:
                out.validators.append(ValidatorUpdate.decode(r.read_bytes()))
            elif f == 3:
                out.app_hash = r.read_bytes()
            elif f == 4:
                out.rollapp_params = RollappParams.decode(r.read_bytes())
            elif f == 5:
                out.genesis_bridge_data_bytes = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseQuery:
    code: int = 0
    log: str = ""
    info: str = ""
    index: int = 0
    key: bytes = b""
    value: bytes = b""
    proof_ops: Optional[object] = None  # crypto.ProofOps
    height: int = 0
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        out = b""
        if self.code:
            out += protoio.field_varint(1, self.code)
        if self.log:
            out += protoio.field_string(3, self.log)
        if self.info:
            out += protoio.field_string(4, self.info)
        if self.index:
            out += protoio.field_varint(5, self.index)
        out += protoio.field_bytes(6, self.key)
        out += protoio.field_bytes(7, self.value)
        if self.proof_ops is not None:
            out += protoio.field_message(8, self.proof_ops.encode())
        if self.height:
            out += protoio.field_varint(9, self.height)
        if self.codespace:
            out += protoio.field_string(10, self.codespace)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseQuery":
        from cometbft_tpu.crypto.merkle import ProofOps

        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.code = r.read_varint()
            elif f == 3:
                out.log = r.read_string()
            elif f == 4:
                out.info = r.read_string()
            elif f == 5:
                out.index = r.read_varint()
            elif f == 6:
                out.key = r.read_bytes()
            elif f == 7:
                out.value = r.read_bytes()
            elif f == 8:
                out.proof_ops = ProofOps.decode(r.read_bytes())
            elif f == 9:
                out.height = r.read_varint()
            elif f == 10:
                out.codespace = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseBeginBlock:
    events: List[Event] = field(default_factory=list)

    def encode(self) -> bytes:
        return encode_events(self.events, 1)

    @classmethod
    def decode(cls, data: bytes) -> "ResponseBeginBlock":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.events.append(Event.decode(r.read_bytes()))
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseCheckTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""
    sender: str = ""
    priority: int = 0
    mempool_error: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        out = b""
        if self.code:
            out += protoio.field_varint(1, self.code)
        out += protoio.field_bytes(2, self.data)
        if self.log:
            out += protoio.field_string(3, self.log)
        if self.info:
            out += protoio.field_string(4, self.info)
        if self.gas_wanted:
            out += protoio.field_varint(5, self.gas_wanted)
        if self.gas_used:
            out += protoio.field_varint(6, self.gas_used)
        out += encode_events(self.events, 7)
        if self.codespace:
            out += protoio.field_string(8, self.codespace)
        if self.sender:
            out += protoio.field_string(9, self.sender)
        if self.priority:
            out += protoio.field_varint(10, self.priority)
        if self.mempool_error:
            out += protoio.field_string(11, self.mempool_error)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseCheckTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.code = r.read_varint()
            elif f == 2:
                out.data = r.read_bytes()
            elif f == 3:
                out.log = r.read_string()
            elif f == 4:
                out.info = r.read_string()
            elif f == 5:
                out.gas_wanted = r.read_varint()
            elif f == 6:
                out.gas_used = r.read_varint()
            elif f == 7:
                out.events.append(Event.decode(r.read_bytes()))
            elif f == 8:
                out.codespace = r.read_string()
            elif f == 9:
                out.sender = r.read_string()
            elif f == 10:
                out.priority = r.read_varint()
            elif f == 11:
                out.mempool_error = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseDeliverTx:
    code: int = 0
    data: bytes = b""
    log: str = ""
    info: str = ""
    gas_wanted: int = 0
    gas_used: int = 0
    events: List[Event] = field(default_factory=list)
    codespace: str = ""

    def is_ok(self) -> bool:
        return self.code == CODE_TYPE_OK

    def encode(self) -> bytes:
        out = b""
        if self.code:
            out += protoio.field_varint(1, self.code)
        out += protoio.field_bytes(2, self.data)
        if self.log:
            out += protoio.field_string(3, self.log)
        if self.info:
            out += protoio.field_string(4, self.info)
        if self.gas_wanted:
            out += protoio.field_varint(5, self.gas_wanted)
        if self.gas_used:
            out += protoio.field_varint(6, self.gas_used)
        out += encode_events(self.events, 7)
        if self.codespace:
            out += protoio.field_string(8, self.codespace)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseDeliverTx":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.code = r.read_varint()
            elif f == 2:
                out.data = r.read_bytes()
            elif f == 3:
                out.log = r.read_string()
            elif f == 4:
                out.info = r.read_string()
            elif f == 5:
                out.gas_wanted = r.read_varint()
            elif f == 6:
                out.gas_used = r.read_varint()
            elif f == 7:
                out.events.append(Event.decode(r.read_bytes()))
            elif f == 8:
                out.codespace = r.read_string()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseEndBlock:
    validator_updates: List[ValidatorUpdate] = field(default_factory=list)
    consensus_param_updates: Optional[AbciConsensusParams] = None
    events: List[Event] = field(default_factory=list)
    rollapp_param_updates: Optional[RollappParams] = None  # fork extension

    def encode(self) -> bytes:
        out = b""
        for v in self.validator_updates:
            out += protoio.field_message(1, v.encode())
        if self.consensus_param_updates is not None:
            out += protoio.field_message(2, self.consensus_param_updates.encode())
        out += encode_events(self.events, 3)
        if self.rollapp_param_updates is not None:
            out += protoio.field_message(4, self.rollapp_param_updates.encode())
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseEndBlock":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.validator_updates.append(ValidatorUpdate.decode(r.read_bytes()))
            elif f == 2:
                out.consensus_param_updates = AbciConsensusParams.decode(
                    r.read_bytes()
                )
            elif f == 3:
                out.events.append(Event.decode(r.read_bytes()))
            elif f == 4:
                out.rollapp_param_updates = RollappParams.decode(r.read_bytes())
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseCommit:
    data: bytes = b""  # the new app hash (field 2; field 1 reserved)
    retain_height: int = 0

    def encode(self) -> bytes:
        out = protoio.field_bytes(2, self.data)
        if self.retain_height:
            out += protoio.field_varint(3, self.retain_height)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseCommit":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 2:
                out.data = r.read_bytes()
            elif f == 3:
                out.retain_height = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseListSnapshots:
    snapshots: List[Snapshot] = field(default_factory=list)

    def encode(self) -> bytes:
        return b"".join(
            protoio.field_message(1, s.encode()) for s in self.snapshots
        )

    @classmethod
    def decode(cls, data: bytes) -> "ResponseListSnapshots":
        return cls(_decode_repeated(data, Snapshot.decode))


@dataclass
class ResponseOfferSnapshot:
    result: int = OFFER_SNAPSHOT_UNKNOWN

    def encode(self) -> bytes:
        return protoio.field_varint(1, self.result) if self.result else b""

    @classmethod
    def decode(cls, data: bytes) -> "ResponseOfferSnapshot":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.result = r.read_varint()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseLoadSnapshotChunk:
    chunk: bytes = b""

    def encode(self) -> bytes:
        return protoio.field_bytes(1, self.chunk)

    @classmethod
    def decode(cls, data: bytes) -> "ResponseLoadSnapshotChunk":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.chunk = r.read_bytes()
            else:
                r.skip(wt)
        return out


@dataclass
class ResponseApplySnapshotChunk:
    result: int = APPLY_CHUNK_UNKNOWN
    refetch_chunks: List[int] = field(default_factory=list)
    reject_senders: List[str] = field(default_factory=list)

    def encode(self) -> bytes:
        out = b""
        if self.result:
            out += protoio.field_varint(1, self.result)
        for c in self.refetch_chunks:
            out += protoio.field_varint(2, c)
        for s in self.reject_senders:
            out += protoio.field_string(3, s)
        return out

    @classmethod
    def decode(cls, data: bytes) -> "ResponseApplySnapshotChunk":
        r = protoio.WireReader(data)
        out = cls()
        while not r.at_end():
            f, wt = r.read_tag()
            if f == 1:
                out.result = r.read_varint()
            elif f == 2:
                out.refetch_chunks.append(r.read_varint())
            elif f == 3:
                out.reject_senders.append(r.read_string())
            else:
                r.skip(wt)
        return out


# --- Request / Response oneof wrappers -------------------------------------

_REQUEST_FIELDS = {
    "echo": (1, RequestEcho),
    "flush": (2, RequestFlush),
    "info": (3, RequestInfo),
    "set_option": (4, RequestSetOption),
    "init_chain": (5, RequestInitChain),
    "query": (6, RequestQuery),
    "begin_block": (7, RequestBeginBlock),
    "check_tx": (8, RequestCheckTx),
    "deliver_tx": (9, RequestDeliverTx),
    "end_block": (10, RequestEndBlock),
    "commit": (11, RequestCommit),
    "list_snapshots": (12, RequestListSnapshots),
    "offer_snapshot": (13, RequestOfferSnapshot),
    "load_snapshot_chunk": (14, RequestLoadSnapshotChunk),
    "apply_snapshot_chunk": (15, RequestApplySnapshotChunk),
}

_RESPONSE_FIELDS = {
    "exception": (1, ResponseException),
    "echo": (2, ResponseEcho),
    "flush": (3, ResponseFlush),
    "info": (4, ResponseInfo),
    "set_option": (5, ResponseSetOption),
    "init_chain": (6, ResponseInitChain),
    "query": (7, ResponseQuery),
    "begin_block": (8, ResponseBeginBlock),
    "check_tx": (9, ResponseCheckTx),
    "deliver_tx": (10, ResponseDeliverTx),
    "end_block": (11, ResponseEndBlock),
    "commit": (12, ResponseCommit),
    "list_snapshots": (13, ResponseListSnapshots),
    "offer_snapshot": (14, ResponseOfferSnapshot),
    "load_snapshot_chunk": (15, ResponseLoadSnapshotChunk),
    "apply_snapshot_chunk": (16, ResponseApplySnapshotChunk),
}


class _Oneof:
    """Request/Response envelope: exactly one (kind, value) pair."""

    _FIELDS: dict = {}

    def __init__(self, kind: str, value):
        if kind not in self._FIELDS:
            raise ValueError(f"unknown {type(self).__name__} kind {kind!r}")
        self.kind = kind
        self.value = value

    def encode(self) -> bytes:
        num, _ = self._FIELDS[self.kind]
        return protoio.field_message(num, self.value.encode())

    @classmethod
    def decode(cls, data: bytes) -> "_Oneof":
        by_num = {num: (name, typ) for name, (num, typ) in cls._FIELDS.items()}
        r = protoio.WireReader(data)
        result = None
        while not r.at_end():
            f, wt = r.read_tag()
            if f in by_num:
                name, typ = by_num[f]
                result = cls(name, typ.decode(r.read_bytes()))
            else:
                r.skip(wt)
        if result is None:
            raise ValueError(f"empty {cls.__name__}")
        return result

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.kind}, {self.value!r})"


class Request(_Oneof):
    _FIELDS = _REQUEST_FIELDS


class Response(_Oneof):
    _FIELDS = _RESPONSE_FIELDS


# The reference names the misbehavior message `abci.Evidence`
# (types.proto:384); keep that name available alongside the clearer one.
Evidence = Misbehavior
