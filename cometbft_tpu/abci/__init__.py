"""ABCI — the application boundary.

Reference: abci/ (types, client, server, examples) + proxy/. The protocol
is v0.34 ABCI (Info/CheckTx/BeginBlock/DeliverTx/EndBlock/Commit +
snapshots) over an in-process client or a length-prefixed proto socket.
This fork's proto additions (RollappParams, consensus_messages,
genesis_checksum — proto/tendermint/abci/types.proto) are carried as
optional fields for wire parity.
"""

from cometbft_tpu.abci import types  # noqa: F401
