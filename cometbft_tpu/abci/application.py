"""The Application interface every ABCI app implements.

Reference: abci/types/application.go:11-32 (Application) and :35
(BaseApplication — the no-op base). One method per ABCI request; consensus
drives Info/InitChain/BeginBlock/DeliverTx/EndBlock/Commit, the mempool
drives CheckTx, RPC drives Query, statesync drives the snapshot calls.
"""

from __future__ import annotations

from cometbft_tpu.abci import types as abci


class Application:
    # Info/Query connection
    def info(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        raise NotImplementedError

    def set_option(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        raise NotImplementedError

    def query(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        raise NotImplementedError

    # Mempool connection
    def check_tx(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        raise NotImplementedError

    # Consensus connection
    def init_chain(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        raise NotImplementedError

    def begin_block(self, req: abci.RequestBeginBlock) -> abci.ResponseBeginBlock:
        raise NotImplementedError

    def deliver_tx(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        raise NotImplementedError

    def end_block(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        raise NotImplementedError

    def commit(self) -> abci.ResponseCommit:
        raise NotImplementedError

    # State-sync connection
    def list_snapshots(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots:
        raise NotImplementedError

    def offer_snapshot(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        raise NotImplementedError

    def load_snapshot_chunk(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        raise NotImplementedError

    def apply_snapshot_chunk(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        raise NotImplementedError


class BaseApplication(Application):
    """Returns empty/OK responses for everything — apps override a subset."""

    def info(self, req):
        return abci.ResponseInfo()

    def set_option(self, req):
        return abci.ResponseSetOption()

    def query(self, req):
        return abci.ResponseQuery(code=abci.CODE_TYPE_OK)

    def check_tx(self, req):
        return abci.ResponseCheckTx(code=abci.CODE_TYPE_OK)

    def init_chain(self, req):
        return abci.ResponseInitChain()

    def begin_block(self, req):
        return abci.ResponseBeginBlock()

    def deliver_tx(self, req):
        return abci.ResponseDeliverTx(code=abci.CODE_TYPE_OK)

    def end_block(self, req):
        return abci.ResponseEndBlock()

    def commit(self):
        return abci.ResponseCommit()

    def list_snapshots(self, req):
        return abci.ResponseListSnapshots()

    def offer_snapshot(self, req):
        return abci.ResponseOfferSnapshot()

    def load_snapshot_chunk(self, req):
        return abci.ResponseLoadSnapshotChunk()

    def apply_snapshot_chunk(self, req):
        return abci.ResponseApplySnapshotChunk()


def dispatch_request(app: Application, req: abci.Request) -> abci.Response:
    """Route one Request envelope to the app → Response envelope (the shared
    core of the local client and the socket server)."""
    kind, value = req.kind, req.value
    try:
        if kind == "echo":
            return abci.Response("echo", abci.ResponseEcho(value.message))
        if kind == "flush":
            return abci.Response("flush", abci.ResponseFlush())
        if kind == "info":
            return abci.Response("info", app.info(value))
        if kind == "set_option":
            return abci.Response("set_option", app.set_option(value))
        if kind == "init_chain":
            return abci.Response("init_chain", app.init_chain(value))
        if kind == "query":
            return abci.Response("query", app.query(value))
        if kind == "begin_block":
            return abci.Response("begin_block", app.begin_block(value))
        if kind == "check_tx":
            return abci.Response("check_tx", app.check_tx(value))
        if kind == "deliver_tx":
            return abci.Response("deliver_tx", app.deliver_tx(value))
        if kind == "end_block":
            return abci.Response("end_block", app.end_block(value))
        if kind == "commit":
            return abci.Response("commit", app.commit())
        if kind == "list_snapshots":
            return abci.Response("list_snapshots", app.list_snapshots(value))
        if kind == "offer_snapshot":
            return abci.Response("offer_snapshot", app.offer_snapshot(value))
        if kind == "load_snapshot_chunk":
            return abci.Response(
                "load_snapshot_chunk", app.load_snapshot_chunk(value)
            )
        if kind == "apply_snapshot_chunk":
            return abci.Response(
                "apply_snapshot_chunk", app.apply_snapshot_chunk(value)
            )
        return abci.Response("exception", abci.ResponseException("unknown request"))
    except Exception as e:  # app panics become ResponseException on the wire
        return abci.Response("exception", abci.ResponseException(str(e)))
