"""ABCI over gRPC — the reference's alternative out-of-process transport.

Reference: abci/client/grpc_client.go + abci/server/grpc_server.go,
service tendermint.abci.ABCIApplication (types.proto:418-435). Method
frames are the SAME hand-rolled protobuf codecs the socket transport
uses; gRPC is driven through its generic (method-name → bytes handler)
API, so no generated stubs are needed and the wire format stays
identical to a protoc build.
"""

from __future__ import annotations

import threading
from typing import Optional

import grpc

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.application import Application
from cometbft_tpu.abci.client import Client, ReqRes
from cometbft_tpu.libs.service import BaseService

_SERVICE = "tendermint.abci.ABCIApplication"

# gRPC method name → (request kind, request class)
_METHODS = {
    "Echo": ("echo", abci.RequestEcho),
    "Flush": ("flush", abci.RequestFlush),
    "Info": ("info", abci.RequestInfo),
    "SetOption": ("set_option", abci.RequestSetOption),
    "DeliverTx": ("deliver_tx", abci.RequestDeliverTx),
    "CheckTx": ("check_tx", abci.RequestCheckTx),
    "Query": ("query", abci.RequestQuery),
    "Commit": ("commit", abci.RequestCommit),
    "InitChain": ("init_chain", abci.RequestInitChain),
    "BeginBlock": ("begin_block", abci.RequestBeginBlock),
    "EndBlock": ("end_block", abci.RequestEndBlock),
    "ListSnapshots": ("list_snapshots", abci.RequestListSnapshots),
    "OfferSnapshot": ("offer_snapshot", abci.RequestOfferSnapshot),
    "LoadSnapshotChunk": ("load_snapshot_chunk", abci.RequestLoadSnapshotChunk),
    "ApplySnapshotChunk": ("apply_snapshot_chunk", abci.RequestApplySnapshotChunk),
}
_METHOD_BY_KIND = {kind: name for name, (kind, _) in _METHODS.items()}


class GRPCServer(BaseService):
    """Serves an Application behind the ABCIApplication gRPC service."""

    def __init__(self, addr: str, app: Application):
        super().__init__("GRPCServer")
        self._addr = addr.split("://", 1)[-1]
        self._app = app
        self._app_mtx = threading.Lock()
        self._server: Optional[grpc.Server] = None
        self._bound_port = 0

    @property
    def bound_port(self) -> int:
        return self._bound_port

    def on_start(self) -> None:
        from concurrent import futures

        app = self._app
        mtx = self._app_mtx

        from cometbft_tpu.abci.application import dispatch_request

        def make_handler(kind, req_cls):
            def handle(request_bytes: bytes, _ctx) -> bytes:
                req = req_cls.decode(request_bytes)
                with mtx:
                    resp = dispatch_request(app, abci.Request(kind, req))
                return resp.value.encode()

            return grpc.unary_unary_rpc_method_handler(
                handle,
                request_deserializer=lambda b: b,
                response_serializer=lambda b: b,
            )

        handlers = {
            name: make_handler(kind, req_cls)
            for name, (kind, req_cls) in _METHODS.items()
        }
        service = grpc.method_handlers_generic_handler(_SERVICE, handlers)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((service,))
        self._bound_port = self._server.add_insecure_port(self._addr)
        if self._bound_port == 0:
            raise RuntimeError(f"gRPC server failed to bind {self._addr}")
        self._server.start()

    def on_stop(self) -> None:
        if self._server is not None:
            self._server.stop(grace=1.0)
            self._server = None


class GRPCClient(Client):
    """Client-side: implements the same surface as the socket client, so
    proxy.AppConns can ride gRPC unchanged (grpc_client.go)."""

    def __init__(self, addr: str):
        super().__init__("GRPCClient")
        self._addr = addr.split("://", 1)[-1]
        self._channel: Optional[grpc.Channel] = None
        self._err: Optional[Exception] = None

    def on_start(self) -> None:
        self._channel = grpc.insecure_channel(self._addr)

    def on_stop(self) -> None:
        if self._channel is not None:
            self._channel.close()
            self._channel = None

    def error(self) -> Optional[Exception]:
        return self._err

    def request_async(self, req: abci.Request) -> ReqRes:
        """gRPC calls complete synchronously per request (the reference's
        gRPC client is 'async-shaped but sync' too — grpc_client.go:29)."""
        rr = ReqRes(req)
        method = _METHOD_BY_KIND.get(req.kind)
        if method is None:
            self._err = ValueError(f"unknown ABCI request kind {req.kind!r}")
            raise self._err
        callable_ = self._channel.unary_unary(
            f"/{_SERVICE}/{method}",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )
        try:
            value = req.value if req.value is not None else b""
            resp_bytes = callable_(
                value.encode() if hasattr(value, "encode") else b""
            )
        except grpc.RpcError as exc:
            self._err = exc
            raise
        resp_cls_entry = abci._RESPONSE_FIELDS.get(req.kind)
        resp_value = resp_cls_entry[1].decode(resp_bytes)
        rr.set_done(abci.Response(req.kind, resp_value))
        return rr

    def flush_sync(self) -> None:
        pass  # every call already completed on the wire
