"""ABCI clients: in-process (local) and socket (out-of-process).

Reference: abci/client/local_client.go:29 (one shared mutex around the
app), abci/client/socket_client.go:119,153 (pipelined send/recv routines
over a length-prefixed proto stream, FIFO request/response matching,
Flush batching). The async surface (``*_async`` returning a ReqRes with a
completion callback) is what the mempool's CheckTx pipeline builds on.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Callable, List, Optional

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.application import Application, dispatch_request
from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.service import BaseService


class ReqRes:
    """A request paired with its (eventually delivered) response."""

    def __init__(self, request: abci.Request):
        self.request = request
        self.response: Optional[abci.Response] = None
        self._done = threading.Event()
        self._cb: Optional[Callable[[abci.Response], None]] = None
        self._mtx = threading.Lock()

    def set_callback(self, cb: Callable[[abci.Response], None]) -> None:
        """Runs cb immediately if the response already arrived."""
        with self._mtx:
            if self.response is not None:
                cb(self.response)
                return
            self._cb = cb

    def set_done(self, response: abci.Response) -> None:
        with self._mtx:
            self.response = response
            cb = self._cb
        if cb is not None:
            cb(response)
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> abci.Response:
        if not self._done.wait(timeout):
            raise TimeoutError("ABCI request timed out")
        return self.response


class ClientError(Exception):
    pass


def _unwrap(res: abci.Response, want: str):
    if res.kind == "exception":
        raise ClientError(res.value.error)
    if res.kind != want:
        raise ClientError(f"unexpected response {res.kind!r}, want {want!r}")
    return res.value


class Client(BaseService):
    """Common surface: sync wrappers over the async primitives."""

    def request_async(self, req: abci.Request) -> ReqRes:
        raise NotImplementedError

    def flush_sync(self) -> None:
        raise NotImplementedError

    def error(self) -> Optional[Exception]:
        return None

    # -- sync helpers (reference AppConn*Sync methods) ----------------------

    def _call(self, kind: str, value) -> object:
        rr = self.request_async(abci.Request(kind, value))
        self.flush_sync()
        return _unwrap(rr.wait(), kind)

    def echo_sync(self, msg: str) -> abci.ResponseEcho:
        return self._call("echo", abci.RequestEcho(msg))

    def info_sync(self, req: abci.RequestInfo) -> abci.ResponseInfo:
        return self._call("info", req)

    def set_option_sync(self, req: abci.RequestSetOption) -> abci.ResponseSetOption:
        return self._call("set_option", req)

    def query_sync(self, req: abci.RequestQuery) -> abci.ResponseQuery:
        return self._call("query", req)

    def init_chain_sync(self, req: abci.RequestInitChain) -> abci.ResponseInitChain:
        return self._call("init_chain", req)

    def begin_block_sync(
        self, req: abci.RequestBeginBlock
    ) -> abci.ResponseBeginBlock:
        return self._call("begin_block", req)

    def check_tx_sync(self, req: abci.RequestCheckTx) -> abci.ResponseCheckTx:
        return self._call("check_tx", req)

    def deliver_tx_sync(self, req: abci.RequestDeliverTx) -> abci.ResponseDeliverTx:
        return self._call("deliver_tx", req)

    def end_block_sync(self, req: abci.RequestEndBlock) -> abci.ResponseEndBlock:
        return self._call("end_block", req)

    def commit_sync(self) -> abci.ResponseCommit:
        return self._call("commit", abci.RequestCommit())

    def list_snapshots_sync(
        self, req: abci.RequestListSnapshots
    ) -> abci.ResponseListSnapshots:
        return self._call("list_snapshots", req)

    def offer_snapshot_sync(
        self, req: abci.RequestOfferSnapshot
    ) -> abci.ResponseOfferSnapshot:
        return self._call("offer_snapshot", req)

    def load_snapshot_chunk_sync(
        self, req: abci.RequestLoadSnapshotChunk
    ) -> abci.ResponseLoadSnapshotChunk:
        return self._call("load_snapshot_chunk", req)

    def apply_snapshot_chunk_sync(
        self, req: abci.RequestApplySnapshotChunk
    ) -> abci.ResponseApplySnapshotChunk:
        return self._call("apply_snapshot_chunk", req)

    # -- async helpers used by the mempool ----------------------------------

    def check_tx_async(self, req: abci.RequestCheckTx) -> ReqRes:
        return self.request_async(abci.Request("check_tx", req))

    def deliver_tx_async(self, req: abci.RequestDeliverTx) -> ReqRes:
        return self.request_async(abci.Request("deliver_tx", req))

    def flush_async(self) -> ReqRes:
        return self.request_async(abci.Request("flush", abci.RequestFlush()))


class LocalClient(Client):
    """In-process app behind one shared mutex (builtin mode)."""

    def __init__(self, app: Application, mtx: Optional[threading.Lock] = None):
        super().__init__("LocalClient")
        self._app = app
        self._app_mtx = mtx or threading.Lock()

    def request_async(self, req: abci.Request) -> ReqRes:
        rr = ReqRes(req)
        with self._app_mtx:
            res = dispatch_request(self._app, req)
        rr.set_done(res)
        return rr

    def flush_sync(self) -> None:
        pass


class SocketClient(Client):
    """Pipelined client over a unix/TCP socket.

    A writer thread drains the request queue (flushing after each Flush
    request); a reader thread matches responses FIFO against in-flight
    ReqRes — the same two-routine structure as the reference's
    sendRequestsRoutine/recvResponseRoutine.
    """

    def __init__(self, addr: str, must_connect: bool = False):
        super().__init__("SocketClient")
        self._addr = addr
        self._must_connect = must_connect
        self._sock: Optional[socket.socket] = None
        self._queue: "queue.Queue[Optional[ReqRes]]" = queue.Queue()
        self._pending: "queue.Queue[ReqRes]" = queue.Queue()
        self._err: Optional[Exception] = None
        self._err_mtx = threading.Lock()

    def error(self) -> Optional[Exception]:
        with self._err_mtx:
            return self._err

    def on_start(self) -> None:
        self._sock = _dial(self._addr)
        self._wfile = self._sock.makefile("wb")
        self._rfile = self._sock.makefile("rb")
        threading.Thread(target=self._send_loop, daemon=True).start()
        threading.Thread(target=self._recv_loop, daemon=True).start()

    def on_stop(self) -> None:
        self._queue.put(None)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _fail(self, e: Exception) -> None:
        with self._err_mtx:
            if self._err is None:
                self._err = e
        # unblock everything in flight AND everything still queued to send
        for q in (self._pending, self._queue):
            while True:
                try:
                    rr = q.get_nowait()
                except queue.Empty:
                    break
                if rr is not None:
                    rr.set_done(
                        abci.Response("exception", abci.ResponseException(str(e)))
                    )

    def _send_loop(self) -> None:
        while self.is_running():
            rr = self._queue.get()
            if rr is None:
                return
            try:
                self._pending.put(rr)
                protoio.write_delimited(self._wfile, rr.request.encode())
                if rr.request.kind == "flush":
                    self._wfile.flush()
            except OSError as e:
                self._fail(e)
                return

    def _recv_loop(self) -> None:
        while self.is_running():
            try:
                data = protoio.read_delimited(self._rfile)
                res = abci.Response.decode(data)
            except (OSError, EOFError, ValueError) as e:
                self._fail(e)
                return
            try:
                rr = self._pending.get_nowait()
            except queue.Empty:
                self._fail(ClientError("unexpected response with nothing in flight"))
                return
            if res.kind not in ("exception", rr.request.kind):
                self._fail(
                    ClientError(
                        f"response {res.kind!r} does not match request "
                        f"{rr.request.kind!r}"
                    )
                )
                return
            rr.set_done(res)

    def request_async(self, req: abci.Request) -> ReqRes:
        rr = ReqRes(req)
        err = self.error()
        if err is not None:
            rr.set_done(abci.Response("exception", abci.ResponseException(str(err))))
            return rr
        self._queue.put(rr)
        return rr

    def flush_sync(self) -> None:
        rr = self.flush_async()
        rr.wait(timeout=30)
        err = self.error()
        if err is not None:
            raise ClientError(str(err))


def _dial(addr: str) -> socket.socket:
    """'unix://path', 'tcp://host:port', or bare 'host:port'."""
    if addr.startswith("unix://"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(addr[len("unix://") :])
        return s
    if addr.startswith("tcp://"):
        addr = addr[len("tcp://") :]
    host, _, port = addr.rpartition(":")
    s = socket.create_connection((host or "127.0.0.1", int(port)))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def new_local_client_creator(app: Application) -> Callable[[], Client]:
    mtx = threading.Lock()
    return lambda: LocalClient(app, mtx)


def new_socket_client_creator(addr: str) -> Callable[[], Client]:
    return lambda: SocketClient(addr)
