"""Native (C) runtime pieces of the framework.

The compute plane is JAX/XLA/Pallas (cometbft_tpu.crypto.tpu); this
package holds the native CPU runtime the reference implements in Go +
assembly — today the batched ed25519 fallback verifier
(`ed25519_batch.c`), built on demand with the system toolchain and
loaded via ctypes (which releases the GIL around calls).

Everything here degrades gracefully: if the toolchain or libcrypto is
unavailable the loader returns None and callers use the pure-Python
path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ed25519_batch.c")
_SO = os.path.join(_HERE, "build", "libcbft_ed25519.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> bool:
    try:
        return _build_inner()
    except OSError:
        # read-only package dir, missing source, fs races — all mean
        # "no native path"; the caller degrades to pure Python
        return False


def _build_inner() -> bool:
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    # rebuild only when the source is newer than the cached .so
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return True
    cc = os.environ.get("CC", "cc")
    # build images ship a runtime libcrypto (.so.3 or .so.1.1) without
    # dev symlink/headers: try the dev-style -lcrypto first, then link
    # the runtime .so by path (the EVP ABI used is stable since 1.1.1)
    candidates = [
        ["-lcrypto"],
        ["/usr/lib/x86_64-linux-gnu/libcrypto.so.3"],
        ["/lib/x86_64-linux-gnu/libcrypto.so.3"],
        ["/usr/lib/x86_64-linux-gnu/libcrypto.so.1.1"],
        ["/lib/x86_64-linux-gnu/libcrypto.so.1.1"],
    ]
    for libargs in candidates:
        cmd = [
            cc, "-O2", "-shared", "-fPIC", "-o", _SO + ".tmp", _SRC,
            "-pthread", *libargs,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120
            )
        except (OSError, subprocess.TimeoutExpired):
            return False
        if proc.returncode == 0:
            os.replace(_SO + ".tmp", _SO)
            return True
    return False


def load_ed25519() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native verifier; None on failure."""
    global _lib, _load_failed
    if _lib is not None:
        return _lib
    if _load_failed:
        return None
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("CBFT_NATIVE_ED25519", "1") == "0" or not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _load_failed = True
            return None
        lib.cbft_ed25519_verify_batch.restype = ctypes.c_int
        lib.cbft_ed25519_verify_batch.argtypes = [
            ctypes.c_char_p,                  # pubs
            ctypes.c_char_p,                  # msgs
            ctypes.POINTER(ctypes.c_size_t),  # msg_off
            ctypes.POINTER(ctypes.c_size_t),  # msg_len
            ctypes.c_char_p,                  # sigs
            ctypes.POINTER(ctypes.c_ubyte),   # out
            ctypes.c_size_t,                  # n
            ctypes.c_int,                     # nthreads
        ]
        _lib = lib
        return _lib


def _pack_msgs(msgs: Sequence[bytes]):
    """Concatenate variable-length messages into one buffer with
    per-entry (offset, length) arrays — the shared ctypes marshalling
    for both batch entry points."""
    n = len(msgs)
    offs = (ctypes.c_size_t * n)()
    lens = (ctypes.c_size_t * n)()
    parts = []
    pos = 0
    for i, m in enumerate(msgs):
        b = bytes(m)
        parts.append(b)
        offs[i] = pos
        lens[i] = len(b)
        pos += len(b)
    return b"".join(parts), offs, lens


def ed25519_verify_batch(
    pubs: Sequence[bytes],
    msgs: Sequence[bytes],
    sigs: Sequence[bytes],
    nthreads: Optional[int] = None,
) -> Optional[List[bool]]:
    """One native call for the whole batch; None if the lib is unavailable.

    Entries with malformed lengths are rejected (False) without being
    passed to OpenSSL, matching PubKeyEd25519.verify_signature.
    """
    lib = load_ed25519()
    if lib is None:
        return None
    n = len(pubs)
    if n == 0:
        return []
    ok_shape = [
        len(pubs[i]) == 32 and len(sigs[i]) == 64 for i in range(n)
    ]
    # malformed entries get zeroed slots so indices stay aligned
    pub_buf = b"".join(
        pubs[i] if ok_shape[i] else b"\x00" * 32 for i in range(n)
    )
    sig_buf = b"".join(
        sigs[i] if ok_shape[i] else b"\x00" * 64 for i in range(n)
    )
    msg_buf, offs, lens = _pack_msgs(msgs)
    out = (ctypes.c_ubyte * n)()
    if nthreads is None:
        nthreads = min(os.cpu_count() or 1, 16)
    rc = lib.cbft_ed25519_verify_batch(
        pub_buf, msg_buf, offs, lens, sig_buf, out, n, nthreads
    )
    if rc != 0:
        return None
    return [bool(out[i]) and ok_shape[i] for i in range(n)]


def _load_single():
    """ctypes bindings for the single-key sign/keygen entry points
    (same .so); None on any load failure."""
    lib = load_ed25519()
    if lib is None:
        return None
    sign = getattr(lib, "cbft_ed25519_sign", None)
    pub = getattr(lib, "cbft_ed25519_pub_from_seed", None)
    if sign is None or pub is None:
        return None  # stale cached .so predating these entry points
    if not getattr(sign, "_cbft_typed", False):
        sign.restype = ctypes.c_int
        sign.argtypes = [
            ctypes.c_char_p,  # seed (32)
            ctypes.c_char_p,  # msg
            ctypes.c_size_t,  # msglen
            ctypes.c_char_p,  # sig out (64)
        ]
        sign._cbft_typed = True
        pub.restype = ctypes.c_int
        pub.argtypes = [
            ctypes.c_char_p,  # seed (32)
            ctypes.c_char_p,  # pub out (32)
        ]
    return sign, pub


def ed25519_sign(seed: bytes, msg: bytes) -> Optional[bytes]:
    """OpenSSL ed25519 signature over msg; None if the lib is unavailable."""
    fns = _load_single()
    if fns is None or len(seed) != 32:
        return None
    out = ctypes.create_string_buffer(64)
    if fns[0](seed, msg, len(msg), out) != 0:
        return None
    return out.raw


def ed25519_pub_from_seed(seed: bytes) -> Optional[bytes]:
    """seed → 32-byte public key; None if the lib is unavailable."""
    fns = _load_single()
    if fns is None or len(seed) != 32:
        return None
    out = ctypes.create_string_buffer(32)
    if fns[1](seed, out) != 0:
        return None
    return out.raw


def load_challenges():
    """ctypes binding for cbft_ed25519_challenges (same .so); None on
    any load failure."""
    lib = load_ed25519()
    if lib is None:
        return None
    fn = getattr(lib, "cbft_ed25519_challenges", None)
    if fn is None:
        return None
    if not getattr(fn, "_cbft_typed", False):
        fn.restype = ctypes.c_int
        fn.argtypes = [
            ctypes.c_char_p,                  # pubs (A)
            ctypes.c_char_p,                  # rs (R)
            ctypes.c_char_p,                  # msgs
            ctypes.POINTER(ctypes.c_size_t),  # msg_off
            ctypes.POINTER(ctypes.c_size_t),  # msg_len
            ctypes.POINTER(ctypes.c_ubyte),   # valid
            ctypes.c_char_p,                  # out (n*32 LE)
            ctypes.c_size_t,                  # n
            ctypes.c_int,                     # nthreads
        ]
        fn._cbft_typed = True
    return fn


def ed25519_challenges(
    pubs: bytes,
    rs: bytes,
    msgs: Sequence[Optional[bytes]],
    valid: Sequence[bool],
    nthreads: Optional[int] = None,
) -> Optional[bytes]:
    """h = SHA-512(R ‖ A ‖ M) mod L per valid lane, one native call.

    pubs/rs are the concatenated n*32-byte A and R rows; lanes with
    valid[i] False are skipped (zeros in the output). A valid lane with
    msgs[i] None is a caller bug and returns None (the Python oracle
    would raise — silent empty-message hashing would be a parity
    break). Returns the n*32 little-endian output buffer, or None when
    the native path is unavailable (callers fall back to the Python
    loop)."""
    fn = load_challenges()
    if fn is None:
        return None
    n = len(valid)
    if n == 0:
        return b""
    if len(pubs) != 32 * n or len(rs) != 32 * n:
        return None  # shape mismatch must not reach the C reader
    if any(valid[i] and msgs[i] is None for i in range(n)):
        return None
    vbuf = (ctypes.c_ubyte * n)()
    for i in range(n):
        vbuf[i] = 1 if valid[i] else 0
    msg_buf, offs, lens = _pack_msgs(
        [msgs[i] if valid[i] else b"" for i in range(n)]
    )
    out = ctypes.create_string_buffer(32 * n)
    if nthreads is None:
        nthreads = min(os.cpu_count() or 1, 16)
    rc = fn(pubs, rs, msg_buf, offs, lens, vbuf, out, n, nthreads)
    if rc != 0:
        return None
    return out.raw
