/* Native ed25519 batch verification — CPU fallback hot loop.
 *
 * Why native: the reference's hot loop (types/validator_set.go:685-707)
 * is Go calling an assembly ed25519; our Python CPU path pays ~30%
 * interpreter overhead per signature AND the `cryptography` wheel holds
 * the GIL during verify, so Python threads cannot scale it across cores.
 * This file is the tpu-framework's native runtime answer: one call per
 * batch, GIL released by ctypes, pthreads inside chunk the batch across
 * cores, each thread looping OpenSSL EVP_DigestVerify.
 *
 * Semantics: identical accept/reject to OpenSSL's ed25519 verify
 * (cofactorless, rejects s >= L and non-canonical A), which is what the
 * Python path wraps too.
 *
 * Build: cc -O2 -shared -fPIC -o libcbft_ed25519.so ed25519_batch.c \
 *           -lcrypto -pthread
 */

#include <pthread.h>
#include <stddef.h>
#include <string.h>

/* The build image ships libcrypto.so.3 without dev headers; the EVP
 * functions used below have had a stable ABI since OpenSSL 1.1.1, so we
 * declare them directly. EVP_PKEY_ED25519 == NID_ED25519 == 1087. */
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;
#define EVP_PKEY_ED25519 1087
EVP_PKEY *EVP_PKEY_new_raw_public_key(int type, ENGINE *e,
                                      const unsigned char *pub, size_t len);
void EVP_PKEY_free(EVP_PKEY *pkey);
EVP_MD_CTX *EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX *ctx);
int EVP_DigestVerifyInit(EVP_MD_CTX *ctx, EVP_PKEY_CTX **pctx,
                         const EVP_MD *type, ENGINE *e, EVP_PKEY *pkey);
int EVP_DigestVerify(EVP_MD_CTX *ctx, const unsigned char *sig,
                     size_t siglen, const unsigned char *tbs, size_t tbslen);

EVP_PKEY *EVP_PKEY_new_raw_private_key(int type, ENGINE *e,
                                       const unsigned char *priv, size_t len);
int EVP_PKEY_get_raw_public_key(const EVP_PKEY *pkey, unsigned char *pub,
                                size_t *len);
int EVP_DigestSignInit(EVP_MD_CTX *ctx, EVP_PKEY_CTX **pctx,
                       const EVP_MD *type, ENGINE *e, EVP_PKEY *pkey);
int EVP_DigestSign(EVP_MD_CTX *ctx, unsigned char *sig, size_t *siglen,
                   const unsigned char *tbs, size_t tbslen);

typedef struct {
    const unsigned char *pubs;   /* n * 32 */
    const unsigned char *msgs;   /* concatenated */
    const size_t *msg_off;       /* n offsets into msgs */
    const size_t *msg_len;       /* n lengths */
    const unsigned char *sigs;   /* n * 64 */
    unsigned char *out;          /* n result bytes: 1 ok / 0 bad */
    size_t begin, end;
} chunk_t;

static void *verify_chunk(void *arg)
{
    chunk_t *c = (chunk_t *)arg;
    for (size_t i = c->begin; i < c->end; i++) {
        unsigned char ok = 0;
        EVP_PKEY *pk = EVP_PKEY_new_raw_public_key(
            EVP_PKEY_ED25519, NULL, c->pubs + 32 * i, 32);
        if (pk != NULL) {
            EVP_MD_CTX *ctx = EVP_MD_CTX_new();
            if (ctx != NULL) {
                if (EVP_DigestVerifyInit(ctx, NULL, NULL, NULL, pk) == 1 &&
                    EVP_DigestVerify(ctx, c->sigs + 64 * i, 64,
                                     c->msgs + c->msg_off[i],
                                     c->msg_len[i]) == 1)
                    ok = 1;
                EVP_MD_CTX_free(ctx);
            }
            EVP_PKEY_free(pk);
        }
        c->out[i] = ok;
    }
    return NULL;
}

/* Returns 0 on success. nthreads <= 1 runs inline (no thread spawn). */
int cbft_ed25519_verify_batch(const unsigned char *pubs,
                              const unsigned char *msgs,
                              const size_t *msg_off, const size_t *msg_len,
                              const unsigned char *sigs, unsigned char *out,
                              size_t n, int nthreads)
{
    if (n == 0)
        return 0;
    if (nthreads <= 1 || (size_t)nthreads > n) {
        chunk_t c = {pubs, msgs, msg_off, msg_len, sigs, out, 0, n};
        verify_chunk(&c);
        return 0;
    }
    enum { MAX_THREADS = 64 };
    if (nthreads > MAX_THREADS)
        nthreads = MAX_THREADS;
    pthread_t tids[MAX_THREADS];
    chunk_t chunks[MAX_THREADS];
    size_t per = n / nthreads, rem = n % nthreads, pos = 0;
    int spawned = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + (t < (int)rem ? 1 : 0);
        chunks[t] = (chunk_t){pubs, msgs, msg_off, msg_len,
                              sigs, out, pos, pos + take};
        pos += take;
        if (t == nthreads - 1) {
            /* run the last chunk on the calling thread */
            verify_chunk(&chunks[t]);
        } else if (pthread_create(&tids[spawned], NULL, verify_chunk,
                                  &chunks[t]) == 0) {
            spawned++;
        } else {
            verify_chunk(&chunks[t]); /* spawn failed: run inline */
        }
    }
    for (int t = 0; t < spawned; t++)
        pthread_join(tids[t], NULL);
    return 0;
}

/* --- single-key sign / keygen ------------------------------------------
 *
 * The image may lack the Python `cryptography` wheel entirely; these two
 * entry points let crypto/ed25519.py keep OpenSSL semantics for signing
 * and seed→pubkey derivation through the same ctypes .so instead of
 * dropping to the (much slower) pure-Python scalar path. */

/* Returns 0 on success; sig_out receives 64 bytes. */
int cbft_ed25519_sign(const unsigned char *seed, const unsigned char *msg,
                      size_t msglen, unsigned char *sig_out)
{
    int rc = 1;
    EVP_PKEY *pk = EVP_PKEY_new_raw_private_key(
        EVP_PKEY_ED25519, NULL, seed, 32);
    if (pk != NULL) {
        EVP_MD_CTX *ctx = EVP_MD_CTX_new();
        if (ctx != NULL) {
            size_t siglen = 64;
            if (EVP_DigestSignInit(ctx, NULL, NULL, NULL, pk) == 1 &&
                EVP_DigestSign(ctx, sig_out, &siglen, msg, msglen) == 1 &&
                siglen == 64)
                rc = 0;
            EVP_MD_CTX_free(ctx);
        }
        EVP_PKEY_free(pk);
    }
    return rc;
}

/* Returns 0 on success; pub_out receives 32 bytes. */
int cbft_ed25519_pub_from_seed(const unsigned char *seed,
                               unsigned char *pub_out)
{
    int rc = 1;
    EVP_PKEY *pk = EVP_PKEY_new_raw_private_key(
        EVP_PKEY_ED25519, NULL, seed, 32);
    if (pk != NULL) {
        size_t publen = 32;
        if (EVP_PKEY_get_raw_public_key(pk, pub_out, &publen) == 1 &&
            publen == 32)
            rc = 0;
        EVP_PKEY_free(pk);
    }
    return rc;
}

/* --- batch challenge scalars: h = SHA-512(R ‖ A ‖ M) mod L ------------
 *
 * Host-side packing cost of the TPU batch/resident verify paths
 * (crypto/tpu/ed25519_batch.py _challenge_scalars): the pure-Python
 * loop pays ~6 us/sig (hashlib call + 512-bit int mod); this native
 * loop is one call per batch with the same pthread chunking as the
 * verifier above. Output is 32 little-endian bytes per lane; lanes
 * with valid[i] == 0 are skipped (left zeroed). */

typedef struct bignum_st BIGNUM;
typedef struct bignum_ctx BN_CTX;
BIGNUM *BN_lebin2bn(const unsigned char *s, size_t len, BIGNUM *ret);
int BN_bn2lebinpad(const BIGNUM *a, unsigned char *to, size_t tolen);
int BN_div(BIGNUM *dv, BIGNUM *rem, const BIGNUM *m, const BIGNUM *d,
           BN_CTX *ctx);
BIGNUM *BN_new(void);
void BN_free(BIGNUM *a);
BN_CTX *BN_CTX_new(void);
void BN_CTX_free(BN_CTX *c);
const EVP_MD *EVP_sha512(void);
int EVP_DigestInit_ex(EVP_MD_CTX *ctx, const EVP_MD *type, ENGINE *impl);
int EVP_DigestUpdate(EVP_MD_CTX *ctx, const void *d, size_t cnt);
int EVP_DigestFinal_ex(EVP_MD_CTX *ctx, unsigned char *md, unsigned int *s);

/* L = 2^252 + 27742317777372353535851937790883648493, little-endian */
static const unsigned char CBFT_L_LE[32] = {
    0xed, 0xd3, 0xf5, 0x5c, 0x1a, 0x63, 0x12, 0x58,
    0xd6, 0x9c, 0xf7, 0xa2, 0xde, 0xf9, 0xde, 0x14,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00,
    0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x00, 0x10,
};

typedef struct {
    const unsigned char *pubs;   /* n * 32 (A) */
    const unsigned char *rs;     /* n * 32 (R) */
    const unsigned char *msgs;   /* concatenated */
    const size_t *msg_off;
    const size_t *msg_len;
    const unsigned char *valid;  /* n: 0 = skip lane */
    unsigned char *out;          /* n * 32 LE */
    size_t begin, end;
    int rc;
} hchunk_t;

static void *challenge_chunk(void *arg)
{
    hchunk_t *c = (hchunk_t *)arg;
    EVP_MD_CTX *ctx = EVP_MD_CTX_new();
    BIGNUM *L = BN_lebin2bn(CBFT_L_LE, 32, NULL);
    BIGNUM *h = BN_new();
    BIGNUM *rem = BN_new();
    BN_CTX *bctx = BN_CTX_new();
    if (ctx == NULL || L == NULL || h == NULL || rem == NULL ||
        bctx == NULL) {
        c->rc = 1;
        goto done;
    }
    for (size_t i = c->begin; i < c->end; i++) {
        unsigned char digest[64];
        unsigned int dlen = 0;
        if (!c->valid[i])
            continue;
        if (EVP_DigestInit_ex(ctx, EVP_sha512(), NULL) != 1 ||
            EVP_DigestUpdate(ctx, c->rs + 32 * i, 32) != 1 ||
            EVP_DigestUpdate(ctx, c->pubs + 32 * i, 32) != 1 ||
            EVP_DigestUpdate(ctx, c->msgs + c->msg_off[i],
                             c->msg_len[i]) != 1 ||
            EVP_DigestFinal_ex(ctx, digest, &dlen) != 1 || dlen != 64 ||
            BN_lebin2bn(digest, 64, h) == NULL ||
            BN_div(NULL, rem, h, L, bctx) != 1 ||
            BN_bn2lebinpad(rem, c->out + 32 * i, 32) != 32) {
            c->rc = 1;
            goto done;
        }
    }
done:
    if (ctx) EVP_MD_CTX_free(ctx);
    if (L) BN_free(L);
    if (h) BN_free(h);
    if (rem) BN_free(rem);
    if (bctx) BN_CTX_free(bctx);
    return NULL;
}

/* Returns 0 on success (any lane failure poisons the call — callers
 * fall back to the Python path rather than trust partial output). */
int cbft_ed25519_challenges(const unsigned char *pubs,
                            const unsigned char *rs,
                            const unsigned char *msgs,
                            const size_t *msg_off, const size_t *msg_len,
                            const unsigned char *valid, unsigned char *out,
                            size_t n, int nthreads)
{
    if (n == 0)
        return 0;
    if (nthreads <= 1 || (size_t)nthreads > n) {
        hchunk_t c = {pubs, rs, msgs, msg_off, msg_len,
                      valid, out, 0, n, 0};
        challenge_chunk(&c);
        return c.rc;
    }
    enum { MAX_THREADS = 64 };
    if (nthreads > MAX_THREADS)
        nthreads = MAX_THREADS;
    pthread_t tids[MAX_THREADS];
    hchunk_t chunks[MAX_THREADS];
    size_t per = n / nthreads, rem = n % nthreads, pos = 0;
    int spawned = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + (t < (int)rem ? 1 : 0);
        chunks[t] = (hchunk_t){pubs, rs, msgs, msg_off, msg_len,
                               valid, out, pos, pos + take, 0};
        pos += take;
        if (t == nthreads - 1) {
            challenge_chunk(&chunks[t]);
        } else if (pthread_create(&tids[spawned], NULL, challenge_chunk,
                                  &chunks[t]) == 0) {
            spawned++;
        } else {
            challenge_chunk(&chunks[t]);
        }
    }
    for (int t = 0; t < spawned; t++)
        pthread_join(tids[t], NULL);
    int rc = 0;
    for (int t = 0; t < nthreads; t++)
        rc |= chunks[t].rc;
    return rc;
}
