/* Native ed25519 batch verification — CPU fallback hot loop.
 *
 * Why native: the reference's hot loop (types/validator_set.go:685-707)
 * is Go calling an assembly ed25519; our Python CPU path pays ~30%
 * interpreter overhead per signature AND the `cryptography` wheel holds
 * the GIL during verify, so Python threads cannot scale it across cores.
 * This file is the tpu-framework's native runtime answer: one call per
 * batch, GIL released by ctypes, pthreads inside chunk the batch across
 * cores, each thread looping OpenSSL EVP_DigestVerify.
 *
 * Semantics: identical accept/reject to OpenSSL's ed25519 verify
 * (cofactorless, rejects s >= L and non-canonical A), which is what the
 * Python path wraps too.
 *
 * Build: cc -O2 -shared -fPIC -o libcbft_ed25519.so ed25519_batch.c \
 *           -lcrypto -pthread
 */

#include <pthread.h>
#include <stddef.h>
#include <string.h>

/* The build image ships libcrypto.so.3 without dev headers; the EVP
 * functions used below have had a stable ABI since OpenSSL 1.1.1, so we
 * declare them directly. EVP_PKEY_ED25519 == NID_ED25519 == 1087. */
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;
#define EVP_PKEY_ED25519 1087
EVP_PKEY *EVP_PKEY_new_raw_public_key(int type, ENGINE *e,
                                      const unsigned char *pub, size_t len);
void EVP_PKEY_free(EVP_PKEY *pkey);
EVP_MD_CTX *EVP_MD_CTX_new(void);
void EVP_MD_CTX_free(EVP_MD_CTX *ctx);
int EVP_DigestVerifyInit(EVP_MD_CTX *ctx, EVP_PKEY_CTX **pctx,
                         const EVP_MD *type, ENGINE *e, EVP_PKEY *pkey);
int EVP_DigestVerify(EVP_MD_CTX *ctx, const unsigned char *sig,
                     size_t siglen, const unsigned char *tbs, size_t tbslen);

typedef struct {
    const unsigned char *pubs;   /* n * 32 */
    const unsigned char *msgs;   /* concatenated */
    const size_t *msg_off;       /* n offsets into msgs */
    const size_t *msg_len;       /* n lengths */
    const unsigned char *sigs;   /* n * 64 */
    unsigned char *out;          /* n result bytes: 1 ok / 0 bad */
    size_t begin, end;
} chunk_t;

static void *verify_chunk(void *arg)
{
    chunk_t *c = (chunk_t *)arg;
    for (size_t i = c->begin; i < c->end; i++) {
        unsigned char ok = 0;
        EVP_PKEY *pk = EVP_PKEY_new_raw_public_key(
            EVP_PKEY_ED25519, NULL, c->pubs + 32 * i, 32);
        if (pk != NULL) {
            EVP_MD_CTX *ctx = EVP_MD_CTX_new();
            if (ctx != NULL) {
                if (EVP_DigestVerifyInit(ctx, NULL, NULL, NULL, pk) == 1 &&
                    EVP_DigestVerify(ctx, c->sigs + 64 * i, 64,
                                     c->msgs + c->msg_off[i],
                                     c->msg_len[i]) == 1)
                    ok = 1;
                EVP_MD_CTX_free(ctx);
            }
            EVP_PKEY_free(pk);
        }
        c->out[i] = ok;
    }
    return NULL;
}

/* Returns 0 on success. nthreads <= 1 runs inline (no thread spawn). */
int cbft_ed25519_verify_batch(const unsigned char *pubs,
                              const unsigned char *msgs,
                              const size_t *msg_off, const size_t *msg_len,
                              const unsigned char *sigs, unsigned char *out,
                              size_t n, int nthreads)
{
    if (n == 0)
        return 0;
    if (nthreads <= 1 || (size_t)nthreads > n) {
        chunk_t c = {pubs, msgs, msg_off, msg_len, sigs, out, 0, n};
        verify_chunk(&c);
        return 0;
    }
    enum { MAX_THREADS = 64 };
    if (nthreads > MAX_THREADS)
        nthreads = MAX_THREADS;
    pthread_t tids[MAX_THREADS];
    chunk_t chunks[MAX_THREADS];
    size_t per = n / nthreads, rem = n % nthreads, pos = 0;
    int spawned = 0;
    for (int t = 0; t < nthreads; t++) {
        size_t take = per + (t < (int)rem ? 1 : 0);
        chunks[t] = (chunk_t){pubs, msgs, msg_off, msg_len,
                              sigs, out, pos, pos + take};
        pos += take;
        if (t == nthreads - 1) {
            /* run the last chunk on the calling thread */
            verify_chunk(&chunks[t]);
        } else if (pthread_create(&tids[spawned], NULL, verify_chunk,
                                  &chunks[t]) == 0) {
            spawned++;
        } else {
            verify_chunk(&chunks[t]); /* spawn failed: run inline */
        }
    }
    for (int t = 0; t < spawned; t++)
        pthread_join(tids[t], NULL);
    return 0;
}
