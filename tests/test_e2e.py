"""E2E testnet harness: 4 validators over real TCP, tx load, kill/restart
perturbations, catch-up, and cross-node invariants.

Model: reference test/e2e/runner (perturb.go kill/restart) +
test/e2e/tests (app hash agreement, header chaining, tx visibility) +
test/loadtime (commit-latency report).
"""

import time

import pytest

from cometbft_tpu.e2e import LoadGenerator, Testnet


@pytest.mark.slow
class TestE2ETestnet:
    def test_load_perturbation_and_invariants(self):
        net = Testnet(n_validators=4, timeout_commit_ns=200_000_000)
        net.setup()
        net.start()
        load = LoadGenerator(net, rate_per_s=4.0)
        try:
            # the net makes progress and accepts load
            net.wait_for_height(3, timeout=90)
            load.start()
            net.wait_for_height(6, timeout=90)

            # perturbation: kill one validator — 3/4 voting power keeps
            # committing (perturb.go "kill")
            net.kill_node(3)
            h_at_kill = max(net.height(i) for i in net.live_indexes())
            net.wait_for_height(h_at_kill + 3, timeout=90)

            # restart: the node comes back from disk and CATCHES UP
            net.restart_node(3)
            target = max(net.height(i) for i in (0, 1, 2)) + 2
            net.wait_for_height(target, timeout=120)

            load.stop()
            rep = load.report()
            assert rep["committed"] >= 5, rep
            assert rep["p50_latency_s"] < 30, rep

            # invariants across every node, including the restarted one
            check_h = min(net.height(i) for i in net.live_indexes()) - 1
            assert check_h >= 4
            net.check_app_hashes_agree(check_h)
            net.check_blocks_well_formed(min(check_h, 8))
            net.check_block_results_consistent(min(check_h, 8))
            assert len(net.live_indexes()) == 4
            # a committed tx is queryable on all nodes (indexers agree)
            if load.tx_hashes:
                deadline = time.monotonic() + 30
                last_err = None
                while time.monotonic() < deadline:
                    try:
                        net.check_tx_visible_everywhere(load.tx_hashes[0])
                        last_err = None
                        break
                    except Exception as exc:  # indexer catch-up on node 3
                        last_err = exc
                        time.sleep(0.5)
                assert last_err is None, last_err
        finally:
            load.stop()
            net.stop()

    def test_scheduled_misbehavior_commits_evidence(self):
        """Maverick via the runner API (test/maverick +
        test/e2e/networks/ci.toml `misbehaviors`): node 0 is scheduled to
        double-precommit at heights 3-5; the honest majority detects the
        equivocation and commits DuplicateVoteEvidence naming node 0."""
        net = Testnet(
            n_validators=4,
            timeout_commit_ns=200_000_000,
            misbehaviors={0: {3: "double-precommit",
                              4: "double-precommit",
                              5: "double-precommit"}},
        )
        net.setup()
        net.start()
        try:
            deadline = time.time() + 150
            while time.time() < deadline:
                if net.evidence_committed_for(0):
                    break
                time.sleep(1.0)
            assert net.evidence_committed_for(0), (
                "evidence for the scheduled misbehavior never committed"
            )
            # the net keeps making progress with the maverick aboard
            h = max(net.height(i) for i in net.live_indexes())
            net.wait_for_height(h + 2, timeout=60)
        finally:
            net.stop()
