"""E2E testnet harness: 4 validators over real TCP, tx load, kill/restart
perturbations, catch-up, and cross-node invariants.

Model: reference test/e2e/runner (perturb.go kill/restart) +
test/e2e/tests (app hash agreement, header chaining, tx visibility) +
test/loadtime (commit-latency report).
"""

import time

import pytest

from cometbft_tpu.e2e import LoadGenerator, Testnet


@pytest.mark.slow
class TestE2ETestnet:
    def test_load_perturbation_and_invariants(self):
        net = Testnet(n_validators=4, timeout_commit_ns=200_000_000)
        net.setup()
        net.start()
        load = LoadGenerator(net, rate_per_s=4.0)
        try:
            # the net makes progress and accepts load
            net.wait_for_height(3, timeout=90)
            load.start()
            net.wait_for_height(6, timeout=90)

            # perturbation: kill one validator — 3/4 voting power keeps
            # committing (perturb.go "kill")
            net.kill_node(3)
            h_at_kill = max(net.height(i) for i in net.live_indexes())
            net.wait_for_height(h_at_kill + 3, timeout=90)

            # restart: the node comes back from disk and CATCHES UP
            net.restart_node(3)
            target = max(net.height(i) for i in (0, 1, 2)) + 2
            net.wait_for_height(target, timeout=120)

            load.stop()
            rep = load.report()
            assert rep["committed"] >= 5, rep
            assert rep["p50_latency_s"] < 30, rep

            # invariants across every node, including the restarted one
            check_h = min(net.height(i) for i in net.live_indexes()) - 1
            assert check_h >= 4
            net.check_app_hashes_agree(check_h)
            net.check_blocks_well_formed(min(check_h, 8))
            net.check_block_results_consistent(min(check_h, 8))
            assert len(net.live_indexes()) == 4
            # a committed tx is queryable on all nodes (indexers agree)
            if load.tx_hashes:
                deadline = time.monotonic() + 30
                last_err = None
                while time.monotonic() < deadline:
                    try:
                        net.check_tx_visible_everywhere(load.tx_hashes[0])
                        last_err = None
                        break
                    except Exception as exc:  # indexer catch-up on node 3
                        last_err = exc
                        time.sleep(0.5)
                assert last_err is None, last_err
        finally:
            load.stop()
            net.stop()

    def test_scheduled_misbehavior_commits_evidence(self):
        """Maverick via the runner API (test/maverick +
        test/e2e/networks/ci.toml `misbehaviors`): node 0 is scheduled to
        double-precommit at heights 3-5; the honest majority detects the
        equivocation and commits DuplicateVoteEvidence naming node 0."""
        net = Testnet(
            n_validators=4,
            timeout_commit_ns=200_000_000,
            misbehaviors={0: {3: "double-precommit",
                              4: "double-precommit",
                              5: "double-precommit"}},
        )
        net.setup()
        net.start()
        try:
            deadline = time.time() + 150
            while time.time() < deadline:
                if net.evidence_committed_for(0):
                    break
                time.sleep(1.0)
            assert net.evidence_committed_for(0), (
                "evidence for the scheduled misbehavior never committed"
            )
            # the net keeps making progress with the maverick aboard
            h = max(net.height(i) for i in net.live_indexes())
            net.wait_for_height(h + 2, timeout=60)
        finally:
            net.stop()

    def test_valset_churn_and_statesync_join(self):
        """Reference: test/e2e/networks/ci.toml — validator-set churn
        scheduled mid-run plus a node that joins via state sync. Here:
        (1) an existing validator's power changes through the kvstore's
        `val:` tx and the RPC /validators view rotates at the right
        height; (2) a brand-new key is voted in, then out; (3) a fresh
        full node statesyncs into the live net (snapshot restore behind
        light-client verification) and catches up to consensus."""
        import base64

        from cometbft_tpu.abci.kvstore import PersistentKVStoreApplication
        from cometbft_tpu.crypto import ed25519

        net = Testnet(
            n_validators=4,
            timeout_commit_ns=200_000_000,
            # the validator-update + snapshot-serving app (the plain
            # "kvstore" ignores val: txs and takes no snapshots)
            proxy_app="snapshot_kvstore",
        )
        net.setup()
        net.start()
        try:
            net.wait_for_height(2, timeout=90)
            c = net.client(0)

            # -- (1) power change for a sitting validator -------------------
            val0 = net.nodes[0].priv_validator.get_pub_key()
            tx = PersistentKVStoreApplication.make_val_set_change_tx(
                base64.b64encode(val0.bytes()).decode(), 25
            )
            res = c.broadcast_tx_commit(tx)
            assert (res.get("deliver_tx") or {}).get("code", 1) == 0, res
            changed_h = int(res["height"])
            # the update takes effect at changed_h + 2 (EndBlock at H
            # schedules the set for H+2 — types/validator_set.go rule)
            net.wait_for_height(changed_h + 2, timeout=60)
            vals = c.validators(height=changed_h + 2)["validators"]
            by_addr = {v["address"]: int(v["voting_power"]) for v in vals}
            assert by_addr[val0.address().hex().upper()] == 25, by_addr

            # -- (2) vote a brand-new validator in, then out ----------------
            newkey = ed25519.gen_priv_key_from_secret(b"churn-join")
            new_b64 = base64.b64encode(newkey.pub_key().bytes()).decode()
            res = c.broadcast_tx_commit(
                PersistentKVStoreApplication.make_val_set_change_tx(new_b64, 3)
            )
            assert (res.get("deliver_tx") or {}).get("code", 1) == 0, res
            join_h = int(res["height"])
            net.wait_for_height(join_h + 2, timeout=60)
            vals = c.validators(height=join_h + 2)["validators"]
            assert any(
                v["address"] == newkey.pub_key().address().hex().upper()
                for v in vals
            ), vals
            # the chain keeps committing with the absent validator aboard
            # (3 voting units of 58 — well under 1/3)
            res = c.broadcast_tx_commit(
                PersistentKVStoreApplication.make_val_set_change_tx(new_b64, 0)
            )
            assert (res.get("deliver_tx") or {}).get("code", 1) == 0, res
            leave_h = int(res["height"])
            net.wait_for_height(leave_h + 2, timeout=60)
            vals = c.validators(height=leave_h + 2)["validators"]
            assert not any(
                v["address"] == newkey.pub_key().address().hex().upper()
                for v in vals
            ), vals

            # -- (3) statesync join -----------------------------------------
            # snapshots are taken every 10 heights; make sure one exists
            net.wait_for_height(11, timeout=120)
            joiner = net.add_node(statesync=True)
            target = max(net.height(i) for i in range(net.n)) + 2
            net.wait_for_height(target, timeout=120, nodes=[joiner])
            # the joiner agrees with the net post-restore (its history
            # legitimately starts at the snapshot height, so compare at
            # a height it has; the app hash there commits the full
            # churned history)
            net.wait_for_height(target, timeout=60)
            net.check_app_hashes_agree(target)
            # and it statesynced (no full block history before the
            # snapshot): earliest stored height is past genesis
            st = net.client(joiner).status()
            assert int(st["sync_info"]["earliest_block_height"]) > 1, st
        finally:
            net.stop()

    def test_double_proposal_liveness(self):
        """Byzantine proposer equivocation (consensus/byzantine_test.go):
        node 0 proposes TWO different blocks at heights 3-5. v0.34 has no
        proposal-equivocation evidence, so the assertion is liveness +
        agreement: the first valid proposal wins per peer and all nodes
        commit identical blocks."""
        net = Testnet(
            n_validators=4,
            timeout_commit_ns=200_000_000,
            # four consecutive heights: the proposer rotates over the 4
            # validators, so node 0 is guaranteed a proposing slot
            misbehaviors={0: {3: "double-proposal",
                              4: "double-proposal",
                              5: "double-proposal",
                              6: "double-proposal"}},
        )
        net.setup()
        net.start()
        try:
            net.wait_for_height(7, timeout=150)
            # the misbehavior must have actually FIRED (a vacuous pass —
            # no second proposal ever broadcast — must fail here)
            fired = getattr(net.nodes[0], "maverick_fired", set())
            assert any(
                isinstance(k, tuple) and k[1] == "prop" for k in fired
            ), f"double-proposal never fired: {fired}"
            for h in (3, 4, 5, 6):
                net.check_app_hashes_agree(h)
        finally:
            net.stop()


@pytest.mark.slow
class TestNoEmptyBlocks:
    def test_chain_waits_for_txs_then_advances(self):
        """create_empty_blocks = false: the chain must HOLD with an empty
        mempool and advance once a tx arrives — which requires the node
        to wire mempool.enable_txs_available + the push notification
        into consensus (reference node.go + the TxsAvailable goroutine);
        without that wiring the poke never fires and the chain stalls
        forever."""
        net = Testnet(
            n_validators=2,
            timeout_commit_ns=200_000_000,
            create_empty_blocks=False,
        )
        net.setup()
        net.start()
        try:
            # the first heights are proof blocks (_need_proof_block:
            # app hash changes after genesis) — wait for them, then the
            # chain must hold. Without suppression the 200ms commit
            # timeout would gain dozens of heights over these samples.
            net.wait_for_height(2, timeout=60)
            time.sleep(5.0)
            h0 = max(net.height(i) for i in net.live_indexes())
            time.sleep(5.0)
            h1 = max(net.height(i) for i in net.live_indexes())
            assert h1 <= h0 + 1, f"chain advanced without txs: {h0} -> {h1}"
            # one tx unblocks the next height
            net.client(0).broadcast_tx_sync(b"wake=up")
            net.wait_for_height(h1 + 1, timeout=60)
        finally:
            net.stop()
