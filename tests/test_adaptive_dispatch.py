"""Adaptive fault-tolerant dispatch: the degradation ladder between
HEALTHY and BROKEN (crypto/supervisor.py, crypto/tpu/mesh.py).

Contract under test:
  - device exceptions are classified transient / oom / persistent by
    scanning the whole exception chain, and only persistents strike the
    breaker on first sight;
  - a transient error is retried once with jittered backoff and a
    successful retry costs no breaker strike and no CPU fallback;
  - an OOM halves the effective mesh chunk cap per retry down to a
    floor, and the cap recovers one doubling per chunk_recover_n
    consecutive clean dispatches (hysteresis);
  - the EWMA latency model hedges an overrunning dispatch with a
    parallel CPU verify, first mask wins, and the loser is audited for
    divergence (divergence trips the breaker);
  - a mixed-verdict batch is triaged: claimed-bad lanes bisected on
    device within the ceil(log2 n) + 1 pass bound, convictions
    CPU-confirmed, offenders attributed per submitting request, and a
    CPU overturn (silent corruption) trips the breaker;
  - the deterministic chaos smoke walks every rung with zero verdict
    divergence (tools/chaos.py runs the same harness).
"""

import math
import threading
import time

import pytest

from cometbft_tpu.crypto import batch as cryptobatch
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import BackendSpec, CPUBatchVerifier
from cometbft_tpu.crypto.faults import (
    FaultPlan,
    ResourceExhaustedFault,
    TransientFault,
    install,
    run_chaos_smoke,
)
from cometbft_tpu.crypto.supervisor import (
    BROKEN,
    DEGRADED,
    HEALTHY,
    OOM,
    PERSISTENT,
    TRANSIENT,
    BackendSupervisor,
    LatencyModel,
    classify_device_error,
    hedge_pct_default,
    retry_ms_default,
    chunk_recover_n_default,
)
from cometbft_tpu.crypto.tpu import mesh


def _make_items(n, tag=b"", poison_at=None):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"adaptive-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if poison_at is not None and i == poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


def _cpu_mask(items):
    bv = CPUBatchVerifier()
    for pk, m, s in items:
        bv.add(pk, m, s)
    _, mask = bv.verify()
    return mask


def _total(counter):
    return sum(c.value() for c in counter._series())


_seq = [0]


def _faulty(plan=None, **sup_kwargs):
    _seq[0] += 1
    name = f"test-adaptive-{_seq[0]}"
    plan = install(name=name, inner="cpu",
                   plan=plan if plan is not None else FaultPlan(seed=_seq[0]))
    sup_kwargs.setdefault("dispatch_timeout_ms", 2000)
    sup_kwargs.setdefault("breaker_threshold", 3)
    sup_kwargs.setdefault("audit_pct", 0)
    sup_kwargs.setdefault("probe_base_ms", 10)
    sup_kwargs.setdefault("probe_max_ms", 80)
    sup_kwargs.setdefault("retry_ms", 5)
    sup = BackendSupervisor(spec=BackendSpec(name), **sup_kwargs)
    return plan, sup


@pytest.fixture(autouse=True)
def _clean_chunk_shrink():
    # the shrink level is module state in mesh (it models device memory
    # pressure, which outlives any one supervisor) — isolate tests
    mesh.reset_chunk_shrink()
    yield
    mesh.reset_chunk_shrink()


class TestClassification:
    def test_oom_markers(self):
        for msg in (
            "RESOURCE_EXHAUSTED: while allocating",
            "out of memory on device",
            "HBM allocation failure",
            "oom killed",
        ):
            assert classify_device_error(RuntimeError(msg)) == OOM, msg

    def test_transient_markers(self):
        for msg in (
            "UNAVAILABLE: socket closed",
            "DEADLINE_EXCEEDED waiting for tunnel",
            "connection reset by peer",
            "temporarily unreachable, try again",
        ):
            assert classify_device_error(RuntimeError(msg)) == TRANSIENT, msg

    def test_persistent_default(self):
        assert classify_device_error(RuntimeError("kernel mismatch")) \
            == PERSISTENT

    def test_substring_innocents_stay_persistent(self):
        # "boom" must not trigger the OOM rung (bare-"oom" regression)
        assert classify_device_error(RuntimeError("boom")) == PERSISTENT

    def test_walks_cause_chain(self):
        # mesh.dispatch_batch wraps chunk errors but chains the original
        try:
            try:
                raise RuntimeError("RESOURCE_EXHAUSTED: hbm")
            except RuntimeError as inner:
                raise RuntimeError("chunk 3/8 failed") from inner
        except RuntimeError as outer:
            assert classify_device_error(outer) == OOM

    def test_fault_shapes_classify(self):
        assert classify_device_error(
            TransientFault("UNAVAILABLE: injected")) == TRANSIENT
        assert classify_device_error(
            ResourceExhaustedFault("RESOURCE_EXHAUSTED: injected")) == OOM


class TestLatencyModel:
    def test_cold_returns_none(self):
        assert LatencyModel().predict_p99(1024) is None

    def test_warm_bucket_predicts_tail_above_mean(self):
        lm = LatencyModel()
        for v in (0.010, 0.012, 0.011, 0.013):
            lm.observe(1024, v)
        p99 = lm.predict_p99(1024)
        assert p99 is not None and p99 >= 0.010

    def test_nearest_warm_bucket_fallback(self):
        lm = LatencyModel()
        for _ in range(4):
            lm.observe(1024, 0.010)
        # 4096 bucket is cold: the 1024 one answers for it
        assert lm.predict_p99(4096) == pytest.approx(
            lm.predict_p99(1024))

    def test_below_min_samples_stays_cold(self):
        lm = LatencyModel()
        lm.observe(64, 0.001)
        assert lm.predict_p99(64) is None


class TestKnobs:
    def test_defaults_and_env_precedence(self, monkeypatch):
        assert hedge_pct_default() == 200
        assert retry_ms_default() == 25
        assert chunk_recover_n_default() == 32
        monkeypatch.setenv("CBFT_HEDGE_PCT", "350")
        monkeypatch.setenv("CBFT_RETRY_MS", "7")
        monkeypatch.setenv("CBFT_CHUNK_RECOVER_N", "4")
        assert hedge_pct_default(100) == 350  # env beats config
        assert retry_ms_default(100) == 7
        assert chunk_recover_n_default(100) == 4

    def test_config_knobs_validate(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        assert cfg.crypto.hedge_pct == 200
        assert cfg.crypto.retry_ms == 25
        assert cfg.crypto.chunk_recover_n == 32
        cfg.validate_basic()
        cfg.crypto.hedge_pct = 0  # 0 = hedging off, and is valid
        cfg.validate_basic()
        cfg.crypto.hedge_pct = -1
        with pytest.raises(ValueError):
            cfg.validate_basic()
        cfg.crypto.hedge_pct = 200
        cfg.crypto.retry_ms = 0
        with pytest.raises(ValueError):
            cfg.validate_basic()


class TestTransientRetry:
    def test_one_flap_absorbed_without_strike(self):
        plan, sup = _faulty()
        plan.transient_n = 1
        items = _make_items(12, b"flap")
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.state() == HEALTHY  # no strike, no DEGRADED
            assert _total(sup.metrics.retries) == 1
            assert sup.metrics.failures.value() == 0
        finally:
            sup.stop()

    def test_second_flap_in_a_row_falls_through(self):
        # one retry only: two consecutive flaps on the same batch cost a
        # breaker strike + CPU fallback, exactly like before the ladder
        plan, sup = _faulty()
        plan.transient_n = 2
        items = _make_items(12, b"flap2")
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.state() == DEGRADED
            assert sup.metrics.failures.value() == 1
        finally:
            sup.stop()

    def test_persistent_error_not_retried(self):
        plan, sup = _faulty()
        plan.exception_rate = 1.0  # FaultInjected: persistent-shaped
        items = _make_items(12, b"persist")
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.state() == DEGRADED
            assert _total(sup.metrics.retries) == 0
        finally:
            sup.stop()


class TestChunkShrink:
    def test_mesh_shrink_and_floor(self):
        assert mesh.chunk_shrink_levels() == 0
        base = mesh.effective_chunk_cap(8192)
        for lvl in range(1, mesh.MAX_SHRINK_LEVELS + 1):
            assert mesh.shrink_chunk_cap()
            assert mesh.chunk_shrink_levels() == lvl
        assert not mesh.shrink_chunk_cap()  # at the floor
        assert mesh.effective_chunk_cap(8192) == max(
            64, base >> mesh.MAX_SHRINK_LEVELS
        )

    def test_shrunk_cap_respects_min_pad(self):
        for _ in range(mesh.MAX_SHRINK_LEVELS):
            mesh.shrink_chunk_cap()
        assert mesh.effective_chunk_cap(128, min_pad=64) == 64

    def test_recovery_hysteresis_exact_count(self):
        mesh.shrink_chunk_cap()
        mesh.shrink_chunk_cap()
        n = 4
        for _ in range(n - 1):
            assert not mesh.note_clean_dispatch(n)
        assert mesh.note_clean_dispatch(n)  # nth clean recovers a level
        assert mesh.chunk_shrink_levels() == 1
        # the streak resets after a recovery: another n cleans needed
        for _ in range(n - 1):
            assert not mesh.note_clean_dispatch(n)
        assert mesh.note_clean_dispatch(n)
        assert mesh.chunk_shrink_levels() == 0
        # fully recovered: further cleans are no-ops
        assert not mesh.note_clean_dispatch(n)

    def test_shrink_resets_streak(self):
        mesh.shrink_chunk_cap()
        mesh.note_clean_dispatch(3)
        mesh.note_clean_dispatch(3)
        mesh.shrink_chunk_cap()  # a fresh OOM voids the progress
        assert not mesh.note_clean_dispatch(3)
        assert not mesh.note_clean_dispatch(3)
        assert mesh.note_clean_dispatch(3)

    def test_oom_dispatch_shrinks_to_floor_then_cpu(self):
        plan, sup = _faulty(chunk_recover_n=2)
        plan.oom_rate = 1.0
        items = _make_items(12, b"oom")
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            # every retry shrank one level until the floor, then the
            # failure fell through to one breaker strike + CPU
            assert mesh.chunk_shrink_levels() == mesh.MAX_SHRINK_LEVELS
            assert sup.metrics.chunk_shrinks.value() \
                == mesh.MAX_SHRINK_LEVELS
            assert _total(sup.metrics.retries) == mesh.MAX_SHRINK_LEVELS
            assert sup.state() == DEGRADED
            # repair: clean dispatches recover one doubling per
            # chunk_recover_n (supervisor default threaded from knob)
            plan.clear()
            for _ in range(2 * sup.chunk_recover_n):
                assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.metrics.chunk_recoveries.value() == 2
            assert mesh.chunk_shrink_levels() == mesh.MAX_SHRINK_LEVELS - 2
            assert sup.state() == HEALTHY
        finally:
            sup.stop()


class TestHedge:
    def _primed(self, items, **kwargs):
        plan, sup = _faulty(**kwargs)
        for _ in range(5):
            sup.latency_model.observe(len(items), 0.002)
        return plan, sup

    def test_overrunning_dispatch_hedges_and_agrees(self):
        items = _make_items(12, b"hedge")
        plan, sup = self._primed(items)
        plan.hang_rate = 1.0
        plan.hang_s = 0.04  # well past predicted p99 x 2, under watchdog
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.metrics.hedge_fires.value() == 1
            assert _total(sup.metrics.hedge_wins) == 1
            # let the loser limp home and be compared against the winner
            time.sleep(plan.hang_s + 0.02)
            assert sup.metrics.hedge_divergence.value() == 0
            assert sup.state() in (HEALTHY, DEGRADED)
        finally:
            sup.stop()

    def test_hedge_disabled_by_zero_pct(self):
        items = _make_items(12, b"nohedge")
        plan, sup = self._primed(items, hedge_pct=0)
        plan.hang_rate = 1.0
        plan.hang_s = 0.04
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.metrics.hedge_fires.value() == 0
        finally:
            sup.stop()

    def test_cold_model_never_hedges(self):
        plan, sup = _faulty()
        plan.hang_rate = 1.0
        plan.hang_s = 0.04
        items = _make_items(12, b"cold")
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.metrics.hedge_fires.value() == 0
        finally:
            sup.stop()

    def test_loser_divergence_trips_breaker(self):
        # device hangs past the hedge point AND returns corrupt verdicts:
        # the CPU mask is released (ground truth), and when the device
        # limps home disagreeing, the audit path breaks the circuit
        items = _make_items(12, b"hedge-corrupt")
        plan, sup = self._primed(items)
        plan.hang_rate = 1.0
        plan.hang_s = 0.04
        plan.corrupt_rate = 1.0
        try:
            mask = sup.verify_items(items)
            assert mask == _cpu_mask(items)  # corruption never released
            deadline = time.monotonic() + 2
            while time.monotonic() < deadline and sup.state() != BROKEN:
                time.sleep(0.005)
            assert sup.state() == BROKEN
            assert sup.metrics.hedge_divergence.value() == 1
        finally:
            sup.stop()

    def test_hedge_threshold_beyond_watchdog_stays_plain(self):
        # predicted hedge point past dispatch_timeout_ms: plain watchdog
        items = _make_items(12, b"far")
        plan, sup = _faulty(dispatch_timeout_ms=50)
        for _ in range(5):
            sup.latency_model.observe(len(items), 10.0)  # absurd p99
        plan.hang_rate = 1.0
        plan.hang_s = 5.0
        try:
            assert sup.verify_items(items) == _cpu_mask(items)
            assert sup.metrics.hedge_fires.value() == 0
            assert sup.metrics.watchdog_kills.value() == 1
            assert sup.state() == BROKEN
        finally:
            sup.stop()


class _LyingVerifier(CPUBatchVerifier):
    """CPU verifier that falsely claims configured lanes bad — but only
    on dispatches of at least ``full_n`` items, so triage's smaller
    re-dispatches see the truth (a transient device glitch)."""

    lie_lanes = ()
    full_n = 0
    persistent = False

    def verify(self):
        n = self.count()
        ok, mask = super().verify()
        if self.persistent or n >= type(self).full_n:
            mask = list(mask)
            for lane in type(self).lie_lanes:
                if lane < n:
                    mask[lane] = False
            ok = all(mask)
        return ok, mask


class TestTriage:
    def _lying(self, lanes, full_n, persistent=False):
        _seq[0] += 1
        name = f"test-liar-{_seq[0]}"
        _LyingVerifier.lie_lanes = tuple(lanes)
        _LyingVerifier.full_n = full_n
        _LyingVerifier.persistent = persistent
        cryptobatch.register_backend(name, _LyingVerifier)
        return BackendSupervisor(
            spec=BackendSpec(name), dispatch_timeout_ms=2000,
            breaker_threshold=3, audit_pct=0, probe_base_ms=10,
            probe_max_ms=80, retry_ms=5,
        )

    def test_genuinely_bad_lanes_convicted_and_attributed(self):
        plan, sup = _faulty()
        items = _make_items(24, b"triage", poison_at=7)
        truth = _cpu_mask(items)
        try:
            before = sup.metrics.device_dispatches.value()
            mask = sup.verify_items(
                items, reason="flush",
                origins=[(8, "consensus", 5), (8, "blocksync", 6),
                         (8, "evidence", 7)],
            )
            assert mask == truth
            passes = sup.metrics.triage_passes.value()
            assert 1 <= passes <= math.ceil(math.log2(24)) + 1
            # device passes observed via the dispatch counter too
            assert sup.metrics.device_dispatches.value() - before \
                == 1 + passes
            offenders = {
                c._labels["subsystem"]: c.value()
                for c in sup.metrics.triage_offenders._series()
                if "subsystem" in c._labels
            }
            assert offenders == {"consensus": 1.0}  # lane 7 = request 1
            assert sup.metrics.triage_divergence.value() == 0
            assert sup.state() == HEALTHY  # a bad signature is not a
            # device incident: the breaker must not move
        finally:
            sup.stop()

    def test_transient_device_lie_cleared_on_reaffirm(self):
        # the device wrongly claims lanes bad once; triage's re-dispatch
        # sees them clean and clears them without any CPU confirmation
        sup = self._lying(lanes=(3, 11), full_n=16)
        items = _make_items(16, b"lie")
        try:
            mask = sup.verify_items(items)
            assert mask == [True] * 16
            assert sup.metrics.triage_runs.value() == 1
            assert sup.metrics.triage_divergence.value() == 0
            assert sup.state() == HEALTHY
        finally:
            sup.stop()

    def test_persistent_device_lie_is_silent_corruption(self):
        # the device insists lane 0 is bad through every bisection pass
        # (lane 0 so the lie survives re-indexed re-dispatches): the CPU
        # ground truth overturns the conviction, the released mask is
        # correct, and the breaker opens (audit cause)
        sup = self._lying(lanes=(0,), full_n=16, persistent=True)
        items = _make_items(16, b"liar")
        try:
            mask = sup.verify_items(items)
            assert mask == [True] * 16  # CPU verdict wins, always
            assert sup.metrics.triage_divergence.value() == 1
            assert sup.state() == BROKEN
            assert sup.metrics.trips.with_labels(
                cause="audit").value() >= 1
        finally:
            sup.stop()

    def test_pass_bound_8k_batch_8_offenders(self):
        plan, sup = _faulty()
        n = 2048  # same shape as the bench's 8k assert, CI-sized
        items = _make_items(n, b"big")
        for lane in range(0, n, n // 8):
            pk, m, _ = items[lane]
            items[lane] = (pk, m, b"\x21" * 64)
        truth = _cpu_mask(items)
        try:
            before = sup.metrics.device_dispatches.value()
            mask = sup.verify_items(items)
            assert mask == truth
            passes = sup.metrics.device_dispatches.value() - before - 1
            assert passes <= math.ceil(math.log2(n)) + 1
        finally:
            sup.stop()

    def test_half_bad_batch_exact_verdicts_and_attribution(self):
        # PR 18 edge: a 50% byzantine flood, invalid lanes interleaved
        # with honest ones — worst case for run-coalescing (every
        # suspect segment is a singleton). Verdicts stay lane-exact,
        # attribution splits exactly across the contributing
        # subsystems, and the breaker never moves for signature crime.
        plan, sup = _faulty()
        n = 64
        items = _make_items(n, b"half")
        for lane in range(1, n, 2):
            pk, m, s = items[lane]
            items[lane] = (pk, m, bytes(s[:-1]) + bytes([s[-1] ^ 1]))
        truth = _cpu_mask(items)
        assert truth.count(False) == n // 2
        try:
            before = sup.metrics.device_dispatches.value()
            mask = sup.verify_items(
                items, reason="flush",
                origins=[(n // 2, "consensus", 9),
                         (n // 2, "blocksync", 9)],
            )
            assert mask == truth
            passes = sup.metrics.device_dispatches.value() - before - 1
            assert 1 <= passes <= math.ceil(math.log2(n)) + 1
            offenders = {
                c._labels["subsystem"]: c.value()
                for c in sup.metrics.triage_offenders._series()
                if "subsystem" in c._labels
            }
            assert offenders == {"consensus": 16.0, "blocksync": 16.0}
            assert sup.metrics.triage_divergence.value() == 0
            assert sup.state() == HEALTHY
        finally:
            sup.stop()

    def test_all_byzantine_flush_convicts_every_lane(self):
        # PR 18 edge: 100% of the flush is invalid — one maximal
        # suspect segment spanning the whole batch. Every lane
        # convicts, the full flush is charged to its origin, the pass
        # bound holds, and no conviction is overturned (so no breaker
        # trip: a byzantine committee is not a device incident).
        plan, sup = _faulty()
        n = 32
        items = _make_items(n, b"allbad")
        for lane in range(n):
            pk, m, s = items[lane]
            items[lane] = (pk, m, bytes(s[:-1]) + bytes([s[-1] ^ 1]))
        try:
            before = sup.metrics.device_dispatches.value()
            mask = sup.verify_items(
                items, reason="flush", origins=[(n, "consensus", 3)],
            )
            assert mask == [False] * n
            passes = sup.metrics.device_dispatches.value() - before - 1
            assert 1 <= passes <= math.ceil(math.log2(n)) + 1
            offenders = {
                c._labels["subsystem"]: c.value()
                for c in sup.metrics.triage_offenders._series()
                if "subsystem" in c._labels
            }
            assert offenders == {"consensus": float(n)}
            assert sup.metrics.triage_divergence.value() == 0
            assert sup.state() == HEALTHY
        finally:
            sup.stop()

    def test_triage_device_death_falls_back_to_cpu(self):
        # the device dies mid-triage: remaining suspects go to the CPU
        # ground truth, verdicts stay exact, no breaker strike for it
        plan, sup = _faulty()
        items = _make_items(16, b"die", poison_at=4)
        truth = _cpu_mask(items)
        plan.die_after = 1  # first dispatch fine, triage passes raise
        try:
            assert sup.verify_items(items) == truth
            assert sup.metrics.triage_cpu_fallbacks.value() == 1
        finally:
            sup.stop()


class TestSchedulerOriginsThreading:
    def test_origins_reach_supervisor(self):
        from cometbft_tpu.crypto.scheduler import VerifyScheduler

        calls = []

        class Spy:
            spec = BackendSpec("cpu")

            @staticmethod
            def state():
                return HEALTHY

            @staticmethod
            def verify_items(items, reason="direct", origins=None):
                calls.append(origins)
                return _cpu_mask(items)

        sched = VerifyScheduler(spec=BackendSpec("cpu"), supervisor=Spy())
        a, b = _make_items(3, b"oa"), _make_items(2, b"ob")
        fa = sched.submit(a, subsystem="consensus", height=42)
        fb = sched.submit(b, subsystem="evidence")
        ok_a, mask_a = fa.result(timeout=5)
        ok_b, _ = fb.result(timeout=5)
        assert ok_a and ok_b and mask_a == [True, True, True]
        # not-running scheduler dispatches inline, one request per call
        assert calls == [
            [(3, "consensus", 42)],
            [(2, "evidence", None)],
        ]


class TestChaosSmoke:
    def test_every_rung_walked_no_divergence(self):
        s = run_chaos_smoke(seed=23)
        assert s["wrong_verdicts"] == 0
        assert s["retries"] >= 1
        assert s["state_after_transient"] == HEALTHY
        assert s["chunk_shrinks"] >= 1
        assert s["shrink_levels_peak"] == mesh.MAX_SHRINK_LEVELS
        assert s["chunk_recoveries"] >= 1
        assert s["hedge_fires"] >= 1
        assert s["hedge_wins"] >= 1
        assert s["hedge_divergence"] == 0
        assert s["triage_runs"] >= 1
        assert s["triage_passes"] >= 1
        assert s["triage_offenders"] == {"blocksync": 1.0}
        assert s["triage_clean_futures_ok"]
        assert not s["triage_tripped_breaker"]
        assert s["triage_divergence"] == 0
        assert s["state_broken"] == BROKEN
        assert s["probe_ok"]
        assert s["state_final"] == HEALTHY
