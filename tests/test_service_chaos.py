"""Verify-as-a-service chaos rung (PR 17) in tier-1.

One daemon (VerifyScheduler + VerifyService on a Unix socket), 36
clients over real sockets: deterministic disconnect containment (four
clients severed mid-flight against a frozen pool), a 2.5x flood with
QoS shed/drop visible to remote tenants as honest rejections, and
bottom-up brownout recovery — the same invariants tools/chaos.py
--service gates on. Mirrors the in-process overload rung's tier-1 test
(tests/test_qos.py::TestChaosOverloadRung)."""


class TestChaosServiceRung:
    def test_service_rung_end_to_end(self):
        from cometbft_tpu.crypto.faults import run_chaos_service

        s = run_chaos_service(seed=29, flood_s=1.0)
        assert s["wrong_verdicts"] == 0, s["wrong_by_phase"]
        assert s["latency_ok"], (
            f"loaded p99 {s['loaded_p99_ms']}ms over bound "
            f"{s['latency_bound_ms']}ms"
        )
        # consensus never shed/dropped while flood tenants were
        assert s["consensus_sheds"] == 0
        assert s["consensus_drops"] == 0
        assert s["flood_sheds"] >= 1
        assert s["flood_drops"] >= 1
        # QoS verdicts crossed the wire as rejections, not CPU bounces
        assert s["rejected"] >= 1
        # disconnect containment: every killed client's in-flight
        # request resolved via the LOCAL fallback with the distinct
        # reason, and the server metered the severed tenants
        assert s["disconnect_fallbacks"] >= 4, s["kill_reasons"]
        assert s["killed_client_fallbacks"] >= 1
        assert s["disconnects_metered"] >= 1
        # overload tripped the brownout; recovery re-admitted bottom-up
        assert s["brownout"]["trips"] >= 1
        assert s["readmitted"]
        assert not s["brownout"]["disabled"]
        # the service drained: no request left behind
        assert s["pending_after"] == 0
        # the wire never grew past the compact bound
        assert s["bytes_per_lane_ok"], s["bytes_per_lane"]
        assert s["bytes_per_lane"]["compact"] == 128.0
        # the incident timeline saw the kill from BOTH sides (server
        # disconnect + client typed fallback) on one ordered clock, and
        # the brownout trip flushed an incident dump embedding the
        # per-tenant service panel
        assert s["timeline_ok"], (
            s["timeline_kill_disconnects"], s["timeline_kill_fallbacks"],
        )
        assert s["incident_dump_ok"]
