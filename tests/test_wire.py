"""Wire-ledger contract tests (crypto/wire.py + the mesh dispatch
instrumentation, scheduler demux feed, calibration cold seed, and the
verify_top / trace_report render surfaces).

The load-bearing acceptance bounds:

* a live dispatch's per-phase sums reconcile with its wall time within
  10% (coverage in [0.9, 1.1]) on a payload large enough that the
  measured phases dominate loop bookkeeping;
* ``CostProfile.predict_ms(route, bucket)`` lands within 2x of a
  subsequently measured dispatch once the profile holds >= 5
  observations (compile-warm; a cold first dispatch would fold the JIT
  wall into the EWMA and wreck the prediction — by design: the ledger
  reports what the wire actually did);
* the chaos rung (faults.run_chaos_wire) attributes an injected slow
  link to the h2d phase, not compute — the ledger's whole point;
* ``verify_wire_*`` conformance lives in test_metrics.py (one strict
  family check per metric plane).
"""

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np
import pytest

from cometbft_tpu.config import Config
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import wire as wirelib
from cometbft_tpu.crypto.batch import BackendSpec
from cometbft_tpu.crypto.faults import run_chaos_wire
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.crypto.telemetry import TelemetryHub
from cometbft_tpu.crypto.tpu import calibrate
from cometbft_tpu.crypto.tpu import mesh
from cometbft_tpu.crypto.wire import (
    CHUNK_PHASES,
    CostProfile,
    WireLedger,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _note_uniform_chunk(ledger, route="single", device="dev0",
                        bucket=256, lanes=200, wire_bytes=32_768,
                        pack_s=1e-4, h2d_s=2e-3, compute_s=5e-4,
                        d2h_s=1e-4, hidden_s=0.0):
    ledger.note_chunk(route, device, bucket, lanes, wire_bytes,
                      pack_s, h2d_s, compute_s, d2h_s, hidden_s=hidden_s)


# ---------------------------------------------------------------------------
# ledger unit behavior
# ---------------------------------------------------------------------------


class TestWireLedgerUnit:
    def test_profile_folds_and_snapshot_shape(self):
        ledger = WireLedger(window=8)
        for _ in range(4):
            _note_uniform_chunk(ledger, hidden_s=1e-3)
        snap = ledger.snapshot()
        assert snap["window"] == 8
        assert snap["chunks"] == 4 and snap["dispatches"] == 0
        (row,) = snap["profiles"]
        assert (row["route"], row["bucket"], row["device"]) == \
            ("single", 256, "dev0")
        assert row["n"] == 4
        for ph in CHUNK_PHASES:
            ent = row["phases_ms"][ph]
            assert set(ent) == {"ewma", "p50", "p99"}
        # identical samples: ewma == p50 == p99
        assert row["phases_ms"]["h2d"]["p50"] == pytest.approx(2.0)
        assert row["phases_ms"]["h2d"]["ewma"] == pytest.approx(2.0)
        assert row["bytes_per_lane"] == pytest.approx(32_768 / 200, rel=0.01)
        # 1ms hidden of 2ms transfer per chunk
        assert row["overlap"] == pytest.approx(0.5)
        # effective bandwidth = bytes / h2d
        assert row["effective_MBps"] == pytest.approx(
            32_768 / 2e-3 / 1e6, rel=0.01
        )

    def test_overlap_clamped_to_transfer_time(self):
        # hidden can never exceed h2d (a clock-skew guard)
        ledger = WireLedger(window=4)
        _note_uniform_chunk(ledger, h2d_s=1e-3, hidden_s=5e-3)
        (row,) = ledger.snapshot()["profiles"]
        assert row["overlap"] == pytest.approx(1.0)

    def test_dispatch_record_reconciliation_fields(self):
        ledger = WireLedger(window=4)
        ledger.note_dispatch(
            "single", "dev0", n=512, wall_s=4e-3,
            pack_s=1e-3, h2d_s=1e-3, compute_s=1.5e-3, d2h_s=5e-4,
            hidden_s=5e-4, wire_bytes=65_536, chunks=2,
        )
        snap = ledger.snapshot()
        assert snap["dispatches"] == 1
        (rec,) = snap["recent"]
        assert rec["wall_ms"] == pytest.approx(4.0)
        assert rec["coverage"] == pytest.approx(1.0)   # phases sum to wall
        assert rec["overlap"] == pytest.approx(0.5)    # half the h2d hidden
        assert rec["bytes"] == 65_536 and rec["chunks"] == 2

    def test_demux_pow2_bucketing(self):
        ledger = WireLedger(window=4)
        ledger.note_demux("cpu", 200, 5e-5)   # 200 sigs -> bucket 256
        ledger.note_demux("cpu", 250, 7e-5)
        ledger.note_demux("single", 8, 1e-5)
        snap = ledger.snapshot()
        assert snap["demux_notes"] == 3
        by_key = {(d["route"], d["bucket"]): d for d in snap["demux"]}
        assert by_key[("cpu", 256)]["n"] == 2
        assert by_key[("single", 8)]["n"] == 1
        assert by_key[("cpu", 256)]["p50_ms"] > 0

    def test_default_ledger_install_and_restore(self):
        ledger = WireLedger(window=4)
        prev = wirelib.set_default_ledger(ledger)
        try:
            assert wirelib.default_ledger() is ledger
            assert wirelib.set_default_ledger(None) is ledger
            assert wirelib.default_ledger() is None
        finally:
            wirelib.set_default_ledger(prev)

    def test_env_knobs_win_over_config(self, monkeypatch):
        monkeypatch.delenv("CBFT_WIRE_LEDGER", raising=False)
        monkeypatch.delenv("CBFT_WIRE_WINDOW", raising=False)
        assert wirelib.wire_ledger_default(True) is True
        assert wirelib.wire_ledger_default(False) is False
        monkeypatch.setenv("CBFT_WIRE_LEDGER", "0")
        assert wirelib.wire_ledger_default(True) is False
        monkeypatch.setenv("CBFT_WIRE_LEDGER", "on")
        assert wirelib.wire_ledger_default(False) is True
        assert wirelib.wire_window_default(32) == 32
        monkeypatch.setenv("CBFT_WIRE_WINDOW", "16")
        assert wirelib.wire_window_default(32) == 16
        monkeypatch.setenv("CBFT_WIRE_WINDOW", "garbage")
        assert wirelib.wire_window_default(32) == 32

    def test_config_validates_wire_knobs(self):
        cfg = Config()
        cfg.validate_basic()
        cfg.instrumentation.wire_window = 0
        with pytest.raises(ValueError):
            cfg.validate_basic()
        cfg.instrumentation.wire_window = 64
        cfg.instrumentation.wire_ledger = "yes"
        with pytest.raises(ValueError):
            cfg.validate_basic()


# ---------------------------------------------------------------------------
# cost queries
# ---------------------------------------------------------------------------


class TestCostProfile:
    def test_empty_ledger_predicts_nothing(self):
        assert WireLedger().predict_ms("single", 256) is None

    def test_cold_seed_from_link_probe(self):
        ledger = WireLedger(window=4)
        ledger.seed_link({
            "platform": "cpu", "kernel_roundtrip_ms": 0.05,
            "effective_MBps": 1000.0, "fixed_latency_ms_est": 0.95,
        })
        pred = ledger.predict_ms("single", 1024)
        # fixed (0.95 + 0.05) + 1024 lanes * 128 B/lane / 1 GB/s
        assert pred == pytest.approx(1.0 + 1024 * 128.0 / 1e9 * 1e3,
                                     rel=0.01)
        # bigger buckets cost strictly more on the same curve
        assert ledger.predict_ms("single", 8192) > pred

    def test_warm_profile_beats_cold_seed(self):
        ledger = WireLedger(window=8)
        ledger.seed_link({"effective_MBps": 1.0,
                          "fixed_latency_ms_est": 500.0})
        for _ in range(6):
            _note_uniform_chunk(ledger, bucket=256)
        # exact-bucket hit: per-chunk phase sum, not the silly cold seed
        pred = ledger.predict_ms("single", 256)
        assert pred == pytest.approx((1e-4 + 2e-3 + 5e-4 + 1e-4) * 1e3,
                                     rel=0.05)
        assert ledger.observations("single", 256) == 6

    def test_nearest_bucket_scales_the_variable_part(self):
        ledger = WireLedger(window=8)
        ledger.seed_link({"fixed_latency_ms_est": 1.0})
        for _ in range(5):
            _note_uniform_chunk(ledger, bucket=1024, h2d_s=4e-3)
        per_chunk = ledger.predict_ms("single", 1024)
        smaller = ledger.predict_ms("single", 256)
        assert smaller is not None and smaller < per_chunk
        # scaled-down lanes keep the fixed latency floor
        assert smaller >= 1.0
        # above the largest measured bucket: split into chunks
        bigger = ledger.predict_ms("single", 4096)
        assert bigger > per_chunk

    def test_cost_profile_wrapper(self):
        ledger = WireLedger(window=4)
        for _ in range(3):
            _note_uniform_chunk(ledger)
        cp = ledger.cost_profile()
        assert isinstance(cp, CostProfile)
        assert cp.predict_ms("single", 256) == \
            ledger.predict_ms("single", 256)
        assert cp.observations("single", 256) == 3


class TestPredictMsEdges:
    """Pinned edge behavior (PR 15): the decision plane prices every
    candidate on every flush through predict_ms, so it must NEVER
    raise and its edges are regression-locked here."""

    def test_unknown_route_falls_to_seed_then_none(self):
        ledger = WireLedger(window=4)
        for _ in range(3):
            _note_uniform_chunk(ledger, route="single")
        # no profile for the route, no link seed: None (not a raise)
        assert ledger.predict_ms("no-such-route", 256) is None
        # with a link seed the unknown route prices off the cold curve
        ledger.seed_link({"effective_MBps": 1000.0,
                          "fixed_latency_ms_est": 1.0})
        pred = ledger.predict_ms("no-such-route", 256)
        assert pred is not None and pred > 0.0

    def test_bucket_below_smallest_observed_keeps_fixed_floor(self):
        ledger = WireLedger(window=8)
        ledger.seed_link({"fixed_latency_ms_est": 1.0})
        for _ in range(5):
            _note_uniform_chunk(ledger, bucket=1024, h2d_s=4e-3)
        per_chunk = ledger.predict_ms("single", 1024)
        tiny = ledger.predict_ms("single", 1)
        # only the size-dependent part scales down: never below the
        # link's fixed latency, never negative
        assert tiny is not None and 1.0 <= tiny <= per_chunk

    def test_bucket_above_largest_never_cheaper_than_one_chunk(self):
        ledger = WireLedger(window=8)
        # pathological overlap: hidden transfer bigger than the chunk
        # itself must not predict a megabatch cheaper than one chunk
        for _ in range(5):
            _note_uniform_chunk(ledger, bucket=256, h2d_s=50e-3,
                                hidden_s=50e-3)
        per_chunk = ledger.predict_ms("single", 256)
        mega = ledger.predict_ms("single", 16384)
        assert mega >= per_chunk

    def test_malformed_bucket_answers_none_never_raises(self):
        ledger = WireLedger(window=4)
        for _ in range(3):
            _note_uniform_chunk(ledger)
        for bad in (None, "256x", object()):
            assert ledger.predict_ms("single", bad) is None
        # and through the CostProfile wrapper the decision plane holds
        assert ledger.cost_profile().predict_ms("single", None) is None

    def test_cold_ledger_every_route_is_none(self):
        ledger = WireLedger()
        for route in ("cpu", "single", "sharded", "indexed",
                      "device_hash"):
            assert ledger.predict_ms(route, 64) is None


# ---------------------------------------------------------------------------
# calibration cold seed (tools/tpu_link_probe.py --merge roundtrip)
# ---------------------------------------------------------------------------


class TestCalibrationSeed:
    PROBE = {
        "platform": "cpu", "kernel_roundtrip_ms": 0.05,
        "put_64KiB_ms": 0.06, "effective_MBps": 6185.6,
        "fixed_latency_ms_est": 0.98, "junk": "ignore-me",
    }

    def test_merge_and_seed_roundtrip(self, tmp_path):
        calibrate.set_table_path(str(tmp_path / "calib.json"))
        try:
            table = calibrate.merge_link_profile(self.PROBE)
            assert table is not None
            link = calibrate.load_link_profile()
            assert link["effective_MBps"] == pytest.approx(6185.6)
            assert link["put_64KiB_ms"] == pytest.approx(0.06)
            assert link["platform"] == "cpu"
            assert "junk" not in link
            assert link["measured_at"] > 0
            ledger = WireLedger(window=4)
            assert wirelib.seed_from_calibration(ledger) is True
            assert ledger.link()["effective_MBps"] == pytest.approx(6185.6)
            assert ledger.predict_ms("single", 1024) is not None
        finally:
            calibrate.set_table_path(None)

    def test_merge_rejects_unusable_probe(self, tmp_path):
        calibrate.set_table_path(str(tmp_path / "calib.json"))
        try:
            assert calibrate.merge_link_profile({"platform": "cpu"}) is None
            assert calibrate.load_link_profile() == {}
            ledger = WireLedger(window=4)
            assert wirelib.seed_from_calibration(ledger) is False
        finally:
            calibrate.set_table_path(None)

    def test_probe_cli_merges(self, tmp_path):
        path = tmp_path / "calib.json"
        res = subprocess.run(
            [sys.executable,
             os.path.join(_REPO, "tools", "tpu_link_probe.py"),
             "--merge", "--calibration", str(path)],
            capture_output=True, text=True, timeout=300, cwd=_REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert res.returncode == 0, res.stderr[-400:]
        # the last stdout line is still the full probe document
        doc = json.loads(res.stdout.strip().splitlines()[-1])
        # effective_MBps is omitted when a loaded host inverts the
        # size/latency slope; the fixed-latency estimate always lands
        assert "fixed_latency_ms_est" in doc
        table = json.loads(path.read_text())
        link = table["link"]
        assert link["fixed_latency_ms_est"] == pytest.approx(
            doc["fixed_latency_ms_est"], abs=0.01
        )
        if "effective_MBps" in doc:
            assert link["effective_MBps"] == pytest.approx(
                doc["effective_MBps"], rel=0.01
            )


# ---------------------------------------------------------------------------
# live mesh dispatch: the acceptance bounds
# ---------------------------------------------------------------------------


def _parity_kernel():
    import jax

    @jax.jit
    def parity(rows):
        return (rows.sum(axis=0) % 2) == 0

    return parity


class TestMeshDispatchAttribution:
    """dispatch_batch feeds the ledger per chunk; the payload here is
    sized so measured phases dominate the chunk loop's bookkeeping
    (tiny payloads legitimately report low coverage — the wall is all
    Python, not wire)."""

    def test_phase_sums_reconcile_and_overlap_reported(self):
        kernel = _parity_kernel()
        rng = np.random.default_rng(7)
        full = rng.integers(0, 100, size=(256, 4096)).astype(np.int32)
        want = (full.sum(axis=0) % 2) == 0
        prev = wirelib.set_default_ledger(None)
        try:
            with mesh.route_scope(mesh.ROUTE_SINGLE):
                # compile-warm with no ledger: the JIT wall is not wire
                mesh.dispatch_batch(kernel, [full], 4096, 1024, 8)
                ledger = WireLedger(window=8)
                wirelib.set_default_ledger(ledger)
                for _ in range(5):
                    out = mesh.dispatch_batch(kernel, [full], 4096, 1024, 8)
        finally:
            wirelib.set_default_ledger(prev)
        assert (out == want).all()
        snap = ledger.snapshot()
        assert snap["dispatches"] == 5
        assert snap["chunks"] == 20  # 4 chunks of 1024 per dispatch
        covs = [r["coverage"] for r in snap["recent"]]
        # acceptance: phase sums reconcile with wall within 10%
        assert max(covs) >= 0.9, f"best coverage {max(covs)} ({covs})"
        assert all(c <= 1.1 for c in covs), covs
        (row,) = snap["profiles"]
        assert (row["route"], row["bucket"]) == ("single", 1024)
        # the double-buffered pipeline hid SOME transfer on chunks 2..4
        assert row["overlap"] is not None and row["overlap"] > 0
        assert row["effective_MBps"] is not None
        assert row["predicted_ms"] is not None

    def test_predict_within_2x_of_measured_after_5_observations(self):
        kernel = _parity_kernel()
        rng = np.random.default_rng(11)
        single = rng.integers(0, 100, size=(256, 1024)).astype(np.int32)
        prev = wirelib.set_default_ledger(None)
        try:
            with mesh.route_scope(mesh.ROUTE_SINGLE):
                mesh.dispatch_batch(kernel, [single], 1024, 1024, 8)
                ledger = WireLedger(window=8)
                wirelib.set_default_ledger(ledger)
                for _ in range(5):
                    mesh.dispatch_batch(kernel, [single], 1024, 1024, 8)
                assert ledger.observations("single", 1024) >= 5
                pred = ledger.predict_ms("single", 1024)
                walls = []
                for _ in range(5):
                    t0 = time.perf_counter()
                    mesh.dispatch_batch(kernel, [single], 1024, 1024, 8)
                    walls.append((time.perf_counter() - t0) * 1e3)
        finally:
            wirelib.set_default_ledger(prev)
        measured = statistics.median(walls)
        assert pred is not None
        assert measured / 2 <= pred <= measured * 2, \
            f"pred {pred:.3f}ms vs measured {measured:.3f}ms"

    def test_uninstalled_ledger_costs_nothing(self):
        # the mesh loop must run identically with no ledger installed
        kernel = _parity_kernel()
        ones = np.ones((2, 17), np.int32)
        prev = wirelib.set_default_ledger(None)
        try:
            with mesh.route_scope(mesh.ROUTE_SINGLE):
                out = mesh.dispatch_batch(kernel, [ones], 17, 16, 8)
        finally:
            wirelib.set_default_ledger(prev)
        assert out.shape == (17,) and out.all()


class TestChaosWireRung:
    def test_jittery_link_attributed_to_transfer(self):
        summary = run_chaos_wire(seed=7, jitter_ms=20.0)
        assert summary["ok"] is True
        assert summary["injected_jitter_ms"] > 0
        assert summary["h2d_delta_ms"] >= 0.5 * summary["injected_jitter_ms"]
        assert summary["compute_delta_ms"] <= max(
            5.0, 0.25 * summary["injected_jitter_ms"]
        )


# ---------------------------------------------------------------------------
# scheduler demux feed + telemetry hub source
# ---------------------------------------------------------------------------


def _make_items(n, tag=b"wire"):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"wire-msg-" + i.to_bytes(4, "big")
        items.append((k.pub_key(), msg, k.sign(msg)))
    return items


class TestSchedulerDemuxFeed:
    def test_flush_notes_demux_phase(self):
        ledger = WireLedger(window=8)
        prev = wirelib.set_default_ledger(ledger)
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=500)
        sched.start()
        try:
            ok, mask = sched.submit(
                _make_items(4), subsystem="blocksync", height=9
            ).result(timeout=60)
        finally:
            sched.stop()
            wirelib.set_default_ledger(prev)
        assert ok and all(mask)
        snap = ledger.snapshot()
        assert snap["demux_notes"] >= 1
        assert any(d["route"] == "cpu" for d in snap["demux"])

    def test_hub_source_lands_in_debug_verify(self):
        hub = TelemetryHub()
        hub.note_request(4, 0.0, 0.001, True, subsystem="light")
        ledger = WireLedger(window=8)
        _note_uniform_chunk(ledger, hidden_s=1e-3)
        ledger.note_demux("cpu", 4, 1e-5)
        hub.register_source("wire", ledger.snapshot)
        wire = hub.snapshot()["sources"]["wire"]
        assert wire["chunks"] == 1 and wire["demux_notes"] == 1
        assert wire["profiles"][0]["bucket"] == 256


# ---------------------------------------------------------------------------
# render surfaces: verify_top wire table, trace_report --wire
# ---------------------------------------------------------------------------


class TestVerifyTopWireTable:
    def test_once_renders_wire_section(self, tmp_path):
        hub = TelemetryHub()
        hub.note_request(4, 0.0, 0.001, True, subsystem="light")
        ledger = WireLedger(window=8)
        ledger.seed_link({"platform": "cpu", "effective_MBps": 6185.6,
                          "fixed_latency_ms_est": 0.98,
                          "kernel_roundtrip_ms": 0.05})
        for _ in range(3):
            _note_uniform_chunk(ledger, hidden_s=1e-3)
        ledger.note_demux("cpu", 200, 5e-5)
        hub.register_source("wire", ledger.snapshot)
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(hub.snapshot()))
        res = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "verify_top.py"),
             str(path), "--once"],
            capture_output=True, text=True, timeout=60, cwd=_REPO,
        )
        assert res.returncode == 0, res.stderr[-400:]
        out = res.stdout
        assert "wire ledger" in out
        assert "overlap" in out and "pred_ms" in out
        assert "50.0%" in out          # 1ms hidden of 2ms h2d
        assert "link ceiling" in out and "6185.6" in out
        assert "demux" in out and "cpu/256" in out
        # the phase bar renders with the h2d glyph dominant
        assert "hh" in out


class TestTraceReportWire:
    @staticmethod
    def _load():
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "trace_report_wire_test",
            os.path.join(_REPO, "tools", "trace_report.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    @staticmethod
    def _chunk_span(span_id, pack_ns, h2d_ns, compute_ns, wait_ns,
                    hidden_ns, pad=1024):
        return {
            "name": "chunk", "span_id": span_id, "parent_id": "1",
            "trace_id": "t1", "start_us": 0.0,
            "dur_us": (pack_ns + h2d_ns + compute_ns + wait_ns) / 1e3,
            "tags": {
                "pad": pad, "pack_ns": pack_ns, "h2d_ns": h2d_ns,
                "compute_ns": compute_ns, "device_wait_ns": wait_ns,
                "hidden_ns": hidden_ns, "host_ns": pack_ns,
            },
        }

    def _dump(self):
        return [{
            "trace_id": "t1", "root": "request", "dur_us": 9000.0,
            "spans": [
                {"name": "request", "span_id": "1", "parent_id": None,
                 "trace_id": "t1", "start_us": 0.0, "dur_us": 9000.0,
                 "tags": {}},
                self._chunk_span("2", 100_000, 2_000_000, 500_000,
                                 100_000, 0),
                self._chunk_span("3", 100_000, 2_000_000, 500_000,
                                 100_000, 1_000_000),
            ],
        }]

    def test_wire_table_per_bucket(self):
        report = self._load()
        rows = report.wire_table(self._dump())
        (row,) = rows
        assert (row["stage"], row["bucket"], row["chunks"]) == \
            ("chunk", 1024, 2)
        assert row["h2d_p50_ms"] == pytest.approx(2.0)
        assert row["pack_p50_ms"] == pytest.approx(0.1)
        # 1ms hidden of 4ms total transfer across the bucket
        assert row["overlap"] == "25.0%"

    def test_stage_table_gains_wire_columns(self):
        report = self._load()
        rows = report.stage_table(self._dump())
        chunk = {r["stage"]: r for r in rows}["chunk"]
        assert chunk["pack_ms"] == pytest.approx(0.2)
        assert chunk["h2d_ms"] == pytest.approx(4.0)
        assert chunk["compute_ms"] == pytest.approx(1.0)
        assert chunk["hidden_ms"] == pytest.approx(1.0)
        # spans without wire tags don't grow the columns
        req = {r["stage"]: r for r in rows}["request"]
        assert "pack_ms" not in req

    def test_render_wire_flag(self):
        report = self._load()
        out = report.render({}, self._dump(), wire=True)
        assert "wire phases per bucket" in out
        assert "25.0%" in out
        out_plain = report.render({}, self._dump())
        assert "wire phases per bucket" not in out_plain


# ---------------------------------------------------------------------------
# bench history: transfer/prepare regressions must read lower-is-better
# ---------------------------------------------------------------------------


class TestBenchHistoryDirection:
    @staticmethod
    def _load():
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "bench_history_wire_test",
            os.path.join(_REPO, "tools", "bench_history.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_wire_phase_leaves_are_lower_is_better(self):
        bh = self._load()
        for leaf in ("h2d_transfer_ms", "result_transfer_ms",
                     "host_prepare_ms", "tpu.breakdown.h2d_transfer_ms"):
            assert bh.direction(leaf) == bh.LOWER_IS_BETTER, leaf
        # throughput leaves keep their direction
        assert bh.direction("sigs_per_sec") == bh.HIGHER_IS_BETTER
