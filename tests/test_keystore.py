"""Generational device key store — staleness must be undispatchable.

Valset rotation, topology generation bumps, and quarantine re-slices
each invalidate the device pubkey table: a stale-generation dispatch
MISSES (indexed path returns None, resident path rebuilds) and never
verifies against old keys or an old device slicing. Runs on the virtual
CPU mesh (conftest.py); the indexed table is single-device only, so
these tests pin n_devices to 1.
"""

import hashlib

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import ed25519_batch as eb
from cometbft_tpu.crypto.tpu import keystore, mesh, topology


def _valset(n, tag=b"ks"):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    pks = [k.pub_key().bytes() for k in keys]
    vid = hashlib.sha256(b"".join(pks)).digest()
    return keys, pks, vid


def _flush(keys, tag=b"vote"):
    msgs = [tag + b" %d" % i for i in range(len(keys))]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return msgs, sigs


def _cpu(pks, msgs, sigs):
    return [
        ed.PubKeyEd25519(p).verify_signature(m, s)
        for p, m, s in zip(pks, msgs, sigs)
    ]


@pytest.fixture
def store(monkeypatch):
    """Single-device view + a store drained before AND after, with the
    topology quarantine state restored so generation bumps made here
    don't leak into other tests' plans."""
    monkeypatch.setattr(mesh, "n_devices", lambda: 1)
    st = keystore.default_store()
    st.invalidate()
    yield st
    st.invalidate()
    topo = topology.default_topology()
    for i in range(len(topo)):
        topo.set_quarantined(i, False)


def _resident(vid, pks, keys, tag=b"seed"):
    """Build (or refresh) the resident entry by running one real commit
    verification through the store."""
    msgs, sigs = _flush(keys, tag)
    got = eb.verify_valset_resident(vid, pks, msgs, sigs)
    assert got == [True] * len(pks)


class TestValsetRotation:
    def test_rotation_is_a_miss_not_a_reuse(self, store):
        keys_a, pks_a, vid_a = _valset(4, b"rot-a")
        _resident(vid_a, pks_a, keys_a)
        base = store.snapshot()["stats"]

        # same flush again: pure hit, no upload
        _resident(vid_a, pks_a, keys_a, b"again")
        s = store.snapshot()["stats"]
        assert s["hits"] == base["hits"] + 1
        assert s["uploads"] == base["uploads"]

        # rotated valset: different digest -> miss + fresh upload,
        # old entry untouched alongside
        keys_b, pks_b, vid_b = _valset(4, b"rot-b")
        _resident(vid_b, pks_b, keys_b)
        snap = store.snapshot()
        assert snap["stats"]["uploads"] == base["uploads"] + 1
        assert len(snap["entries"]) == 2
        gens = [e["generation"] for e in snap["entries"]]
        assert len(set(gens)) == 2, "each upload gets its own generation"

    def test_lru_eviction_at_cache_max(self, store):
        vids = []
        for i in range(keystore.CACHE_MAX + 1):
            keys, pks, vid = _valset(3, b"lru-%d" % i)
            _resident(vid, pks, keys)
            vids.append(vid)
        with store._mtx:
            held = list(store._entries.keys())
        assert len(held) == keystore.CACHE_MAX
        assert vids[0] not in held, "oldest valset evicted"
        assert vids[-1] in held


class TestTopologyGenerationStaleness:
    def test_quarantine_bump_makes_indexed_dispatch_miss(self, store):
        keys, pks, vid = _valset(4, b"topo")
        _resident(vid, pks, keys)
        msgs, sigs = _flush(keys, b"indexed")

        got = keystore.verify_batch_indexed(pks, msgs, sigs)
        assert got == [True] * 4, "fresh entry must serve the flush"

        topo = topology.default_topology()
        assert topo.set_quarantined(0, True), "membership must change"
        before = store.snapshot()["stats"]["stale_drops"]
        assert keystore.verify_batch_indexed(pks, msgs, sigs) is None, (
            "stale-generation dispatch must MISS, not verify against "
            "the old table"
        )
        assert store.snapshot()["stats"]["stale_drops"] == before + 1
        assert store.snapshot()["entries"] == [], "stale entry dropped"

        # un-quarantine: ANOTHER generation bump — rebuilding under the
        # old generation would be just as wrong
        assert topo.set_quarantined(0, False)
        assert keystore.verify_batch_indexed(pks, msgs, sigs) is None

        # resident path rebuilds under the current generation and the
        # indexed path serves again
        _resident(vid, pks, keys, b"rebuilt")
        entry = store.snapshot()["entries"][0]
        assert entry["topo_generation"] == topo.generation()
        assert keystore.verify_batch_indexed(pks, msgs, sigs) == [True] * 4

    def test_stale_entry_never_verifies_old_keys(self, store):
        # Adversarial rotation: entry built from keys A; topology bumps;
        # the SAME valset_id is re-registered with keys B (as a re-slice
        # rebuild would). get() must rebuild from B — returning the
        # cached A-entry would verify A-signed flushes forever.
        keys_a, pks_a, vid = _valset(3, b"stale-a")
        _resident(vid, pks_a, keys_a)

        topology.default_topology().set_quarantined(1, True)

        keys_b, _, _ = _valset(3, b"stale-b")
        pks_b = [k.pub_key().bytes() for k in keys_b]
        msgs, sigs_a = _flush(keys_a, b"old-sig")
        # flush signed by the OLD keys, presented with the NEW valset
        got = eb.verify_valset_resident(vid, pks_b, msgs, sigs_a)
        assert got == [False] * 3, (
            "stale table reuse would have accepted these"
        )
        entry = store.snapshot()["entries"][0]
        assert entry["topo_generation"] == (
            topology.default_topology().generation()
        )
        # and the new keys' own signatures verify against the rebuilt rows
        msgs_b, sigs_b = _flush(keys_b, b"new-sig")
        assert eb.verify_valset_resident(vid, pks_b, msgs_b, sigs_b) == (
            [True] * 3
        )

    def test_explicit_invalidate(self, store):
        keys, pks, vid = _valset(3, b"inv")
        _resident(vid, pks, keys)
        gen0 = store.snapshot()["generation"]
        assert store.invalidate(vid) == 1
        snap = store.snapshot()
        assert snap["entries"] == []
        assert snap["generation"] == gen0 + 1
        assert store.invalidate(vid) == 0, "double-drop is a no-op"


class TestIndexedDispatch:
    def test_verdicts_match_cpu_and_count_lanes(self, store):
        keys, pks, vid = _valset(5, b"idx")
        _resident(vid, pks, keys)
        msgs, sigs = _flush(keys, b"mix")
        bad = bytearray(sigs[2])
        bad[10] ^= 1
        sigs[2] = bytes(bad)

        before = store.snapshot()["stats"]
        got = keystore.verify_batch_indexed(pks, msgs, sigs)
        assert got == _cpu(pks, msgs, sigs)
        assert got == [True, True, False, True, True]
        s = store.snapshot()["stats"]
        assert s["indexed_dispatches"] == before["indexed_dispatches"] + 1
        assert s["indexed_lanes"] == before["indexed_lanes"] + 5

    def test_repeated_lanes_gather_same_row(self, store):
        # one validator signing several lanes — the index vector repeats
        keys, pks, vid = _valset(3, b"rep")
        _resident(vid, pks, keys)
        k = keys[1]
        msgs = [b"dup %d" % i for i in range(4)]
        sigs = [k.sign(m) for m in msgs]
        got = keystore.verify_batch_indexed(
            [pks[1]] * 4, msgs, sigs
        )
        assert got == [True] * 4

    def test_unknown_key_falls_back(self, store):
        keys, pks, vid = _valset(3, b"fb")
        _resident(vid, pks, keys)
        stranger = ed.gen_priv_key_from_secret(b"fb-stranger")
        msgs, sigs = _flush(keys + [stranger], b"fall")
        assert keystore.verify_batch_indexed(
            pks + [stranger.pub_key().bytes()], msgs, sigs
        ) is None, "flush not fully covered by one entry -> fallback"

    def test_sharded_mesh_falls_back(self, store, monkeypatch):
        keys, pks, vid = _valset(3, b"sh")
        _resident(vid, pks, keys)
        msgs, sigs = _flush(keys)
        monkeypatch.setattr(mesh, "n_devices", lambda: 2)
        assert keystore.verify_batch_indexed(pks, msgs, sigs) is None

    def test_empty_flush(self, store):
        assert keystore.verify_batch_indexed([], [], []) == []


class TestSnapshotPlumbing:
    def test_scheduler_snapshot_carries_keystore(self, store):
        from cometbft_tpu.crypto.batch import BackendSpec
        from cometbft_tpu.crypto.scheduler import VerifyScheduler

        keys, pks, vid = _valset(3, b"snap")
        _resident(vid, pks, keys)
        s = VerifyScheduler(spec=BackendSpec("cpu"))
        snap = s.queue_snapshot()  # not started: snapshot still works
        assert "keystore" in snap
        assert snap["keystore"]["entries"][0]["keys"] == 3
        assert set(snap["keystore"]["stats"]) >= {
            "hits", "misses", "uploads", "stale_drops",
            "indexed_dispatches",
        }

    def test_residency_summary_for_decision_plane(self, store):
        # PR 15: the cheap per-flush summary the decision ledger embeds
        # in every RouteDecision (and the telemetry keystore source)
        empty = store.residency()
        assert empty["entries"] == 0 and empty["keys"] == 0
        # stats survive invalidate(): hit_rate is None only on a virgin
        # store, else a ratio
        assert empty["hit_rate"] is None or 0.0 <= empty["hit_rate"] <= 1.0
        keys, pks, vid = _valset(4, b"resid")
        _resident(vid, pks, keys)
        msgs, sigs = _flush(keys, b"resid-hit")
        assert eb.verify_valset_resident(vid, pks, msgs, sigs) == \
            [True] * 4
        res = store.residency()
        assert res["entries"] == 1 and res["keys"] == 4
        assert res["generation"] >= 1
        assert 0.0 < res["hit_rate"] <= 1.0
        assert isinstance(res["indexed_dispatches"], int)


class TestChurnThrash:
    """PR 18: valset churn faster than flushes drain the cache must
    never yank an in-flight table (pins) and must be visible as the
    ``keystore_thrash`` counter (evictions of never-hit entries)."""

    def _pks(self, tag, n=3):
        return [hashlib.sha256(tag + b"-%d" % i).digest()
                for i in range(n)]

    def test_pinned_entry_survives_lru_pressure(self, store):
        vid_a = hashlib.sha256(b"pin-a").digest()
        store.register(vid_a, self._pks(b"pin-a"))
        assert store.pin(vid_a)
        try:
            # churn well past CACHE_MAX while the dispatch is in flight
            for i in range(keystore.CACHE_MAX + 2):
                vid = hashlib.sha256(b"pin-press-%d" % i).digest()
                store.register(vid, self._pks(b"pin-press-%d" % i))
            with store._mtx:
                held = set(store._entries.keys())
            assert vid_a in held, "pinned entry yanked under pressure"
            assert len(held) == keystore.CACHE_MAX
        finally:
            store.unpin(vid_a)
        # eviction resumes once the dispatch lands: the next insert
        # takes out the (now oldest, unpinned) formerly-pinned entry
        vid_z = hashlib.sha256(b"pin-z").digest()
        store.register(vid_z, self._pks(b"pin-z"))
        with store._mtx:
            held = set(store._entries.keys())
        assert vid_a not in held
        assert vid_z in held

    def test_pin_context_manager_balances(self, store):
        vid = hashlib.sha256(b"pin-ctx").digest()
        store.register(vid, self._pks(b"pin-ctx"))
        with store.pinned(vid) as ok:
            assert ok
            with store._mtx:
                assert store._entries[vid].pins == 1
        with store._mtx:
            assert store._entries[vid].pins == 0
        # pinning a missing entry reports False and never raises
        with store.pinned(b"\x00" * 32) as ok:
            assert not ok

    def test_thrash_counts_never_hit_evictions(self, store):
        base = store.residency()["thrash"]
        # the adversary's churn shape: rotate valsets faster than any
        # flush touches them — every eviction is of a never-hit entry
        for i in range(keystore.CACHE_MAX + 3):
            vid = hashlib.sha256(b"thrash-%d" % i).digest()
            store.register(vid, self._pks(b"thrash-%d" % i))
        assert store.residency()["thrash"] == base + 3

    def test_served_entries_do_not_count_as_thrash(self, store):
        base = store.residency()["thrash"]
        # entries that served at least one flush are working-set
        # turnover, not thrash
        vids = []
        for i in range(keystore.CACHE_MAX):
            vid = hashlib.sha256(b"used-%d" % i).digest()
            store.register(vid, self._pks(b"used-%d" % i))
            store.register(vid, self._pks(b"used-%d" % i))  # a hit
            vids.append(vid)
        for i in range(keystore.CACHE_MAX):
            vid = hashlib.sha256(b"churn-%d" % i).digest()
            store.register(vid, self._pks(b"churn-%d" % i))
        with store._mtx:
            held = set(store._entries.keys())
        assert all(v not in held for v in vids), "all churned out"
        assert store.residency()["thrash"] == base
