"""Verify-path tracing: span core, flight recorder, exporters, the
instrumented scheduler/supervisor/mesh pipeline, incident dumps, and the
tools/trace_report.py CLI.

The end-to-end acceptance test drives a REAL TPU-kernel dispatch (on the
virtual CPU-device mesh the conftest configures) through scheduler →
supervisor → mesh so the recorded trace carries request → dispatch →
supervise → device → chunk nesting with nonzero device-time attribution,
then trips the watchdog to produce the automatic flight-recorder dump
and renders it through the report CLI and the Chrome exporter.
"""

import glob
import importlib.util
import json
import os
import threading
import time

import pytest

from cometbft_tpu.libs import trace as tracelib
from cometbft_tpu.libs.metrics import Registry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dumps(dirpath, reason="watchdog"):
    """Incident dump files for ``reason`` in ``dirpath``, oldest first
    (filenames embed a nanosecond timestamp, so name order = time order)."""
    return sorted(
        glob.glob(os.path.join(str(dirpath), f"trace_dump_{reason}_*.json"))
    )


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(_REPO, "tools", "trace_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _mk_items(n=4, secret=b"trace-test"):
    from cometbft_tpu.crypto import ed25519 as ed

    k = ed.gen_priv_key_from_secret(secret)
    m = b"trace test message"
    sig = k.sign(m)
    return [(k.pub_key(), m, sig)] * n


# ---------------------------------------------------------------------------
# span core


class TestSpanCore:
    def test_lifecycle_nesting_and_parent_ids(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        root = tr.start_span("request", n_sigs=4)
        assert not root.noop
        child = root.child("dispatch", reason="explicit")
        grand = child.child("chunk", chunk=0)
        assert child.trace_id == root.trace_id == grand.trace_id
        grand.end()
        child.end()
        assert tr.recent() == []  # trace completes only when the ROOT ends
        root.end(ok=True)
        traces = tr.recent()
        assert len(traces) == 1
        spans = traces[0]["spans"]
        assert [s["name"] for s in spans] == ["request", "dispatch", "chunk"]
        by_name = {s["name"]: s for s in spans}
        assert by_name["request"]["parent_id"] is None
        assert by_name["dispatch"]["parent_id"] == by_name["request"]["span_id"]
        assert by_name["chunk"]["parent_id"] == by_name["dispatch"]["span_id"]
        assert by_name["request"]["tags"] == {"n_sigs": 4, "ok": True}
        assert all(s["dur_us"] >= 0 for s in spans)

    def test_context_manager_tags_errors(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        with pytest.raises(RuntimeError):
            with tr.start_span("request") as sp:
                sp.set_tag("k", "v")
                raise RuntimeError("boom")
        (trace,) = tr.recent()
        tags = trace["spans"][0]["tags"]
        assert tags["k"] == "v"
        assert "boom" in tags["error"]

    def test_end_is_idempotent_first_wins(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        sp = tr.start_span("request")
        sp.end(outcome="first")
        sp.end(outcome="second")
        (trace,) = tr.recent()
        assert trace["spans"][0]["tags"]["outcome"] == "first"
        assert len(tr.recent()) == 1  # no double-complete

    def test_ring_buffer_eviction(self):
        tr = tracelib.Tracer(sample=1.0, buffer=4)
        for i in range(10):
            tr.start_span("request", i=i).end()
        traces = tr.recent()
        assert len(traces) == 4
        # newest first, oldest evicted
        assert [t["spans"][0]["tags"]["i"] for t in traces] == [9, 8, 7, 6]

    def test_straggler_ending_after_root_is_dropped(self):
        tr = tracelib.Tracer(sample=1.0, buffer=4)
        root = tr.start_span("request")
        zombie = root.child("chunk")
        root.end()
        zombie.end()  # late: its trace already completed
        (trace,) = tr.recent()
        assert [s["name"] for s in trace["spans"]] == ["request"]

    def test_sampling_zero_is_noop_fast_path(self):
        tr = tracelib.Tracer(sample=0.0, buffer=8)
        sp = tr.start_span("request", n_sigs=4)
        assert sp is tracelib.NOOP_SPAN
        assert sp.child("dispatch") is tracelib.NOOP_SPAN
        sp.set_tag("k", "v")
        sp.end()
        assert tr.recent() == []
        assert tr.n_started == 0

    def test_sampling_fraction_deterministic(self):
        tr = tracelib.Tracer(sample=0.5, buffer=1024, seed=7)
        for _ in range(200):
            tr.start_span("request").end()
        n = len(tr.recent())
        assert 0 < n < 200
        assert n == tr.n_started

    def test_child_through_explicit_parent_ignores_sampling(self):
        # once a root is sampled, children always record regardless of
        # the sampling fraction
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        root = tr.start_span("request")
        child = tr.start_span("dispatch", parent=root)
        child.end()
        root.end()
        (trace,) = tr.recent()
        assert len(trace["spans"]) == 2

    def test_thread_safety(self):
        tr = tracelib.Tracer(sample=1.0, buffer=64)
        errs = []

        def work(tid):
            try:
                for i in range(50):
                    root = tr.start_span("request", tid=tid, i=i)
                    with tracelib.use(root):
                        tracelib.child_of_current("dispatch").end()
                    root.end()
            except Exception as exc:  # noqa: BLE001
                errs.append(exc)

        ts = [threading.Thread(target=work, args=(t,)) for t in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs
        traces = tr.recent()
        assert len(traces) == 64  # buffer full, 8*50 completed total
        assert tr.n_completed == 400
        for t in traces:
            assert [s["name"] for s in t["spans"]] == ["request", "dispatch"]

    def test_use_and_child_of_current(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        assert tracelib.current_span() is None
        assert tracelib.child_of_current("x") is tracelib.NOOP_SPAN
        root = tr.start_span("request")
        with tracelib.use(root):
            assert tracelib.current_span() is root
            child = tracelib.child_of_current("dispatch")
            assert child.parent_id == root.span_id
            with tracelib.use(child):
                assert tracelib.current_span() is child
            assert tracelib.current_span() is root
            child.end()
        assert tracelib.current_span() is None
        root.end()

    def test_noop_current_span_yields_noop_children(self):
        with tracelib.use(tracelib.NOOP_SPAN):
            assert tracelib.child_of_current("chunk") is tracelib.NOOP_SPAN

    def test_tracer_span_roots_when_no_current(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        sp = tr.span("supervise")
        assert sp.parent_id is None
        sp.end()
        assert len(tr.recent()) == 1


# ---------------------------------------------------------------------------
# knobs + exporters


class TestKnobsAndExporters:
    def test_sample_knob_precedence(self, monkeypatch):
        monkeypatch.delenv("CBFT_TRACE_SAMPLE", raising=False)
        assert tracelib.trace_sample_default() == 0.0
        assert tracelib.trace_sample_default(0.25) == 0.25
        monkeypatch.setenv("CBFT_TRACE_SAMPLE", "0.75")
        assert tracelib.trace_sample_default(0.25) == 0.75
        monkeypatch.setenv("CBFT_TRACE_SAMPLE", "junk")
        assert tracelib.trace_sample_default(0.25) == 0.25

    def test_buffer_knob_precedence(self, monkeypatch):
        monkeypatch.delenv("CBFT_TRACE_BUFFER", raising=False)
        assert tracelib.trace_buffer_default() == tracelib.DEFAULT_BUFFER
        assert tracelib.trace_buffer_default(32) == 32
        monkeypatch.setenv("CBFT_TRACE_BUFFER", "8")
        assert tracelib.trace_buffer_default(32) == 8

    def test_config_trace_knobs_roundtrip_and_validation(self, tmp_path):
        from cometbft_tpu.config import (
            Config,
            load_config_file,
            write_config_file,
        )

        cfg = Config()
        cfg.instrumentation.trace_sample = 0.125
        cfg.instrumentation.trace_buffer = 64
        cfg.validate_basic()
        path = str(tmp_path / "config.toml")
        write_config_file(path, cfg)
        # floats must survive TOML round-trip AS floats (regression: the
        # writer used to quote them into strings)
        loaded = load_config_file(path)
        assert loaded.instrumentation.trace_sample == 0.125
        assert loaded.instrumentation.trace_buffer == 64
        loaded.validate_basic()
        for bad in (-0.1, 1.5, "half", True):
            cfg.instrumentation.trace_sample = bad
            with pytest.raises(ValueError):
                cfg.validate_basic()
        cfg.instrumentation.trace_sample = 0.5
        for bad in (0, -1, "many", 1.5):
            cfg.instrumentation.trace_buffer = bad
            with pytest.raises(ValueError):
                cfg.validate_basic()

    def test_chrome_trace_schema(self):
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        root = tr.start_span("request", n_sigs=4, blob=b"\x00")
        root.child("dispatch").end()
        root.end()
        doc = tracelib.chrome_trace(tr.recent())
        # must be valid JSON end to end (bytes tags coerced)
        parsed = json.loads(json.dumps(doc))
        assert parsed["displayTimeUnit"] == "ms"
        events = parsed["traceEvents"]
        xevents = [e for e in events if e["ph"] == "X"]
        assert len(xevents) == 2
        for e in xevents:
            for key in ("name", "cat", "ph", "pid", "tid", "ts", "dur", "args"):
                assert key in e, key
            assert e["dur"] > 0
        # the child is time-contained in the root (how "X" events nest)
        byname = {e["name"]: e for e in xevents}
        req, dis = byname["request"], byname["dispatch"]
        assert req["ts"] <= dis["ts"]
        assert dis["ts"] + dis["dur"] <= req["ts"] + req["dur"] + 0.01

    def test_stage_histogram_in_registry_expose(self):
        reg = Registry()
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        tracelib.attach_stage_metrics(tr, reg)
        root = tr.start_span("request")
        root.child("dispatch").end()
        root.end()
        text = reg.expose()
        assert "verify_trace_stage_seconds_bucket" in text
        assert 'stage="request"' in text
        assert 'stage="dispatch"' in text
        assert 'verify_trace_stage_seconds_count{stage="request"} 1' in text

    def test_dump_to_configured_dir_and_env_override(self, tmp_path, monkeypatch):
        monkeypatch.delenv("CBFT_TRACE_DUMP_DIR", raising=False)
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        tr.start_span("request").end()
        assert tr.dump("nowhere") is None  # no destination configured
        tr.set_dump_dir(str(tmp_path / "cfg"))
        p1 = tr.dump("watchdog")
        assert p1 in _dumps(tmp_path / "cfg")
        doc = json.load(open(p1))
        assert doc["reason"] == "watchdog"
        assert len(doc["traces"]) == 1
        envdir = tmp_path / "env"
        monkeypatch.setenv("CBFT_TRACE_DUMP_DIR", str(envdir))
        p2 = tr.dump("watchdog")
        assert p2 in _dumps(envdir)
        assert _dumps(tmp_path / "cfg")  # cfg dump untouched

    def test_dump_retention_keeps_newest_n(self, tmp_path, monkeypatch):
        monkeypatch.delenv("CBFT_TRACE_DUMP_DIR", raising=False)
        monkeypatch.delenv("CBFT_TRACE_DUMP_KEEP", raising=False)
        tr = tracelib.Tracer(sample=1.0, buffer=8, dump_keep=3)
        tr.start_span("request").end()
        tr.set_dump_dir(str(tmp_path))
        paths = [tr.dump(f"cause{i}") for i in range(6)]
        assert all(paths)
        left = sorted(
            glob.glob(str(tmp_path / "trace_dump_*.json"))
        )
        assert len(left) == 3
        # the newest three survived, oldest three were pruned
        assert set(left) == set(paths[-3:])

    def test_dump_keep_env_overrides(self, monkeypatch):
        monkeypatch.setenv("CBFT_TRACE_DUMP_KEEP", "7")
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        assert tr.dump_keep == 7
        monkeypatch.delenv("CBFT_TRACE_DUMP_KEEP")
        assert tracelib.Tracer(sample=0).dump_keep == (
            tracelib.DEFAULT_DUMP_KEEP
        )

    def test_explicit_path_write_does_not_prune(self, tmp_path):
        tr = tracelib.Tracer(sample=1.0, buffer=8, dump_keep=1)
        tr.start_span("request").end()
        tr.set_dump_dir(str(tmp_path))
        auto = tr.dump("auto")
        assert auto and os.path.exists(auto)
        # an explicit-path write is caller-owned: verbatim filename, no
        # retention sweep of the surrounding directory
        pinned = str(tmp_path / "trace_dump_pinned.json")
        assert tr.dump("pinned", path=pinned) == pinned
        assert os.path.exists(auto)


# ---------------------------------------------------------------------------
# scheduler integration


class TestSchedulerTracing:
    def _scheduler(self, tracer, **kw):
        from cometbft_tpu.crypto.scheduler import VerifyScheduler

        kw.setdefault("flush_us", 100)
        return VerifyScheduler(spec="cpu", tracer=tracer, **kw)

    def test_request_and_dispatch_spans(self):
        tr = tracelib.Tracer(sample=1.0, buffer=16)
        sched = self._scheduler(tr)
        sched.start()
        try:
            fut = sched.submit(_mk_items(3), subsystem="consensus", height=42)
            ok, _ = fut.result(timeout=10)
            assert ok
        finally:
            sched.stop()
        traces = [
            t for t in tr.recent()
            if any(s["name"] == "dispatch" for s in t["spans"])
        ]
        assert traces
        spans = {s["name"]: s for s in traces[0]["spans"]}
        req = spans["request"]
        assert req["tags"]["n_sigs"] == 3
        assert req["tags"]["subsystem"] == "consensus"
        assert req["tags"]["height"] == 42
        assert req["tags"]["ok"] is True
        assert "wait_us" in req["tags"]
        dis = spans["dispatch"]
        assert dis["parent_id"] == req["span_id"]
        assert dis["tags"]["reason"] in (
            "deadline", "size", "explicit", "drain", "broken"
        )
        assert dis["tags"]["n_sigs"] == 3
        assert 0 < dis["tags"]["lane_fill"] <= 1.0

    def test_coalesced_requests_link_to_dispatch(self):
        tr = tracelib.Tracer(sample=1.0, buffer=16)
        sched = self._scheduler(tr, flush_us=50_000)
        sched.start()
        try:
            f1 = sched.submit(_mk_items(2))
            f2 = sched.submit(_mk_items(2))
            sched.flush()
            f1.result(timeout=10)
            f2.result(timeout=10)
        finally:
            sched.stop()
        traces = tr.recent()
        hosts = [
            t for t in traces
            if any(s["name"] == "dispatch" for s in t["spans"])
        ]
        riders = [
            t for t in traces
            if t["spans"]
            and t["spans"][0]["name"] == "request"
            and "dispatch_span" in t["spans"][0]["tags"]
        ]
        # one request hosted the dispatch span; the coalesced sibling
        # links to it by tag (spans form a tree, traces stay separate)
        assert len(hosts) == 1
        assert len(riders) == 1
        did = hosts[0]
        dispatch_id = next(
            s["span_id"] for s in did["spans"] if s["name"] == "dispatch"
        )
        assert riders[0]["spans"][0]["tags"]["dispatch_span"] == dispatch_id

    def test_disabled_mode_records_nothing(self):
        tr = tracelib.Tracer(sample=0.0, buffer=16)
        sched = self._scheduler(tr)
        sched.start()
        try:
            for _ in range(3):
                ok, _ = sched.submit(_mk_items(2)).result(timeout=10)
                assert ok
        finally:
            sched.stop()
        assert tr.recent() == []
        assert tr.n_started == 0  # the no-op path never allocated a span

    def test_empty_submit_and_inline_dispatch_spans(self):
        tr = tracelib.Tracer(sample=1.0, buffer=16)
        sched = self._scheduler(tr)  # NOT started: inline dispatch path
        ok, mask = sched.submit(_mk_items(2)).result(timeout=5)
        assert ok and mask == [True, True]
        ok, mask = sched.submit([]).result(timeout=5)
        assert ok and mask == []
        names = [
            s["name"] for t in tr.recent() for s in t["spans"]
        ]
        assert names.count("request") == 2
        assert names.count("dispatch") == 1  # empty submit never dispatches


# ---------------------------------------------------------------------------
# supervisor integration + incident dumps


class TestSupervisorTracing:
    def test_watchdog_trip_writes_flight_recorder_dump(self, tmp_path):
        from cometbft_tpu.crypto import faults
        from cometbft_tpu.crypto.supervisor import BackendSupervisor

        tr = tracelib.Tracer(sample=1.0, buffer=16)
        tr.set_dump_dir(str(tmp_path))
        plan = faults.install(
            "trace-wd", inner="cpu", plan=faults.FaultPlan()
        )
        sup = BackendSupervisor(
            spec="trace-wd",
            dispatch_timeout_ms=200,
            audit_pct=0,
            tracer=tr,
        )
        items = _mk_items(4)
        # healthy dispatch first so the recorder has a completed trace
        assert sup.verify_items(items) == [True] * 4
        plan.hang_rate = 1.0
        plan.hang_s = 30.0
        mask = sup.verify_items(items)  # watchdog fires; CPU fallback
        assert mask == [True] * 4
        assert sup.state() == "broken"
        dumps = _dumps(tmp_path)
        assert dumps
        doc = json.load(open(dumps[-1]))
        assert doc["reason"] == "watchdog"
        assert doc["traces"]  # the healthy dispatch made it in
        # the dump is written at trip time, so it holds the COMPLETED
        # healthy trace (the hanging request's root is still open)
        names = {
            s["name"] for t in doc["traces"] for s in t["spans"]
        }
        assert {"supervise", "device"} <= names
        sup.stop()
        plan.clear()

    def test_supervise_span_outcomes(self):
        from cometbft_tpu.crypto import faults
        from cometbft_tpu.crypto.supervisor import BackendSupervisor

        tr = tracelib.Tracer(sample=1.0, buffer=16)
        plan = faults.install(
            "trace-outcome", inner="cpu",
            plan=faults.FaultPlan(exception_rate=1.0),
        )
        sup = BackendSupervisor(
            spec="trace-outcome",
            breaker_threshold=1,
            audit_pct=0,
            tracer=tr,
        )
        items = _mk_items(2)
        assert sup.verify_items(items) == [True, True]  # fails → CPU
        assert sup.state() == "broken"
        assert sup.verify_items(items) == [True, True]  # broken → routed
        outcomes = [
            t["spans"][0]["tags"].get("outcome")
            for t in tr.recent()
            if t["spans"][0]["name"] == "supervise"
        ]
        assert "failure_cpu" in outcomes
        assert "cpu_routed" in outcomes
        sup.stop()
        plan.clear()


# ---------------------------------------------------------------------------
# end-to-end acceptance: TPU dispatch nesting + dump + chrome + report


class TestEndToEnd:
    def test_tpu_trace_dump_chrome_export_and_report(self, tmp_path, capsys):
        from cometbft_tpu.crypto import faults
        from cometbft_tpu.crypto.batch import BackendSpec
        from cometbft_tpu.crypto.scheduler import VerifyScheduler
        from cometbft_tpu.crypto.supervisor import BackendSupervisor

        tracer = tracelib.Tracer(sample=1.0, buffer=64)
        tracer.set_dump_dir(str(tmp_path))

        # 1. a traced coalesced dispatch through the REAL device path
        #    (virtual CPU-device mesh; min_batch=1 forces device routing)
        spec = BackendSpec(name="tpu", min_batch=1)
        sup = BackendSupervisor(spec=spec, audit_pct=0, tracer=tracer)
        sched = VerifyScheduler(
            spec=spec, supervisor=sup, tracer=tracer, flush_us=100
        )
        sched.start()
        try:
            fut = sched.submit(
                _mk_items(8), subsystem="blocksync", height=11
            )
            ok, mask = fut.result(timeout=300)
            assert ok and mask == [True] * 8
        finally:
            sched.stop()
            sup.stop()

        # 2. watchdog trip through a hanging backend sharing the SAME
        #    tracer → automatic flight-recorder dump includes the device
        #    trace recorded above
        plan = faults.install(
            "trace-e2e", inner="cpu",
            plan=faults.FaultPlan(hang_rate=1.0, hang_s=30.0),
        )
        sup2 = BackendSupervisor(
            spec="trace-e2e",
            dispatch_timeout_ms=150,
            audit_pct=0,
            tracer=tracer,
        )
        assert sup2.verify_items(_mk_items(2)) == [True, True]
        assert sup2.state() == "broken"
        sup2.stop()
        plan.clear()

        dumps = _dumps(tmp_path)
        assert dumps
        dump_path = dumps[-1]
        doc = json.load(open(dump_path))
        assert doc["reason"] == "watchdog"

        # request → dispatch → supervise → device → chunk parent chain
        # with nonzero device-time attribution
        target = None
        for t in doc["traces"]:
            names = {s["name"] for s in t["spans"]}
            if {"request", "dispatch", "device", "chunk"} <= names:
                target = t
                break
        assert target is not None, "no fully-nested device trace in dump"
        by_id = {s["span_id"]: s for s in target["spans"]}
        chunk = next(s for s in target["spans"] if s["name"] == "chunk")
        chain = [chunk["name"]]
        cur = chunk
        while cur["parent_id"] is not None:
            cur = by_id[cur["parent_id"]]
            chain.append(cur["name"])
        assert chain == [
            "chunk", "device", "supervise", "dispatch", "request"
        ]
        assert chunk["tags"]["device_wait_ns"] > 0
        assert chunk["tags"]["host_ns"] > 0
        req = next(s for s in target["spans"] if s["name"] == "request")
        assert req["tags"]["subsystem"] == "blocksync"
        assert req["tags"]["height"] == 11

        # Chrome export: valid trace-event JSON, chunk time-contained in
        # its dispatch on the same tid
        chrome = tracelib.chrome_trace(doc["traces"])
        parsed = json.loads(json.dumps(chrome))
        assert parsed["traceEvents"]
        for e in parsed["traceEvents"]:
            assert e["ph"] in ("X", "M")
            if e["ph"] == "X":
                assert e["dur"] > 0 and "ts" in e
        xev = [e for e in parsed["traceEvents"] if e["ph"] == "X"]
        chunk_ev = next(e for e in xev if e["name"] == "chunk")
        disp_ev = next(
            e for e in xev
            if e["name"] == "dispatch" and e["tid"] == chunk_ev["tid"]
        )
        assert disp_ev["ts"] <= chunk_ev["ts"]
        assert (
            chunk_ev["ts"] + chunk_ev["dur"]
            <= disp_ev["ts"] + disp_ev["dur"] + 0.01
        )

        # trace_report renders a per-stage breakdown from the dump
        report = _load_trace_report()
        rows = report.stage_table(doc["traces"])
        stages = {r["stage"] for r in rows}
        assert {"request", "dispatch", "supervise", "device", "chunk"} <= stages
        chunk_row = next(r for r in rows if r["stage"] == "chunk")
        assert chunk_row["device_ms"] > 0
        chrome_out = str(tmp_path / "report_chrome.json")
        rc = report.main([dump_path, "--top", "2", "--chrome", chrome_out])
        assert rc == 0
        out = capsys.readouterr().out
        assert "per-stage latency breakdown" in out
        assert "chunk" in out and "watchdog" in out
        json.load(open(chrome_out))


# ---------------------------------------------------------------------------
# trace_report unit tests (synthetic dump)


def _synthetic_dump():
    def span(name, span_id, parent, start, dur, **tags):
        return {
            "name": name, "span_id": span_id, "parent_id": parent,
            "trace_id": "t1", "start_us": start, "dur_us": dur,
            "tags": tags,
        }

    return {
        "reason": "watchdog",
        "wall_time": "2026-01-01T00:00:00Z",
        "traces": [
            {
                "trace_id": "t1", "root": "request", "dur_us": 900.0,
                "spans": [
                    span("request", "1", None, 0.0, 900.0, n_sigs=8),
                    span("dispatch", "2", "1", 100.0, 700.0,
                         reason="deadline"),
                    span("chunk", "3", "2", 150.0, 500.0,
                         device_wait_ns=400000, host_ns=50000),
                ],
            },
            {
                "trace_id": "t2", "root": "request", "dur_us": 300.0,
                "spans": [span("request", "1", None, 0.0, 300.0)],
            },
        ],
    }


class TestTraceReport:
    def test_stage_table_and_slowest(self):
        report = _load_trace_report()
        dump = _synthetic_dump()
        rows = report.stage_table(dump["traces"])
        by_stage = {r["stage"]: r for r in rows}
        assert by_stage["request"]["count"] == 2
        assert by_stage["request"]["max_us"] == 900.0
        assert by_stage["chunk"]["device_ms"] == 0.4
        assert by_stage["chunk"]["host_ms"] == 0.05
        top = report.slowest(dump["traces"], 1)
        assert len(top) == 1 and top[0]["trace_id"] == "t1"

    def test_load_traces_shapes(self, tmp_path):
        report = _load_trace_report()
        dump = _synthetic_dump()
        p = tmp_path / "dump.json"
        p.write_text(json.dumps(dump))
        meta, traces = report.load_traces(str(p))
        assert meta["reason"] == "watchdog"
        assert len(traces) == 2
        p2 = tmp_path / "bare.json"
        p2.write_text(json.dumps(dump["traces"]))
        meta2, traces2 = report.load_traces(str(p2))
        assert meta2 == {} and len(traces2) == 2
        p3 = tmp_path / "bad.json"
        p3.write_text('{"not": "traces"}')
        with pytest.raises(ValueError):
            report.load_traces(str(p3))

    def test_cli_main_renders_and_exports(self, tmp_path, capsys):
        report = _load_trace_report()
        p = tmp_path / "dump.json"
        p.write_text(json.dumps(_synthetic_dump()))
        out_chrome = tmp_path / "chrome.json"
        rc = report.main([str(p), "--top", "1", "--chrome", str(out_chrome)])
        assert rc == 0
        out = capsys.readouterr().out
        assert "reason=watchdog" in out
        assert "chunk" in out
        doc = json.load(open(out_chrome))
        assert doc["traceEvents"]
        assert report.main([str(tmp_path / "missing.json")]) == 1
        assert "error:" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# /debug/traces HTTP routes


class TestDebugRoutes:
    def test_metrics_server_serves_traces_and_chrome(self):
        import urllib.request

        from cometbft_tpu.libs.metrics import MetricsServer

        reg = Registry()
        tr = tracelib.Tracer(sample=1.0, buffer=8)
        for i in range(3):
            root = tr.start_span("request", i=i)
            root.child("dispatch").end()
            root.end()
        srv = MetricsServer(reg, tracer=tr)
        port = srv.serve("127.0.0.1", 0)
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces", timeout=5
            ) as r:
                doc = json.load(r)
            assert len(doc["traces"]) == 3
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces?n=1", timeout=5
            ) as r:
                assert len(json.load(r)["traces"]) == 1
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces/chrome", timeout=5
            ) as r:
                chrome = json.load(r)
            assert chrome["displayTimeUnit"] == "ms"
            assert any(e["ph"] == "X" for e in chrome["traceEvents"])
        finally:
            srv.stop()

    def test_metrics_server_without_tracer_has_no_debug_routes(self):
        import urllib.error
        import urllib.request

        from cometbft_tpu.libs.metrics import MetricsServer

        srv = MetricsServer(Registry())
        port = srv.serve("127.0.0.1", 0)
        try:
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/traces", timeout=5
                )
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# satellite regressions: min_batch threading without env mutation


class TestMinBatchThreading:
    def test_resident_routing_honors_spec_floor_without_env(self, monkeypatch):
        """The resident-commit eligibility and the add()/verify()
        verifier resolve the SAME floor from the BackendSpec — no
        re-read of CBFT_TPU_MIN_BATCH with a divergent default."""
        from cometbft_tpu.crypto import batch as cryptobatch

        monkeypatch.delenv("CBFT_TPU_MIN_BATCH", raising=False)
        lo = cryptobatch.BackendSpec(name="tpu", min_batch=5)
        hi = cryptobatch.BackendSpec(name="tpu", min_batch=50)
        assert cryptobatch.resident_commit_eligible(10, lo) is True
        assert cryptobatch.resident_commit_eligible(10, hi) is False
        # the add()/verify() path sees the identical floor
        assert cryptobatch.new_batch_verifier(lo)._min_batch == 5
        assert cryptobatch.new_batch_verifier(hi)._min_batch == 50
        # env still wins for operator A/B overrides, on BOTH paths
        monkeypatch.setenv("CBFT_TPU_MIN_BATCH", "7")
        assert cryptobatch.resident_commit_eligible(10, hi) is True
        assert cryptobatch.new_batch_verifier(hi)._min_batch == 7

    def test_node_does_not_mutate_min_batch_env(self, monkeypatch):
        """Two in-process nodes with different [crypto] min_batch must
        not share the first node's floor through os.environ."""
        import tempfile

        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.cmd.commands import main as cli_main
        from cometbft_tpu.node import default_new_node

        monkeypatch.delenv("CBFT_TPU_MIN_BATCH", raising=False)
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "env-iso"])
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.crypto.min_batch = 77
            node = default_new_node(cfg)
            try:
                assert "CBFT_TPU_MIN_BATCH" not in os.environ
                assert node.crypto_spec.min_batch == 77
                assert node.verify_scheduler.spec.min_batch == 77
                assert node.verify_supervisor.spec.min_batch == 77
            finally:
                for db in node._dbs:
                    db.close()


# ---------------------------------------------------------------------------
# node wiring


class TestNodeWiring:
    def test_node_builds_tracer_from_config(self, monkeypatch):
        import tempfile

        from cometbft_tpu.cmd.commands import _load_config
        from cometbft_tpu.cmd.commands import main as cli_main
        from cometbft_tpu.node import default_new_node

        monkeypatch.delenv("CBFT_TRACE_SAMPLE", raising=False)
        monkeypatch.delenv("CBFT_TRACE_BUFFER", raising=False)
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "trace-node"])
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.instrumentation.trace_sample = 0.5
            cfg.instrumentation.trace_buffer = 17
            node = default_new_node(cfg)
            try:
                assert node.tracer.sample == 0.5
                assert node.tracer.buffer_size == 17
                assert node.tracer._dump_dir == os.path.join(d, "data")
                # the scheduler and supervisor share the node's tracer
                assert node.verify_scheduler._tracer is node.tracer
                assert node.verify_supervisor._tracer is node.tracer
            finally:
                for db in node._dbs:
                    db.close()
