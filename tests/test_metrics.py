"""Metrics: instrument semantics, Prometheus text exposition, engine metric
sets, and a live node serving /metrics.

Model: reference consensus/metrics.go + node/node.go:1221
startPrometheusServer (scrape endpoint contract).

Also under test here: a strict v0.0.4 exposition conformance pass (the
contract a real Prometheus scraper holds us to — label escaping, bucket
monotonicity, +Inf == _count, no duplicate TYPE lines) and a
concurrency hammer racing with_labels() child creation against
expose().
"""

import threading
import urllib.request

import pytest

from cometbft_tpu.consensus.metrics import Metrics as ConsMetrics
from cometbft_tpu.libs.metrics import (
    MICRO_BUCKETS,
    MetricsServer,
    Registry,
)
from cometbft_tpu.mempool.metrics import Metrics as MemMetrics
from cometbft_tpu.p2p.metrics import Metrics as P2PMetrics
from cometbft_tpu.state.metrics import Metrics as SMMetrics


class TestInstruments:
    def test_counter(self):
        r = Registry("t")
        c = r.counter("sub", "hits", "Hits.")
        c.add()
        c.add(2)
        assert c.value() == 3
        with pytest.raises(ValueError):
            c.add(-1)

    def test_gauge(self):
        r = Registry("t")
        g = r.gauge("sub", "height")
        g.set(5)
        g.add(-2)
        assert g.value() == 3

    def test_histogram_buckets(self):
        r = Registry("t")
        h = r.histogram("sub", "lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = r.expose()
        assert 't_sub_lat_bucket{le="0.1"} 1' in text
        assert 't_sub_lat_bucket{le="1"} 2' in text
        assert 't_sub_lat_bucket{le="+Inf"} 3' in text
        assert "t_sub_lat_count 3" in text

    def test_labels_make_child_series(self):
        r = Registry("t")
        c = r.counter("p2p", "bytes")
        c.with_labels(peer="a").add(10)
        c.with_labels(peer="b").add(20)
        c.with_labels(peer="a").add(1)  # same child
        text = r.expose()
        assert 't_p2p_bytes{peer="a"} 11' in text
        assert 't_p2p_bytes{peer="b"} 20' in text

    def test_untouched_metrics_are_hidden(self):
        r = Registry("t")
        r.gauge("sub", "never_set")
        assert "never_set" not in r.expose()

    def test_reregistration_returns_same_instrument(self):
        r = Registry("t")
        a = r.gauge("s", "x")
        b = r.gauge("s", "x")
        assert a is b
        with pytest.raises(ValueError):
            r.counter("s", "x")

    def test_help_and_type_lines(self):
        r = Registry("cometbft")
        g = r.gauge("consensus", "height", "Height of the chain.")
        g.set(7)
        text = r.expose()
        assert "# HELP cometbft_consensus_height Height of the chain." in text
        assert "# TYPE cometbft_consensus_height gauge" in text
        assert "cometbft_consensus_height 7" in text


class TestEngineMetricSets:
    def test_all_sets_build_against_one_registry(self):
        r = Registry("cometbft")
        cons = ConsMetrics(r)
        P2PMetrics(r)
        MemMetrics(r)
        SMMetrics(r)
        cons.height.set(12)
        cons.mark_step("propose")
        text = r.expose()
        assert "cometbft_consensus_height 12" in text
        assert 'step="propose"' in text

    def test_nop_metrics_never_fail(self):
        m = ConsMetrics.nop()
        m.height.set(1)
        m.block_interval_seconds.observe(0.5)
        m.mark_step("prevote")


class TestBucketOverrides:
    def test_micro_buckets_are_sorted_and_sub_ms(self):
        assert list(MICRO_BUCKETS) == sorted(MICRO_BUCKETS)
        assert MICRO_BUCKETS[0] < 1e-5  # µs resolution at the bottom
        assert MICRO_BUCKETS[-1] >= 1.0  # still reaches the watchdog tail

    def test_same_buckets_reregistration_is_idempotent(self):
        r = Registry("t")
        a = r.histogram("sub", "lat", buckets=MICRO_BUCKETS)
        b = r.histogram("sub", "lat", buckets=MICRO_BUCKETS)
        assert a is b

    def test_bucket_mismatch_raises(self):
        r = Registry("t")
        r.histogram("sub", "lat", buckets=MICRO_BUCKETS)
        with pytest.raises(ValueError, match="different buckets"):
            r.histogram("sub", "lat", buckets=(0.1, 1.0))

    def test_children_inherit_parent_buckets(self):
        r = Registry("t")
        h = r.histogram("sub", "lat", buckets=(0.25, 2.0))
        h.with_labels(subsystem="x").observe(1.0)
        text = r.expose()
        assert 't_sub_lat_bucket{le="0.25",subsystem="x"} 0' in text
        assert 't_sub_lat_bucket{le="2",subsystem="x"} 1' in text


def _parse_exposition(text):
    """Strict Prometheus v0.0.4 text parser: returns (types, samples)
    where samples is a list of (name, labels_dict, value). Raises
    AssertionError on any malformed line, duplicate TYPE, or a sample
    appearing before its family's TYPE line."""
    types = {}
    samples = []
    if not text:  # nothing touched yet — an empty exposition is legal
        return types, samples
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if not line:  # blank separator lines are legal v0.0.4
            continue
        assert line == line.strip(), f"stray whitespace: {line!r}"
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name = rest.split(" ", 1)[0]
            assert name, f"HELP without a name: {line!r}"
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ")
            assert len(parts) == 2, f"malformed TYPE: {line!r}"
            name, kind = parts
            assert kind in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ), f"unknown kind: {line!r}"
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        assert not line.startswith("#"), f"unknown comment: {line!r}"
        # sample: name[{labels}] value
        i = 0
        while i < len(line) and (line[i].isalnum() or line[i] in "_:"):
            i += 1
        name = line[:i]
        assert name and not name[0].isdigit(), f"bad name: {line!r}"
        labels = {}
        if i < len(line) and line[i] == "{":
            i += 1
            while line[i] != "}":
                j = i
                while line[j] not in "=":
                    j += 1
                lname = line[i:j]
                assert line[j + 1] == '"', f"unquoted label: {line!r}"
                j += 2
                val = []
                while line[j] != '"':
                    if line[j] == "\\":
                        nxt = line[j + 1]
                        assert nxt in ('"', "\\", "n"), (
                            f"bad escape \\{nxt}: {line!r}"
                        )
                        val.append("\n" if nxt == "n" else nxt)
                        j += 2
                    else:
                        val.append(line[j])
                        j += 1
                assert lname not in labels, f"duplicate label: {line!r}"
                labels[lname] = "".join(val)
                i = j + 1
                if line[i] == ",":
                    i += 1
            i += 1
        assert line[i] == " ", f"missing value separator: {line!r}"
        raw = line[i + 1:]
        value = float("inf") if raw == "+Inf" else float(raw)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types:
                base = name[:-len(suffix)]
        assert base in types, f"sample before TYPE: {line!r}"
        if types[base] == "histogram":
            assert base != name or False, (
                f"bare sample for histogram family: {line!r}"
            )
        key = (name, tuple(sorted(labels.items())))
        assert key not in [
            (n, tuple(sorted(l.items()))) for n, l, _ in samples
        ], f"duplicate series: {line!r}"
        samples.append((name, labels, value))
    return types, samples


class TestExpositionConformance:
    def _verify_registry(self):
        """A registry shaped like the node's verify path exports."""
        r = Registry("cometbft")
        g = r.gauge("verify_slo", "p99_ms", "Rolling p99.")
        g.set(12.5)
        c = r.counter(
            "verify_telemetry", "red_requests", "Requests by subsystem."
        )
        c.with_labels(subsystem="consensus").add(3)
        c.with_labels(subsystem="blocksync").add(1)
        h = r.histogram(
            "verify_telemetry", "red_latency_seconds",
            "Per-request latency.", buckets=MICRO_BUCKETS,
        )
        hs = h.with_labels(subsystem="consensus")
        for v in (0.00002, 0.0004, 0.009, 4.0):
            hs.observe(v)
        return r

    def test_strict_parse(self):
        types, samples = _parse_exposition(self._verify_registry().expose())
        assert types["cometbft_verify_slo_p99_ms"] == "gauge"
        assert types["cometbft_verify_telemetry_red_requests"] == "counter"
        assert (
            types["cometbft_verify_telemetry_red_latency_seconds"]
            == "histogram"
        )
        by_sub = {
            l["subsystem"]: v for n, l, v in samples
            if n == "cometbft_verify_telemetry_red_requests"
        }
        assert by_sub == {"consensus": 3.0, "blocksync": 1.0}

    def test_bucket_monotonicity_and_inf_equals_count(self):
        _, samples = _parse_exposition(self._verify_registry().expose())
        fam = "cometbft_verify_telemetry_red_latency_seconds"
        buckets = [
            (float(l["le"]) if l["le"] != "+Inf" else float("inf"), v)
            for n, l, v in samples if n == fam + "_bucket"
        ]
        assert len(buckets) == len(MICRO_BUCKETS) + 1
        assert buckets == sorted(buckets, key=lambda b: b[0])
        counts = [v for _, v in buckets]
        assert counts == sorted(counts), "bucket counts must be cumulative"
        count = next(v for n, l, v in samples if n == fam + "_count")
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == count
        total = next(v for n, l, v in samples if n == fam + "_sum")
        assert total == pytest.approx(0.00002 + 0.0004 + 0.009 + 4.0)

    def test_label_value_escaping_roundtrip(self):
        r = Registry("t")
        nasty = 'quote:" back:\\ newline:\nend'
        r.counter("sub", "evil", "h").with_labels(device=nasty).add()
        types, samples = _parse_exposition(r.expose())
        assert samples == [("t_sub_evil", {"device": nasty}, 1.0)]

    def test_help_escaping(self):
        r = Registry("t")
        r.gauge("sub", "g", "line one\nline \\ two").set(1)
        text = r.expose()
        assert "# HELP t_sub_g line one\\nline \\\\ two" in text
        _parse_exposition(text)  # still one physical line per entry

    def test_no_duplicate_type_lines_across_families(self):
        text = self._verify_registry().expose()
        type_lines = [
            l for l in text.splitlines() if l.startswith("# TYPE")
        ]
        assert len(type_lines) == len(set(type_lines))

    def test_verify_memory_family_conformance(self):
        """The memory plane's verify_memory_* families, driven by a
        real model-only MemoryPlane (poll + guard shrink + model
        update), must survive the strict v0.0.4 parse with the device
        label intact."""
        from cometbft_tpu.crypto.tpu import memory as memlib
        from cometbft_tpu.crypto.tpu import topology as topolib

        r = Registry("cometbft")
        plane = memlib.MemoryPlane(
            metrics=memlib.Metrics(r), stats=False, poll_ms=0,
            model_limit_bytes=1 << 20,  # tiny: forces a guard shrink
        )
        handle = topolib.default_topology().device(0)
        handle.reset_chunk_shrink()
        try:
            plane.poll(force=True)
            plane.refresh_guard(handle, 8192, 64)
            plane.observe_footprint("ed25519", 1024, 1024 * 5000)
            types, samples = _parse_exposition(r.expose())
            for gauge in (
                "bytes_in_use", "bytes_peak", "bytes_limit",
                "headroom_bytes", "guard_cap",
            ):
                assert types[f"cometbft_verify_memory_{gauge}"] == "gauge"
            for counter in ("guard_shrinks", "polls", "model_updates"):
                assert (
                    types[f"cometbft_verify_memory_{counter}"] == "counter"
                )
            shrink_series = [
                (l, v) for n, l, v in samples
                if n == "cometbft_verify_memory_guard_shrinks"
            ]
            assert any(
                "device" in l and v > 0 for l, v in shrink_series
            ), "guard shrink must surface as a device-labeled series"
        finally:
            handle.reset_chunk_shrink()

    def test_verify_wire_family_conformance(self):
        """The wire ledger's verify_wire_* families, driven by a real
        WireLedger (chunk + dispatch + demux notes), must survive the
        strict v0.0.4 parse with every phase and route label intact."""
        from cometbft_tpu.crypto import wire as wirelib

        r = Registry("cometbft")
        ledger = wirelib.WireLedger(metrics=wirelib.Metrics(r), window=8)
        ledger.note_chunk(
            "single", "dev0", 256, 200, 1024,
            pack_s=1e-4, h2d_s=2e-3, compute_s=5e-4, d2h_s=1e-4,
            hidden_s=1e-3,
        )
        ledger.note_dispatch(
            "single", "dev0", 200, wall_s=3e-3,
            pack_s=1e-4, h2d_s=2e-3, compute_s=5e-4, d2h_s=1e-4,
            hidden_s=1e-3, wire_bytes=1024, chunks=1,
        )
        ledger.note_demux("cpu", 200, 5e-5)
        types, samples = _parse_exposition(r.expose())
        assert types["cometbft_verify_wire_phase_seconds"] == "histogram"
        for counter in ("chunks", "dispatches", "bytes", "lanes"):
            assert types[f"cometbft_verify_wire_{counter}"] == "counter"
        for gauge in ("overlap_ratio", "effective_mbps", "coverage"):
            assert types[f"cometbft_verify_wire_{gauge}"] == "gauge"
        phase_counts = {
            (l.get("phase"), l.get("route")): v
            for n, l, v in samples
            if n == "cometbft_verify_wire_phase_seconds_count"
        }
        for phase in ("pack", "h2d", "compute", "d2h"):
            assert phase_counts.get((phase, "single")) == 1.0, (
                f"phase {phase} must surface as a labeled series"
            )
        assert phase_counts.get(("demux", "cpu")) == 1.0
        assert ("cometbft_verify_wire_bytes", {"device": "dev0"},
                1024.0) in samples
        assert ("cometbft_verify_wire_overlap_ratio",
                {"route": "single"}, 0.5) in samples


    def test_verify_route_family_conformance(self):
        """The decision ledger's verify_route_* families, driven by a
        real DecisionLedger (undiverted + diverted decisions, a forced
        watchdog trip), must survive the strict v0.0.4 parse with the
        route and cause labels intact."""
        from cometbft_tpu.crypto import decisions as declib

        r = Registry("cometbft")
        led = declib.DecisionLedger(
            window=declib.MIN_TRIP_OBS,
            ring_interval_s=0.0,
            metrics=declib.Metrics(r),
        )
        for _ in range(declib.MIN_TRIP_OBS + declib.MIN_SELF_OBS):
            dec = led.open(n=16, reason="size")
            dec.taken = "cpu"
            led.finish(dec, 0.002)
        fb = led.open(n=16, reason="size")
        fb.taken = "sharded"
        led.note_event(fb, "sharded_fallback", final="single")
        led.finish(fb, 0.010)
        dec = led.open(n=16, reason="size")  # stale wall: trips mape
        dec.taken = "cpu"
        led.finish(dec, 0.200)
        types, samples = _parse_exposition(r.expose())
        for counter in ("decisions", "fallbacks", "anomaly_trips"):
            assert types[f"cometbft_verify_route_{counter}"] == "counter"
        for gauge in ("mape", "regret_ms", "anomaly"):
            assert types[f"cometbft_verify_route_{gauge}"] == "gauge"
        assert (
            types["cometbft_verify_route_error_seconds"] == "histogram"
        )
        by_route = {
            l.get("route"): v for n, l, v in samples
            if n == "cometbft_verify_route_decisions"
        }
        assert by_route.get("cpu", 0) >= declib.MIN_TRIP_OBS
        assert by_route.get("sharded") == 1.0
        assert ("cometbft_verify_route_fallbacks", {"route": "sharded"},
                1.0) in samples
        assert ("cometbft_verify_route_anomaly_trips", {"cause": "mape"},
                1.0) in samples
        assert ("cometbft_verify_route_anomaly", {}, 1.0) in samples


class TestReadmeDocDrift:
    def test_every_verify_family_documented_in_readme(self):
        """Doc-drift guard (PR 15 satellite): every verify_* metric
        family the crypto planes can export must appear by name in
        README.md — a new instrument without its reference-table row
        fails tier-1."""
        import os

        from cometbft_tpu.crypto import decisions as declib
        from cometbft_tpu.crypto import qos as qoslib
        from cometbft_tpu.crypto import scheduler as schedlib
        from cometbft_tpu.crypto import service as servicelib
        from cometbft_tpu.crypto import supervisor as suplib
        from cometbft_tpu.crypto import telemetry as tellib
        from cometbft_tpu.crypto import wire as wirelib
        from cometbft_tpu.crypto.tpu import aot as aotlib
        from cometbft_tpu.crypto.tpu import memory as memlib

        r = Registry("cometbft")
        declib.Metrics(r)
        qoslib.QoSMetrics(r)
        schedlib.Metrics(r)
        servicelib.ServiceMetrics(r)
        suplib.Metrics(r)
        tellib.Metrics(r)
        wirelib.Metrics(r)
        aotlib.Metrics(r)
        memlib.Metrics(r)
        families = sorted(
            name[len("cometbft_"):]
            for name in r._instruments
            if name.startswith("cometbft_verify_")
        )
        assert families, "no verify_* families registered?"
        readme = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "README.md",
        )
        with open(readme, "r", encoding="utf-8") as f:
            doc = f.read()

        def documented(fam: str) -> bool:
            # reference-table rows carry the full family name; the
            # Observability bullets document `verify_<sub>_*` with the
            # member names backticked — honor both idioms
            if fam in doc:
                return True
            parts = fam.split("_")
            for cut in range(2, len(parts)):
                prefix = "_".join(parts[:cut])
                suffix = "_".join(parts[cut:])
                if f"`{prefix}_*`" in doc and f"`{suffix}`" in doc:
                    return True
            return False

        missing = [fam for fam in families if not documented(fam)]
        assert not missing, (
            "verify_* metric families exported but not documented in "
            f"README.md: {missing}"
        )


class TestConcurrencyHammer:
    def test_with_labels_races_expose(self):
        """Satellite contract: scrapes concurrent with hot-path child
        creation never tear — every expose() parses strictly, and the
        final totals equal exactly what the writers wrote."""
        r = Registry("cometbft")
        c = r.counter("verify_telemetry", "red_requests", "Req.")
        h = r.histogram(
            "verify_telemetry", "red_latency_seconds", "Lat.",
            buckets=MICRO_BUCKETS,
        )
        n_writers, per_writer = 8, 300
        stop = threading.Event()
        errors = []

        def writer(wid):
            try:
                for i in range(per_writer):
                    sub = f"sub{(wid + i) % 5}"
                    c.with_labels(subsystem=sub).add()
                    h.with_labels(subsystem=sub).observe(0.0001 * (i % 7))
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        def scraper():
            try:
                while not stop.is_set():
                    _parse_exposition(r.expose())
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)

        scrapers = [threading.Thread(target=scraper) for _ in range(2)]
        writers = [
            threading.Thread(target=writer, args=(w,))
            for w in range(n_writers)
        ]
        for t in scrapers + writers:
            t.start()
        for t in writers:
            t.join()
        stop.set()
        for t in scrapers:
            t.join()
        assert not errors, errors[:3]
        _, samples = _parse_exposition(r.expose())
        req = {
            l["subsystem"]: v for n, l, v in samples
            if n == "cometbft_verify_telemetry_red_requests"
        }
        assert sum(req.values()) == n_writers * per_writer
        assert set(req) == {f"sub{i}" for i in range(5)}
        fam = "cometbft_verify_telemetry_red_latency_seconds_count"
        obs = sum(v for n, _, v in samples if n == fam)
        assert obs == n_writers * per_writer


class TestMetricsServer:
    def test_serves_text_format(self):
        r = Registry("cometbft")
        r.gauge("consensus", "height").set(42)
        srv = MetricsServer(r)
        port = srv.serve("127.0.0.1", 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "cometbft_consensus_height 42" in body
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/other", timeout=5
                )
        finally:
            srv.stop()
