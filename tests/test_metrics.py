"""Metrics: instrument semantics, Prometheus text exposition, engine metric
sets, and a live node serving /metrics.

Model: reference consensus/metrics.go + node/node.go:1221
startPrometheusServer (scrape endpoint contract).
"""

import urllib.request

import pytest

from cometbft_tpu.consensus.metrics import Metrics as ConsMetrics
from cometbft_tpu.libs.metrics import (
    MetricsServer,
    Registry,
)
from cometbft_tpu.mempool.metrics import Metrics as MemMetrics
from cometbft_tpu.p2p.metrics import Metrics as P2PMetrics
from cometbft_tpu.state.metrics import Metrics as SMMetrics


class TestInstruments:
    def test_counter(self):
        r = Registry("t")
        c = r.counter("sub", "hits", "Hits.")
        c.add()
        c.add(2)
        assert c.value() == 3
        with pytest.raises(ValueError):
            c.add(-1)

    def test_gauge(self):
        r = Registry("t")
        g = r.gauge("sub", "height")
        g.set(5)
        g.add(-2)
        assert g.value() == 3

    def test_histogram_buckets(self):
        r = Registry("t")
        h = r.histogram("sub", "lat", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        text = r.expose()
        assert 't_sub_lat_bucket{le="0.1"} 1' in text
        assert 't_sub_lat_bucket{le="1"} 2' in text
        assert 't_sub_lat_bucket{le="+Inf"} 3' in text
        assert "t_sub_lat_count 3" in text

    def test_labels_make_child_series(self):
        r = Registry("t")
        c = r.counter("p2p", "bytes")
        c.with_labels(peer="a").add(10)
        c.with_labels(peer="b").add(20)
        c.with_labels(peer="a").add(1)  # same child
        text = r.expose()
        assert 't_p2p_bytes{peer="a"} 11' in text
        assert 't_p2p_bytes{peer="b"} 20' in text

    def test_untouched_metrics_are_hidden(self):
        r = Registry("t")
        r.gauge("sub", "never_set")
        assert "never_set" not in r.expose()

    def test_reregistration_returns_same_instrument(self):
        r = Registry("t")
        a = r.gauge("s", "x")
        b = r.gauge("s", "x")
        assert a is b
        with pytest.raises(ValueError):
            r.counter("s", "x")

    def test_help_and_type_lines(self):
        r = Registry("cometbft")
        g = r.gauge("consensus", "height", "Height of the chain.")
        g.set(7)
        text = r.expose()
        assert "# HELP cometbft_consensus_height Height of the chain." in text
        assert "# TYPE cometbft_consensus_height gauge" in text
        assert "cometbft_consensus_height 7" in text


class TestEngineMetricSets:
    def test_all_sets_build_against_one_registry(self):
        r = Registry("cometbft")
        cons = ConsMetrics(r)
        P2PMetrics(r)
        MemMetrics(r)
        SMMetrics(r)
        cons.height.set(12)
        cons.mark_step("propose")
        text = r.expose()
        assert "cometbft_consensus_height 12" in text
        assert 'step="propose"' in text

    def test_nop_metrics_never_fail(self):
        m = ConsMetrics.nop()
        m.height.set(1)
        m.block_interval_seconds.observe(0.5)
        m.mark_step("prevote")


class TestMetricsServer:
    def test_serves_text_format(self):
        r = Registry("cometbft")
        r.gauge("consensus", "height").set(42)
        srv = MetricsServer(r)
        port = srv.serve("127.0.0.1", 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ).read().decode()
            assert "cometbft_consensus_height 42" in body
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/other", timeout=5
                )
        finally:
            srv.stop()
