"""Live priced router (ISSUE 16) — the argmin must agree with the
threshold ladder when warm, fall back to it when cold, roll back to it
when the decision plane's watchdog says the cost model is lying, and
pick the indexed steady-state wire exactly when the flush's keys are
resident. CBFT_MESH_ROUTE pins beat every router; a malformed pin is
parsed once, warned once, and then ignored.

Runs on the virtual CPU mesh (conftest.py); the indexed tests pin
n_devices to 1 the same way tests/test_keystore.py does.
"""

import hashlib
import json
from types import SimpleNamespace

import pytest

from cometbft_tpu.crypto import decisions as declib
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import wire as wirelib
from cometbft_tpu.crypto.batch import BackendSpec
from cometbft_tpu.crypto.scheduler import (
    ROUTER_REARM_CLEAN,
    VerifyScheduler,
    router_default,
)
from cometbft_tpu.crypto.tpu import ed25519_batch as eb
from cometbft_tpu.crypto.tpu import keystore, mesh, topology
from tools import route_audit


# Per-route seed menus: the third prediction rung, so the argmin is
# fully priced without walking a single route first.
def _seed(menu):
    return lambda route, bucket: menu.get(route)


# single cheapest everywhere — priced and threshold must then agree on
# every unsupervised flush size
_SINGLE_CHEAP = {"cpu": 50.0, "single": 1.0, "sharded": 40.0}


class _Log:
    def __init__(self):
        self.errors = []
        self.infos = []

    def error(self, msg, **kw):
        self.errors.append((msg, kw))

    def info(self, msg, **kw):
        self.infos.append((msg, kw))

    def debug(self, msg, **kw):
        pass

    def warning(self, msg, **kw):
        pass


def _sched(router="priced", supervisor=None, logger=None, spec="faux"):
    return VerifyScheduler(
        spec=BackendSpec(spec), router=router, supervisor=supervisor,
        logger=logger,
    )


@pytest.fixture
def ledger():
    """A seeded decision ledger installed as the process default (the
    priced router reads declib.default_ledger()), restored after."""
    led = declib.DecisionLedger(
        window=8, ring_interval_s=1e9, seed=_seed(_SINGLE_CHEAP)
    )
    prev = declib.set_default_ledger(led)
    yield led
    declib.set_default_ledger(prev)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("CBFT_ROUTER", raising=False)
    monkeypatch.delenv("CBFT_MESH_ROUTE", raising=False)


def _routed(sched, led, n, items=()):
    """One routing decision exactly as _verify would make it: open a
    priced record with the scheduler's own feasibility, park it as the
    flush thread's current decision, route."""
    items = list(items)
    feas = sched._decision_feasible(items, sched._decision_breakers())
    dec = led.open(n, "test", feasible=feas)
    with declib.use(dec):
        return sched._route(n, items)


class TestRouterKnob:
    def test_default_is_priced(self):
        assert router_default() == "priced"
        assert router_default(None) == "priced"

    def test_config_value_respected(self):
        assert router_default("threshold") == "threshold"
        assert router_default("priced") == "priced"

    def test_env_beats_config(self, monkeypatch):
        monkeypatch.setenv("CBFT_ROUTER", "threshold")
        assert router_default("priced") == "threshold"
        monkeypatch.setenv("CBFT_ROUTER", "priced")
        assert router_default("threshold") == "priced"

    def test_unrecognized_degrades_to_threshold(self, monkeypatch):
        assert router_default("bogus") == "threshold"
        monkeypatch.setenv("CBFT_ROUTER", "learned")
        assert router_default("priced") == "threshold"

    def test_config_validates_router(self):
        from cometbft_tpu.config import Config

        cfg = Config()
        cfg.crypto.router = "bogus"
        with pytest.raises(ValueError, match="crypto.router"):
            cfg.validate_basic()


class TestMeshRoutePin:
    def test_malformed_pin_warns_once_and_sizes(self, monkeypatch):
        log = _Log()
        sched = _sched(logger=log)
        monkeypatch.setenv("CBFT_MESH_ROUTE", "shardedd")
        for _ in range(5):
            assert sched._pin_route() is None
        assert len(log.errors) == 1, "parse-once cache must warn once"
        # a DIFFERENT malformed value re-parses (and re-warns) once
        monkeypatch.setenv("CBFT_MESH_ROUTE", "both")
        assert sched._pin_route() is None
        assert sched._pin_route() is None
        assert len(log.errors) == 2

    def test_env_flip_takes_effect_next_flush(self, monkeypatch):
        sched = _sched(logger=_Log())
        monkeypatch.setenv("CBFT_MESH_ROUTE", "single")
        assert sched._pin_route() == "single"
        monkeypatch.setenv("CBFT_MESH_ROUTE", "sharded")
        assert sched._pin_route() == "sharded"
        monkeypatch.delenv("CBFT_MESH_ROUTE")
        assert sched._pin_route() is None

    def test_valid_pin_beats_priced_argmin(self, ledger, monkeypatch):
        """Regression for the pin/argmin precedence: the cost model says
        cpu is free, but the operator pinned single — the pin wins and
        the record is tagged "pinned", not "priced"."""
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9,
            seed=_seed({"cpu": 0.01, "single": 50.0, "sharded": 50.0}),
        )
        prev = declib.set_default_ledger(led)
        try:
            sched = _sched(logger=_Log())
            # without the pin the argmin takes the free cpu rung
            assert _routed(sched, led, 64) == ("cpu", None, "priced")
            monkeypatch.setenv("CBFT_MESH_ROUTE", "single")
            assert _routed(sched, led, 64) == ("single", "single", "pinned")
        finally:
            declib.set_default_ledger(prev)

    def test_malformed_pin_leaves_priced_router_live(
        self, ledger, monkeypatch
    ):
        monkeypatch.setenv("CBFT_MESH_ROUTE", "not-a-route")
        sched = _sched(logger=_Log())
        assert _routed(sched, ledger, 64) == ("single", None, "priced")


class TestFeasibilityAndRegret:
    def test_infeasible_candidate_cannot_inflate_regret(self):
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9,
            seed=_seed({"cpu": 5.0, "single": 10.0, "sharded": 1.0}),
        )
        feas = {
            "cpu": True, "single": True, "sharded": False,
            "indexed": False, "device_hash": False,
        }
        dec = led.open(8, "test", feasible=feas)
        dec.taken = "single"
        led.finish(dec, 0.010)
        # regret vs the cheapest FEASIBLE candidate (cpu @ 5), not the
        # infeasible sharded rung @ 1
        assert dec.regret_ms == pytest.approx(5.0)
        rec = led.snapshot()["recent"][-1]
        assert rec["feasible"] == feas
        assert rec["regret_ms"] == pytest.approx(5.0)

    def test_legacy_records_count_every_priced_candidate(self):
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9,
            seed=_seed({"cpu": 5.0, "single": 10.0, "sharded": 1.0}),
        )
        dec = led.open(8, "test")  # feasible=None: pre-router shape
        dec.taken = "single"
        led.finish(dec, 0.010)
        assert dec.regret_ms == pytest.approx(9.0)

    def test_broken_breakers_leave_only_cpu(self, ledger):
        sup = SimpleNamespace(topology=None)
        sched = _sched(supervisor=sup, logger=_Log())
        feas = sched._decision_feasible(
            [], {"dev0": "broken", "dev1": "broken"}
        )
        assert feas == {
            "cpu": True, "single": False, "sharded": False,
            "indexed": False, "device_hash": False,
        }
        dec = ledger.open(64, "test", feasible=feas)
        with declib.use(dec):
            label, route, tag = sched._route(64, [])
        assert (label, route, tag) == ("cpu", None, "priced")

    def test_cpu_spec_is_cpu_only(self):
        sched = _sched(spec="cpu", logger=_Log())
        feas = sched._decision_feasible([], None)
        assert feas["cpu"] and not feas["single"]
        assert sched._route(4096, []) == ("cpu", None, "threshold")


class TestRouterEquivalenceAndFallback:
    def test_priced_matches_threshold_when_warm(self, ledger):
        """Warm model, single cheapest: the argmin and the threshold
        ladder must take the SAME route at every flush size (the router
        swap is a perf change, not a behavior change)."""
        priced = _sched(router="priced", logger=_Log())
        thresh = _sched(router="threshold", logger=_Log())
        for n in (1, 4, 16, 64, 256, 1024, 4096):
            lp, rp, tp = _routed(priced, ledger, n)
            lt, rt, tt = _routed(thresh, ledger, n)
            assert (lp, rp) == (lt, rt), f"diverged at n={n}"
            assert tp == "priced" and tt == "threshold"

    def test_cold_model_falls_back_to_threshold(self):
        led = declib.DecisionLedger(window=8, ring_interval_s=1e9)
        prev = declib.set_default_ledger(led)
        try:
            sched = _sched(logger=_Log())
            # no seed, no observations: every candidate unpriced
            assert _routed(sched, led, 64) == ("single", None, "threshold")
        finally:
            declib.set_default_ledger(prev)

    def test_partially_priced_menu_stays_on_thresholds(self):
        # one feasible primary still unpriced -> an argmin over the
        # partial menu would dodge the unpriced route; stay threshold
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9,
            seed=_seed({"cpu": 1.0}),
        )
        prev = declib.set_default_ledger(led)
        try:
            sched = _sched(logger=_Log())
            assert _routed(sched, led, 64) == ("single", None, "threshold")
        finally:
            declib.set_default_ledger(prev)

    def test_no_ledger_means_threshold(self):
        prev = declib.set_default_ledger(None)
        try:
            sched = _sched(logger=_Log())
            assert sched._route(64, []) == ("single", None, "threshold")
        finally:
            declib.set_default_ledger(prev)


class _GuardStub:
    """Duck-typed decision ledger for the rollback guard: just the
    watchdog/windowed surface, directly scriptable."""

    def __init__(self):
        self.tripped = None
        self.trips = 0
        self.rate = 0.0
        self.obs = 64
        self.regret_trip = declib.REGRET_TRIP

    def watchdog_state(self):
        return {"tripped": self.tripped, "trips": self.trips}

    def windowed(self):
        return {
            "mape": None, "regret_ms": 0.0,
            "regret_rate": self.rate, "observations": self.obs,
        }


class TestRollbackGuard:
    def test_trip_rolls_back_then_clean_windows_readmit(self):
        log = _Log()
        sched = _sched(logger=log)
        g = _GuardStub()
        assert sched._router_guard(g) is True

        g.tripped = "mape"
        g.trips = 1
        assert sched._router_guard(g) is False
        router = sched.queue_snapshot()["router"]
        assert router["rolled_back"] is True
        assert router["rollbacks"] == 1
        assert router["rollback_cause"] == "mape"
        assert router["live"] == "rolled-back"

        # still tripped: stays rolled back, no double-count
        assert sched._router_guard(g) is False
        assert sched.queue_snapshot()["router"]["rollbacks"] == 1

        # watchdog re-arms: re-admission needs REARM_CLEAN clean checks
        g.tripped = None
        for i in range(ROUTER_REARM_CLEAN - 1):
            assert sched._router_guard(g) is False, f"check {i}"
        assert sched._router_guard(g) is True
        router = sched.queue_snapshot()["router"]
        assert router["rolled_back"] is False
        assert router["readmits"] == 1
        assert router["rollback_cause"] is None

    def test_regret_rate_rolls_back_and_dirty_checks_reset(self):
        sched = _sched(logger=_Log())
        g = _GuardStub()
        g.rate = g.regret_trip * 2
        assert sched._router_guard(g) is False
        assert (
            sched.queue_snapshot()["router"]["rollback_cause"] == "regret"
        )
        # one clean check, then a dirty one: the streak must reset
        g.rate = 0.0
        assert sched._router_guard(g) is False
        g.rate = g.regret_trip  # above the re-admit bar (trip/2)
        assert sched._router_guard(g) is False
        g.rate = 0.0
        for _ in range(ROUTER_REARM_CLEAN - 1):
            assert sched._router_guard(g) is False
        assert sched._router_guard(g) is True

    def test_low_observation_regret_does_not_roll_back(self):
        sched = _sched(logger=_Log())
        g = _GuardStub()
        g.rate = 1.0
        g.obs = declib.MIN_TRIP_OBS - 1
        assert sched._router_guard(g) is True

    def test_rolled_back_route_is_tagged(self, ledger):
        sched = _sched(logger=_Log())
        ledger._tripped = "mape"  # latch the watchdog directly
        assert _routed(sched, ledger, 64) == ("single", None, "rolled-back")
        snap = sched.queue_snapshot()["router"]
        assert snap["rolled_back"] and snap["rollbacks"] == 1


def _valset(n, tag=b"router"):
    keys = [
        ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)
    ]
    pks = [k.pub_key().bytes() for k in keys]
    vid = hashlib.sha256(b"".join(pks)).digest()
    return keys, pks, vid


def _flush(keys, tag=b"vote"):
    msgs = [tag + b" %d" % i for i in range(len(keys))]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    return msgs, sigs


@pytest.fixture
def store(monkeypatch):
    monkeypatch.setattr(mesh, "n_devices", lambda: 1)
    st = keystore.default_store()
    st.invalidate()
    yield st
    st.invalidate()
    topo = topology.default_topology()
    for i in range(len(topo)):
        topo.set_quarantined(i, False)


def _resident(vid, pks, keys, tag=b"seed"):
    msgs, sigs = _flush(keys, tag)
    assert eb.verify_valset_resident(vid, pks, msgs, sigs) == \
        [True] * len(pks)


_INDEXED_CHEAP = {
    "cpu": 50.0, "single": 5.0, "sharded": 40.0, "indexed": 1.0,
}


class TestIndexedRouting:
    def test_indexed_iff_keys_resident(self, store):
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9, seed=_seed(_INDEXED_CHEAP)
        )
        prev = declib.set_default_ledger(led)
        try:
            keys, pks, vid = _valset(4, b"route-idx")
            _resident(vid, pks, keys)
            msgs, sigs = _flush(keys, b"go")
            # items carry PubKey OBJECTS, exactly as scheduler flushes do
            items = [
                (k.pub_key(), m, s) for k, m, s in zip(keys, msgs, sigs)
            ]
            sup = SimpleNamespace(topology=None)
            sched = _sched(supervisor=sup, logger=_Log())

            feas = sched._decision_feasible(items, None)
            assert feas["indexed"] is True
            assert _routed(sched, led, 4, items) == (
                "indexed", "indexed", "priced"
            )

            # residency lost: indexed infeasible, argmin falls to single
            store.invalidate()
            feas = sched._decision_feasible(items, None)
            assert feas["indexed"] is False
            label, route, tag = _routed(sched, led, 4, items)
            assert (label, tag) == ("single", "priced")
            assert route != "indexed"
        finally:
            declib.set_default_ledger(prev)

    def test_unsupervised_never_routes_indexed(self, store):
        led = declib.DecisionLedger(
            window=8, ring_interval_s=1e9, seed=_seed(_INDEXED_CHEAP)
        )
        prev = declib.set_default_ledger(led)
        try:
            keys, pks, vid = _valset(3, b"route-unsup")
            _resident(vid, pks, keys)
            msgs, sigs = _flush(keys)
            items = [
                (k.pub_key(), m, s) for k, m, s in zip(keys, msgs, sigs)
            ]
            sched = _sched(logger=_Log())  # no supervisor
            assert sched._decision_feasible(items, None)["indexed"] is False
            label, route, tag = _routed(sched, led, 3, items)
            assert label == "single"
        finally:
            declib.set_default_ledger(prev)

    def test_indexed_wire_stays_at_100_bytes_per_lane(self, store):
        n = max(64, eb._MIN_PAD)  # pow2 >= the pad floor: no pad waste
        keys, pks, vid = _valset(n, b"route-bpl")
        _resident(vid, pks, keys)
        msgs, sigs = _flush(keys, b"steady")
        wl = wirelib.WireLedger(window=8)
        prev = wirelib.set_default_ledger(wl)
        try:
            assert keystore.verify_batch_indexed(pks, msgs, sigs) == \
                [True] * n
        finally:
            wirelib.set_default_ledger(prev)
        bpl = wl.bytes_per_lane("indexed")
        assert bpl is not None
        assert bpl <= wirelib.ROUTE_BYTES_PER_LANE["indexed"] + 1e-6

    def test_covers_accepts_pubkey_objects(self, store):
        keys, pks, vid = _valset(3, b"route-cov")
        _resident(vid, pks, keys)
        assert keystore.covers(pks)
        assert keystore.covers([k.pub_key() for k in keys])
        stranger = ed.gen_priv_key_from_secret(b"route-cov-x").pub_key()
        assert not keystore.covers([stranger])


class TestEndToEndFlush:
    def test_flush_records_router_tag_and_reconciles(self):
        led = declib.DecisionLedger(window=8, ring_interval_s=1e9)
        prev = declib.set_default_ledger(led)
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=100)
        sched.start()
        try:
            k = ed.gen_priv_key_from_secret(b"router-e2e")
            msg = b"router end to end"
            ok, mask = sched.submit(
                [(k.pub_key(), msg, k.sign(msg))]
            ).result(timeout=30)
            assert ok and mask == [True]
            rec = led.snapshot()["recent"][-1]
            assert rec["taken"] == "cpu"
            assert rec["router"] == "threshold"
            assert rec["feasible"]["cpu"] is True
            snap = sched.queue_snapshot()
            assert snap["routes"]["cpu"] == 1
            assert snap["router"]["last"] == "threshold"
            assert led.snapshot()["counts"].get("cpu") == 1
        finally:
            sched.stop()
            declib.set_default_ledger(prev)


def _audit_sources(recent, router=None, wd=None):
    decisions = {"recent": recent, "watchdog": wd or {}}
    scheduler = {"router": router or {}}
    return decisions, scheduler


class TestRouteAuditAssertLive:
    def test_clean_argmin_passes(self):
        d, s = _audit_sources([{
            "seq": 1, "router": "priced", "taken": "single",
            "predicted_ms": {"cpu": 2.0, "single": 1.0},
            "feasible": {"cpu": True, "single": True},
        }])
        assert route_audit.assert_live(d, s) == []

    def test_divergence_flagged(self):
        d, s = _audit_sources([{
            "seq": 7, "router": "priced", "taken": "single",
            "predicted_ms": {"cpu": 1.0, "single": 10.0},
            "feasible": {"cpu": True, "single": True},
        }])
        problems = route_audit.assert_live(d, s)
        assert len(problems) == 1 and "argmin" in problems[0]

    def test_tolerance_allows_near_ties(self):
        d, s = _audit_sources([{
            "seq": 2, "router": "priced", "taken": "single",
            "predicted_ms": {"cpu": 1.0, "single": 1.05},
            "feasible": {"cpu": True, "single": True},
        }])
        assert route_audit.assert_live(d, s, tolerance=0.10) == []
        assert route_audit.assert_live(d, s, tolerance=0.01)

    def test_infeasible_taken_flagged(self):
        d, s = _audit_sources([{
            "seq": 3, "router": "priced", "taken": "sharded",
            "predicted_ms": {"single": 1.0, "sharded": 0.5},
            "feasible": {"single": True, "sharded": False},
        }])
        problems = route_audit.assert_live(d, s)
        assert len(problems) == 1 and "infeasible" in problems[0]

    def test_unpriced_taken_flagged(self):
        d, s = _audit_sources([{
            "seq": 4, "router": "priced", "taken": "single",
            "predicted_ms": {"cpu": 1.0, "single": None},
            "feasible": {"cpu": True, "single": True},
        }])
        problems = route_audit.assert_live(d, s)
        assert len(problems) == 1 and "unpriced" in problems[0]

    def test_non_priced_records_are_not_judged(self):
        d, s = _audit_sources([{
            "seq": 5, "router": "threshold", "taken": "single",
            "predicted_ms": {"cpu": 1.0, "single": 10.0},
            "feasible": {"cpu": True, "single": True},
        }])
        assert route_audit.assert_live(d, s) == []

    def test_rollback_without_cause_flagged(self):
        d, s = _audit_sources(
            [], router={"rolled_back": True, "rollback_cause": None}
        )
        problems = route_audit.assert_live(d, s)
        assert len(problems) == 1 and "without" in problems[0]

    def test_rollback_without_trip_flagged(self):
        d, s = _audit_sources(
            [],
            router={"rolled_back": True, "rollback_cause": "mape"},
            wd={"tripped": None, "trips": 0},
        )
        assert len(route_audit.assert_live(d, s)) == 1

    def test_cli_gate_exit_codes(self, tmp_path):
        rec = {
            "seq": 1, "router": "priced", "taken": "single",
            "predicted_ms": {"cpu": 2.0, "single": 1.0},
            "feasible": {"cpu": True, "single": True},
        }
        snap = {
            "slo": {},
            "sources": {
                "decisions": {
                    "counts": {"single": 1}, "windowed": {},
                    "profiles": [], "recent": [rec],
                    "watchdog": {"tripped": None, "trips": 0},
                },
                "scheduler": {
                    "routes": {"single": 1},
                    "router": {
                        "mode": "priced", "live": "priced",
                        "rolled_back": False, "rollbacks": 0,
                        "readmits": 0, "rollback_cause": None,
                    },
                },
            },
        }
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(snap))
        assert route_audit.main([str(path), "--assert-live"]) == 0
        rec["predicted_ms"] = {"cpu": 1.0, "single": 10.0}
        path.write_text(json.dumps(snap))
        assert route_audit.main([str(path), "--assert-live"]) == 2
        # without the flag the divergence is not judged
        assert route_audit.main([str(path)]) == 0

    def test_justified_rollback_passes(self):
        d, s = _audit_sources(
            [],
            router={"rolled_back": True, "rollback_cause": "mape"},
            wd={"tripped": "mape", "trips": 1},
        )
        assert route_audit.assert_live(d, s) == []
        d, s = _audit_sources(
            [],
            router={"rolled_back": True, "rollback_cause": "regret"},
            wd={"tripped": None, "trips": 0},
        )
        assert route_audit.assert_live(d, s) == []
