"""Priority mempool (v1): priority-ordered reap, eviction on full,
rejection when nothing lower-priority can make room.

Model: reference mempool/v1/mempool_test.go.
"""

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.application import BaseApplication
from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.mempool.priority_mempool import PriorityMempool
from cometbft_tpu.proxy import AppConnMempool


class _PriorityApp(BaseApplication):
    """CheckTx reads the priority out of 'prio:<n>:<payload>' txs."""

    def check_tx(self, req):
        try:
            _, n, _ = req.tx.split(b":", 2)
            return abci.ResponseCheckTx(
                code=abci.CODE_TYPE_OK, gas_wanted=1, priority=int(n)
            )
        except ValueError:
            return abci.ResponseCheckTx(code=1, log="bad tx")


def _mk(size=None, max_bytes=None):
    cfg = make_test_config().mempool
    if size is not None:
        cfg.size = size
    if max_bytes is not None:
        cfg.max_txs_bytes = max_bytes
    client = LocalClient(_PriorityApp())
    client.start()
    mp = PriorityMempool(cfg, AppConnMempool(client))
    return mp, client


def _tx(priority, payload="x"):
    return f"prio:{priority}:{payload}".encode()


class TestPriorityMempool:
    def test_reap_orders_by_priority_then_fifo(self):
        mp, client = _mk()
        try:
            for i, prio in enumerate((5, 20, 1, 20, 10)):
                mp.check_tx(_tx(prio, f"p{i}"))
            mp.flush_app_conn()
            reaped = mp.reap_max_bytes_max_gas(-1, -1)
            prios = [int(t.split(b":")[1]) for t in reaped]
            assert prios == [20, 20, 10, 5, 1]
            # equal priorities keep insertion order
            assert reaped[0].endswith(b"p1") and reaped[1].endswith(b"p3")
            # gossip order (clist) stays FIFO for the v0 reactor
            gossip = [e.value.tx for e in mp._txs]
            assert [int(t.split(b":")[1]) for t in gossip] == [5, 20, 1, 20, 10]
        finally:
            client.stop()

    def test_byte_budget_breaks_at_first_misfit(self):
        """Reference v1 ReapMaxBytesMaxGas (and this repo's v0 reap) stop
        at the first tx that does not fit — a smaller lower-priority tx is
        NOT pulled forward past it."""
        mp, client = _mk()
        try:
            mp.check_tx(_tx(9, "A" * 200))  # big, high priority
            mp.check_tx(_tx(5, "b"))  # small, low priority
            mp.flush_app_conn()
            assert mp.reap_max_bytes_max_gas(40, -1) == []
            # with room for the big one, both fit (proto-framed sizes)
            reaped = mp.reap_max_bytes_max_gas(4096, -1)
            assert len(reaped) == 2 and reaped[0].endswith(b"A")
        finally:
            client.stop()

    def test_eviction_of_lower_priority_when_full(self):
        mp, client = _mk(size=3)
        try:
            for prio in (1, 2, 3):
                mp.check_tx(_tx(prio))
            mp.flush_app_conn()
            assert mp.size() == 3
            mp.check_tx(_tx(50, "vip"))
            mp.flush_app_conn()
            assert mp.size() == 3  # evicted one to admit
            prios = sorted(
                int(e.value.tx.split(b":")[1]) for e in mp._txs
            )
            assert prios == [2, 3, 50]  # priority-1 tx was the victim
        finally:
            client.stop()

    def test_rejected_when_no_lower_priority_exists(self):
        mp, client = _mk(size=2)
        try:
            mp.check_tx(_tx(10, "a"))
            mp.check_tx(_tx(10, "b"))
            mp.flush_app_conn()
            mp.check_tx(_tx(5, "loser"))
            mp.flush_app_conn()
            assert mp.size() == 2
            kept = {e.value.tx for e in mp._txs}
            assert _tx(5, "loser") not in kept
            # equal priority also cannot displace (strictly lower only)
            mp.check_tx(_tx(10, "tie"))
            mp.flush_app_conn()
            assert _tx(10, "tie") not in {e.value.tx for e in mp._txs}
        finally:
            client.stop()

    def test_update_removes_committed_and_keeps_priorities(self):
        mp, client = _mk()
        try:
            for prio in (3, 7, 5):
                mp.check_tx(_tx(prio))
            mp.flush_app_conn()
            mp.lock()
            try:
                mp.update(
                    1,
                    [_tx(7)],
                    [abci.ResponseDeliverTx(code=0)],
                )
            finally:
                mp.unlock()
            reaped = mp.reap_max_bytes_max_gas(-1, -1)
            assert [int(t.split(b":")[1]) for t in reaped] == [5, 3]
        finally:
            client.stop()

    def test_node_selects_v1_from_config(self):
        cfg = make_test_config()
        cfg.mempool.version = "v1"
        # structural check only: the Node wiring picks PriorityMempool
        from cometbft_tpu.mempool.priority_mempool import PriorityMempool as PM
        from cometbft_tpu.node.node import CListMempool as CL  # imported there

        assert issubclass(PM, CL)
        assert cfg.mempool.version == "v1"


class TestTTLEviction:
    def test_ttl_num_blocks_purges_on_update(self):
        """[mempool] ttl_num_blocks: txs older than N heights are purged
        at commit (v1 mempool.go purgeExpiredTxs — the knob was inert)."""
        mp, client = _mk()
        mp.config.ttl_num_blocks = 2
        mp.check_tx(_tx(5, "old"), None)
        mp.flush_app_conn()
        assert mp.size() == 1
        mp.lock()
        try:
            for h in (1, 2, 3, 4):
                mp.update(h, [], [])
        finally:
            mp.unlock()
        assert mp.size() == 0, "expired tx survived"
        client.stop()

    def test_ttl_duration_purges_on_update(self):
        import time as _t

        mp, client = _mk()
        mp.config.ttl_duration_ns = int(0.05 * 1e9)  # 50 ms
        mp.check_tx(_tx(5, "stale"), None)
        mp.flush_app_conn()
        assert mp.size() == 1
        _t.sleep(0.1)
        mp.lock()
        try:
            mp.update(1, [], [])
        finally:
            mp.unlock()
        assert mp.size() == 0
        client.stop()

    def test_no_ttl_keeps_txs(self):
        mp, client = _mk()
        mp.check_tx(_tx(5, "keep"), None)
        mp.flush_app_conn()
        mp.lock()
        try:
            mp.update(1, [], [])
        finally:
            mp.unlock()
        assert mp.size() == 1
        client.stop()
