"""Light client: verifier matrix (CPU + TPU backends), trusted store,
bisection client, and divergence detection.

Model: reference light/verifier_test.go (the adjacent/non-adjacent case
tables), light/client_test.go (bisection, sequential, update, backwards),
light/detector_test.go (forked primary/witness → attack evidence).
"""

import pytest

from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.light import (
    Client,
    DBStore,
    ErrInvalidHeader,
    ErrLightClientAttack,
    ErrNewValSetCantBeTrusted,
    ErrOldHeaderExpired,
    MockProvider,
    TrustOptions,
    verify,
    verify_adjacent,
    verify_backwards,
    verify_non_adjacent,
)
from cometbft_tpu.light.verifier import validate_trust_level
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import BlockID, Header
from cometbft_tpu.types.light_block import LightBlock, SignedHeader
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.validator_set import Fraction, ValidatorSet
from cometbft_tpu.types.validator import Validator

CHAIN_ID = "light-test-chain"
T0 = 1_700_000_000
HOUR_NS = 3600 * 1_000_000_000
WEEK_NS = 7 * 24 * HOUR_NS
DRIFT_NS = 10 * 1_000_000_000


def _ts(height):
    return Timestamp(T0 + height * 60, 0)


def _distinct_validator_set(n=4, power=10, tag="other"):
    """A validator set whose keys don't overlap deterministic_validator_set
    (that helper varies only power, not key material)."""
    from cometbft_tpu.crypto import ed25519 as ed
    from cometbft_tpu.types.priv_validator import MockPV

    privs = [
        MockPV(ed.gen_priv_key_from_secret(f"{tag}-validator-{i}".encode()))
        for i in range(n)
    ]
    vals = [Validator.new(pv.get_pub_key(), power) for pv in privs]
    vs = ValidatorSet(vals)
    by_addr = {pv.get_pub_key().address(): pv for pv in privs}
    ordered = [by_addr[v.address] for v in vs.validators]
    return vs, ordered


def _make_header(height, vals, next_vals, last_block_id, app_hash=b"\x0a" * 32):
    from cometbft_tpu.proto.version import ConsensusVersion
    from cometbft_tpu.version import BLOCK_PROTOCOL

    return Header(
        version=ConsensusVersion(BLOCK_PROTOCOL, 0),
        chain_id=CHAIN_ID,
        height=height,
        time=_ts(height),
        last_block_id=last_block_id,
        validators_hash=vals.hash(),
        next_validators_hash=next_vals.hash(),
        consensus_hash=b"\x0c" * 32,
        app_hash=app_hash,
        proposer_address=vals.validators[0].address,
    )


def _sign_header(header, vals, privs):
    bid = BlockID(header.hash(), PartSetHeader(1, b"\x02" * 32))
    commit = test_util.make_commit(
        bid, header.height, 0, vals, privs, CHAIN_ID, now=header.time
    )
    return SignedHeader(header, commit)


def _light_chain(n, val_changes=None, n_vals=4, power=10):
    """n light blocks; val_changes maps height -> (vals, privs) taking
    effect AT that height (announced via next_validators_hash at h-1)."""
    val_changes = val_changes or {}
    vals, privs = test_util.deterministic_validator_set(n_vals, power)
    blocks = {}
    last_bid = BlockID()
    cur = (vals, privs)
    for h in range(1, n + 1):
        nxt = val_changes.get(h + 1, cur)
        header = _make_header(h, cur[0], nxt[0], last_bid)
        sh = _sign_header(header, cur[0], cur[1])
        blocks[h] = LightBlock(signed_header=sh, validator_set=cur[0])
        last_bid = BlockID(header.hash(), PartSetHeader(1, b"\x02" * 32))
        cur = nxt
    return blocks, vals, privs


class TestVerifierMatrix:
    """Reference: light/verifier_test.go case tables, both crypto backends."""

    @pytest.fixture(scope="class")
    def chain(self):
        return _light_chain(6)

    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_adjacent_success(self, chain, backend):
        blocks, _, _ = chain
        verify_adjacent(
            blocks[1].signed_header, blocks[2].signed_header,
            blocks[2].validator_set, WEEK_NS, _ts(3), DRIFT_NS,
            backend=backend,
        )

    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_non_adjacent_success_same_vals(self, chain, backend):
        blocks, _, _ = chain
        verify_non_adjacent(
            blocks[1].signed_header, blocks[1].validator_set,
            blocks[5].signed_header, blocks[5].validator_set,
            WEEK_NS, _ts(6), DRIFT_NS, backend=backend,
        )

    def test_adjacent_wrong_height_gap(self, chain):
        blocks, _, _ = chain
        with pytest.raises(ValueError, match="adjacent"):
            verify_adjacent(
                blocks[1].signed_header, blocks[3].signed_header,
                blocks[3].validator_set, WEEK_NS, _ts(4), DRIFT_NS,
            )

    def test_expired_trusted_header(self, chain):
        blocks, _, _ = chain
        with pytest.raises(ErrOldHeaderExpired):
            verify_adjacent(
                blocks[1].signed_header, blocks[2].signed_header,
                blocks[2].validator_set, HOUR_NS,
                Timestamp(T0 + 7200, 0),  # 2h later, 1h trusting period
                DRIFT_NS,
            )

    def test_header_from_the_future(self, chain):
        blocks, _, _ = chain
        with pytest.raises(ErrInvalidHeader, match="future"):
            verify_adjacent(
                blocks[1].signed_header, blocks[2].signed_header,
                blocks[2].validator_set, WEEK_NS,
                Timestamp(T0, 0),  # "now" before block 2's time
                DRIFT_NS,
            )

    def test_next_vals_hash_mismatch(self, chain):
        blocks, _, _ = chain
        other_vals, other_privs = _distinct_validator_set(4, 99)
        header = _make_header(2, other_vals, other_vals, BlockID())
        sh = _sign_header(header, other_vals, other_privs)
        with pytest.raises(ErrInvalidHeader, match="next validators"):
            verify_adjacent(
                blocks[1].signed_header, sh, other_vals, WEEK_NS, _ts(3),
                DRIFT_NS,
            )

    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_non_adjacent_no_trust_overlap(self, chain, backend):
        """A completely different validator set at the target height: the
        trusting check must fail with the bisection-triggering error."""
        blocks, _, _ = chain
        other_vals, other_privs = _distinct_validator_set(4, 99)
        header = _make_header(5, other_vals, other_vals, BlockID())
        sh = _sign_header(header, other_vals, other_privs)
        with pytest.raises(ErrNewValSetCantBeTrusted):
            verify_non_adjacent(
                blocks[1].signed_header, blocks[1].validator_set,
                sh, other_vals, WEEK_NS, _ts(6), DRIFT_NS,
                backend=backend,
            )

    @pytest.mark.parametrize("backend", ["cpu", "tpu"])
    def test_insufficient_new_set_signatures(self, chain, backend):
        """2/3 of the new set didn't sign → ErrInvalidHeader."""
        blocks, vals, privs = chain
        header = _make_header(2, vals, vals, blocks[1].signed_header.commit.block_id)
        header.validators_hash = vals.hash()
        header.next_validators_hash = vals.hash()
        sh = _sign_header(header, vals, privs)
        # blank out all but one signature (10/40 power < 2/3)
        from cometbft_tpu.types.block import CommitSig

        for i in range(1, len(sh.commit.signatures)):
            sh.commit.signatures[i] = CommitSig.absent()
        with pytest.raises(ErrInvalidHeader):
            verify_adjacent(
                blocks[1].signed_header, sh, vals, WEEK_NS, _ts(3), DRIFT_NS,
                backend=backend,
            )

    def test_verify_dispatches(self, chain):
        blocks, _, _ = chain
        verify(
            blocks[1].signed_header, blocks[1].validator_set,
            blocks[2].signed_header, blocks[2].validator_set,
            WEEK_NS, _ts(3), DRIFT_NS,
        )
        verify(
            blocks[1].signed_header, blocks[1].validator_set,
            blocks[4].signed_header, blocks[4].validator_set,
            WEEK_NS, _ts(5), DRIFT_NS,
        )

    def test_backwards(self, chain):
        blocks, _, _ = chain
        verify_backwards(
            blocks[2].signed_header.header, blocks[3].signed_header.header
        )
        with pytest.raises(ErrInvalidHeader, match="does not match"):
            verify_backwards(
                blocks[1].signed_header.header, blocks[3].signed_header.header
            )

    def test_trust_level_validation(self):
        validate_trust_level(Fraction(1, 3))
        validate_trust_level(Fraction(1, 1))
        for bad in (Fraction(1, 4), Fraction(2, 1), Fraction(0, 0)):
            with pytest.raises(ValueError):
                validate_trust_level(bad)


class TestDBStore:
    def test_save_load_latest_first_prune(self):
        blocks, _, _ = _light_chain(5)
        store = DBStore(MemDB())
        for h in (1, 2, 3, 4, 5):
            store.save_light_block(blocks[h])
        assert store.latest_height() == 5
        assert store.first_height() == 1
        assert store.size() == 5
        assert store.light_block(3).height == 3
        assert store.light_block(3).signed_header.header.hash() == (
            blocks[3].signed_header.header.hash()
        )
        store.prune(2)
        assert store.size() == 2
        assert store.first_height() == 4
        assert store.light_block(1) is None


def _mk_client(blocks, trust_height=1, witness_blocks=None, **kw):
    primary = MockProvider(CHAIN_ID, blocks)
    witnesses = []
    if witness_blocks is not None:
        witnesses = [MockProvider(CHAIN_ID, witness_blocks)]
    opts = TrustOptions(
        period_ns=WEEK_NS,
        height=trust_height,
        hash=blocks[trust_height].signed_header.header.hash(),
    )
    return Client(
        CHAIN_ID, opts, primary, witnesses, DBStore(MemDB()), **kw
    ), primary


class TestLightClient:
    def test_bisection_to_latest(self):
        blocks, _, _ = _light_chain(40)
        client, _ = _mk_client(blocks)
        lb = client.verify_light_block_at_height(40, _ts(41))
        assert lb.height == 40
        assert client.last_trusted_height() == 40

    def test_bisection_with_validator_rotation(self):
        """Validator set fully rotates twice along the chain — bisection
        must insert pivots at the rotation points."""
        v2 = _distinct_validator_set(4, 11, tag="gen2")
        v3 = _distinct_validator_set(4, 12, tag="gen3")
        blocks, _, _ = _light_chain(30, val_changes={11: v2, 21: v3})
        client, _ = _mk_client(blocks)
        lb = client.verify_light_block_at_height(30, _ts(31))
        assert lb.height == 30

    def test_sequential_verification(self):
        blocks, _, _ = _light_chain(12)
        client, _ = _mk_client(blocks, sequential=True)
        lb = client.verify_light_block_at_height(12, _ts(13))
        assert lb.height == 12
        # sequential stores every intermediate height? at least the target
        assert client.last_trusted_height() == 12

    def test_update_to_latest(self):
        blocks, _, _ = _light_chain(25)
        client, _ = _mk_client(blocks)
        lb = client.update(_ts(26))
        assert lb is not None and lb.height == 25
        assert client.update(_ts(26)) is None  # already at tip

    def test_backwards_retrieval(self):
        blocks, _, _ = _light_chain(20)
        client, _ = _mk_client(blocks, trust_height=1)
        client.verify_light_block_at_height(20, _ts(21))
        lb = client.verify_light_block_at_height(7, _ts(21))
        assert lb.height == 7
        assert lb.signed_header.header.hash() == (
            blocks[7].signed_header.header.hash()
        )

    def test_bad_root_of_trust_hash_rejected(self):
        blocks, _, _ = _light_chain(5)
        primary = MockProvider(CHAIN_ID, blocks)
        opts = TrustOptions(period_ns=WEEK_NS, height=1, hash=b"\x13" * 32)
        with pytest.raises(ValueError, match="expected header's hash"):
            Client(CHAIN_ID, opts, primary, [], DBStore(MemDB()))


class TestDivergenceDetection:
    def _forked_chain(self, n, fork_at):
        """Two chains that share [1, fork_at) and diverge after (same
        validator keys — an equivocation-style fork)."""
        blocks, vals, privs = _light_chain(n)
        forked = dict(blocks)
        last_bid = forked[fork_at - 1].signed_header.commit.block_id
        for h in range(fork_at, n + 1):
            header = _make_header(
                h, vals, vals, last_bid, app_hash=b"\xee" * 32
            )
            sh = _sign_header(header, vals, privs)
            forked[h] = LightBlock(signed_header=sh, validator_set=vals)
            last_bid = BlockID(header.hash(), PartSetHeader(1, b"\x02" * 32))
        return blocks, forked

    def test_conflicting_witness_raises_attack_and_reports_evidence(self):
        honest, forked = self._forked_chain(10, fork_at=6)
        client, primary = _mk_client(honest, witness_blocks=forked)
        witness = client.witnesses[0]
        with pytest.raises(ErrLightClientAttack):
            client.verify_light_block_at_height(10, _ts(11))
        # evidence reported to both sides
        assert witness.evidence, "witness got no evidence against primary"
        assert primary.evidence, "primary got no evidence against witness"
        from cometbft_tpu.types.evidence import LightClientAttackEvidence

        assert isinstance(witness.evidence[0], LightClientAttackEvidence)
        assert isinstance(primary.evidence[0], LightClientAttackEvidence)
        # equivocation fork: common height is the trusted (primary) height
        assert primary.evidence[0].conflicting_block.signed_header.header.app_hash == b"\xee" * 32

    def test_witness_that_cannot_prove_is_dropped(self):
        """A witness serving garbage (unverifiable chain) is removed, and
        verification succeeds against the honest primary."""
        honest, _, _ = _light_chain(10)
        junk_vals, junk_privs = _distinct_validator_set(4, 99, tag="junk")
        junk = {}
        last_bid = BlockID()
        for h in range(1, 11):
            header = _make_header(h, junk_vals, junk_vals, last_bid)
            sh = _sign_header(header, junk_vals, junk_privs)
            junk[h] = LightBlock(signed_header=sh, validator_set=junk_vals)
            last_bid = BlockID(header.hash(), PartSetHeader(1, b"\x02" * 32))
        # root of trust must agree, else construction fails: splice honest h1
        junk[1] = honest[1]
        client, _ = _mk_client(honest, witness_blocks=junk)
        lb = client.verify_light_block_at_height(10, _ts(11))
        assert lb.height == 10
        assert client.witnesses == []  # junk witness removed

    def test_agreeing_witness_passes(self):
        honest, _, _ = _light_chain(10)
        client, _ = _mk_client(honest, witness_blocks=dict(honest))
        lb = client.verify_light_block_at_height(10, _ts(11))
        assert lb.height == 10
        assert len(client.witnesses) == 1
