"""Capacity telemetry: windowed utilization, per-subsystem RED metering,
the SLO engine, and the /debug/verify health plane.

Contract under test (crypto/telemetry.py + the MetricsServer route +
tools/verify_top.py):
  - _IntervalWindow clips busy intervals to the rolling window; the
    duty cycle never exceeds 1.0 even with overlapping hedge intervals;
  - SLOEngine reports nearest-rank p50/p99, violation counts, and an
    error-budget burn rate against the configured target;
  - note_request meters RED per origin subsystem (untagged tenants fall
    under "untagged") and feeds the SLO window;
  - the headroom estimator projects from the bottleneck device's duty
    cycle scaled by healthy capacity, and refuses to project while cold;
  - snapshot() is one JSON-ready document that survives raising
    sources, and refreshes the verify_slo_*/verify_telemetry_* gauges;
  - scheduler + supervisor integration: a real submit through
    BackendSpec("cpu") lands in the RED table and the "cpu"
    pseudo-device busy window;
  - MetricsServer serves the snapshot at /debug/verify and
    tools/verify_top.py --once renders it.
"""

import json
import os
import subprocess
import sys
import urllib.request

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import telemetry as telemetrylib
from cometbft_tpu.crypto.batch import BackendSpec
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.crypto.supervisor import BackendSupervisor
from cometbft_tpu.crypto.telemetry import (
    DEFAULT_SLO_COMMIT_MS,
    SLOEngine,
    TelemetryHub,
    _IntervalWindow,
    slo_commit_ms_default,
)
from cometbft_tpu.libs.metrics import MetricsServer, Registry


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _make_items(n, tag=b"tel"):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"telemetry-msg-" + i.to_bytes(4, "big")
        items.append((k.pub_key(), msg, k.sign(msg)))
    return items


class TestSLODefault:
    def test_precedence_env_config_builtin(self, monkeypatch):
        monkeypatch.delenv("CBFT_SLO_COMMIT_MS", raising=False)
        assert slo_commit_ms_default() == DEFAULT_SLO_COMMIT_MS
        assert slo_commit_ms_default(250) == 250
        monkeypatch.setenv("CBFT_SLO_COMMIT_MS", "42")
        assert slo_commit_ms_default(250) == 42
        monkeypatch.setenv("CBFT_SLO_COMMIT_MS", "not-a-number")
        assert slo_commit_ms_default(250) == 250

    def test_floor_is_one_ms(self, monkeypatch):
        monkeypatch.setenv("CBFT_SLO_COMMIT_MS", "-5")
        assert slo_commit_ms_default() == 1


class TestIntervalWindow:
    def test_clips_to_window(self):
        w = _IntervalWindow()
        w.add(0.0, 10.0, 100)  # straddles the cutoff
        w.add(95.0, 96.0, 7)
        busy, sigs = w.busy_in(now=100.0, window_s=10.0)
        # only [95, 96] is inside [90, 100]; the first interval ended
        # at t=10, before the cutoff
        assert busy == pytest.approx(1.0)
        assert sigs == 7

    def test_partial_overlap_is_clipped(self):
        w = _IntervalWindow()
        w.add(85.0, 95.0, 10)  # 5s of it lands inside [90, 100]
        busy, _ = w.busy_in(now=100.0, window_s=10.0)
        assert busy == pytest.approx(5.0)

    def test_overlapping_intervals_cap_at_saturation(self):
        # hedge + retry racing on one device: raw busy can exceed the
        # window; the hub caps utilization at 1.0
        clock = FakeClock()
        hub = TelemetryHub(window_s=10.0, clock=clock)
        hub.note_device_busy("dev0", clock.t - 8, clock.t, 64)
        hub.note_device_busy("dev0", clock.t - 8, clock.t, 64)
        util = hub.utilization()
        assert util["dev0"]["utilization"] == 1.0
        assert util["dev0"]["window_sigs"] == 128


class TestSLOEngine:
    def test_percentiles_and_violations(self):
        clock = FakeClock()
        slo = SLOEngine(target_ms=100, window_s=60.0, clock=clock)
        clock.advance(10.0)
        for ms in (10, 20, 30, 40, 50, 60, 70, 80, 90, 500):
            slo.observe(ms / 1e3, n_sigs=10)
        snap = slo.snapshot()
        assert snap["requests"] == 10
        assert snap["violations"] == 1  # only the 500ms sample
        assert snap["p50_ms"] == pytest.approx(50.0)
        assert snap["p99_ms"] == pytest.approx(500.0)
        # 10% violating over a 1% budget: burning 10x sustainable
        assert snap["burn_rate"] == pytest.approx(10.0)
        # 100 sigs over the 10s the node has been alive (< window)
        assert snap["throughput_sigs_per_sec"] == pytest.approx(10.0)

    def test_samples_age_out_of_window(self):
        clock = FakeClock()
        slo = SLOEngine(target_ms=100, window_s=60.0, clock=clock)
        slo.observe(0.5)  # violation, soon stale
        clock.advance(120.0)
        slo.observe(0.01)
        snap = slo.snapshot()
        assert snap["requests"] == 1
        assert snap["violations"] == 0
        assert snap["burn_rate"] == 0.0

    def test_empty_window_is_calm(self):
        snap = SLOEngine(target_ms=100).snapshot()
        assert snap["requests"] == 0
        assert snap["p50_ms"] is None
        assert snap["p99_ms"] is None
        assert snap["burn_rate"] == 0.0


class TestHubRED:
    def test_per_subsystem_accounting(self):
        clock = FakeClock()
        hub = TelemetryHub(window_s=60.0, clock=clock)
        hub.note_request(64, 0.001, 0.004, True,
                         subsystem="consensus", height=7)
        hub.note_request(32, 0.001, 0.004, False,
                         subsystem="consensus", height=8)
        hub.note_request(16, 0.0, 0.002, True, subsystem="blocksync")
        hub.note_request(8, 0.0, 0.001, True)  # origin-less
        subs = hub.subsystems()
        cons = subs["consensus"]
        assert cons["requests"] == 2
        assert cons["errors"] == 1
        assert cons["sigs"] == 96
        assert cons["last_height"] == 8
        assert cons["p50_ms"] == pytest.approx(5.0)
        assert subs["blocksync"]["requests"] == 1
        assert subs[telemetrylib.UNTAGGED]["sigs"] == 8

    def test_red_counters_exported(self):
        r = Registry("cometbft")
        hub = TelemetryHub(metrics=telemetrylib.Metrics(r))
        hub.note_request(4, 0.0, 0.001, False, subsystem="evidence")
        text = r.expose()
        assert ('cometbft_verify_telemetry_red_requests'
                '{subsystem="evidence"} 1') in text
        assert ('cometbft_verify_telemetry_red_errors'
                '{subsystem="evidence"} 1') in text
        assert ('cometbft_verify_telemetry_red_sigs'
                '{subsystem="evidence"} 4') in text
        assert "verify_telemetry_red_latency_seconds_bucket" in text


class TestLaneFill:
    def test_efficiency_ratio(self):
        clock = FakeClock()
        hub = TelemetryHub(window_s=60.0, clock=clock)
        hub.note_chunk("dev0", 100, 128)
        hub.note_chunk("dev0", 28, 32)
        fill = hub.lane_fill()
        assert fill["chunks"] == 2
        assert fill["real_lanes"] == 128
        assert fill["padded_lanes"] == 160
        assert fill["efficiency"] == pytest.approx(0.8)

    def test_no_chunks_means_no_ratio(self):
        assert TelemetryHub().lane_fill()["efficiency"] is None


class TestHeadroom:
    def test_cold_refuses_to_project(self):
        head = TelemetryHub().headroom()
        assert head["headroom_sigs_per_sec"] is None
        assert head["projected_capacity_sigs_per_sec"] is None

    def test_projection_math(self):
        clock = FakeClock()
        hub = TelemetryHub(window_s=10.0, clock=clock)
        clock.advance(100.0)
        # device busy 50% of the window, serving all observed traffic
        hub.note_device_busy("dev0", clock.t - 5.0, clock.t, 1000)
        hub.note_request(1000, 0.0, 0.001, True, subsystem="consensus")
        hub.set_capacity_fraction(lambda: 0.5)
        head = hub.headroom()
        tput = head["throughput_sigs_per_sec"]
        assert tput == pytest.approx(100.0)  # 1000 sigs / 10s window
        assert head["peak_device_utilization"] == pytest.approx(0.5)
        assert head["healthy_capacity_fraction"] == pytest.approx(0.5)
        # 100 / 0.5 util * 0.5 healthy = 100 projected -> 0 headroom
        assert head["projected_capacity_sigs_per_sec"] == pytest.approx(
            tput
        )
        assert head["headroom_sigs_per_sec"] == pytest.approx(0.0)

    def test_raising_capacity_oracle_is_advisory(self):
        clock = FakeClock()
        hub = TelemetryHub(window_s=10.0, clock=clock)
        clock.advance(100.0)
        hub.note_device_busy("dev0", clock.t - 5.0, clock.t, 100)
        hub.note_request(100, 0.0, 0.001, True)

        def boom():
            raise RuntimeError("oracle down")

        hub.set_capacity_fraction(boom)
        head = hub.headroom()
        assert head["healthy_capacity_fraction"] == 1.0
        assert head["headroom_sigs_per_sec"] is not None


class TestSnapshot:
    def test_document_shape(self):
        hub = TelemetryHub()
        hub.note_request(4, 0.0, 0.001, True, subsystem="light")
        snap = hub.snapshot()
        for key in ("ts", "window_s", "devices", "lane_fill",
                    "subsystems", "slo", "headroom", "sources"):
            assert key in snap
        json.dumps(snap)  # must be JSON-ready as served

    def test_raising_source_reports_error(self):
        hub = TelemetryHub()
        hub.register_source("ok", lambda: {"fine": 1})
        hub.register_source("broken", lambda: 1 / 0)
        sources = hub.snapshot()["sources"]
        assert sources["ok"] == {"fine": 1}
        assert "ZeroDivisionError" in sources["broken"]["error"]

    def test_snapshot_refreshes_gauges(self):
        r = Registry("cometbft")
        clock = FakeClock()
        hub = TelemetryHub(
            metrics=telemetrylib.Metrics(r), slo_target_ms=100,
            window_s=10.0, clock=clock,
        )
        clock.advance(50.0)
        hub.note_device_busy("dev0", clock.t - 2.0, clock.t, 64)
        hub.note_request(64, 0.0, 0.010, True, subsystem="consensus")
        hub.snapshot()
        text = r.expose()
        assert "cometbft_verify_slo_target_ms 100" in text
        assert "cometbft_verify_slo_p50_ms 10" in text
        assert "cometbft_verify_slo_window_requests 1" in text
        assert ('cometbft_verify_telemetry_device_utilization'
                '{device="dev0"} 0.2') in text

    def test_cold_headroom_gauge_is_negative_one(self):
        r = Registry("cometbft")
        hub = TelemetryHub(metrics=telemetrylib.Metrics(r))
        hub.note_request(1, 0.0, 0.001, True)  # wakes slo gauges
        hub.snapshot()
        assert "cometbft_verify_slo_headroom_sigs_per_sec -1" in (
            r.expose()
        )


class TestDefaultHub:
    def test_set_get_restore(self):
        prev = telemetrylib.set_default_hub(None)
        try:
            assert telemetrylib.default_hub() is None
            hub = TelemetryHub()
            assert telemetrylib.set_default_hub(hub) is None
            assert telemetrylib.default_hub() is hub
            assert telemetrylib.set_default_hub(None) is hub
        finally:
            telemetrylib.set_default_hub(prev)


class TestSchedulerIntegration:
    def test_submit_lands_in_red_and_slo(self):
        hub = TelemetryHub(slo_target_ms=60_000)
        sched = VerifyScheduler(
            spec=BackendSpec("cpu"), flush_us=500, telemetry=hub
        )
        sched.start()
        try:
            ok, mask = sched.submit(
                _make_items(4), subsystem="blocksync", height=12
            ).result(timeout=60)
            assert ok and all(mask)
        finally:
            sched.stop()
        snap = hub.snapshot()
        bs = snap["subsystems"]["blocksync"]
        assert bs["requests"] == 1
        assert bs["sigs"] == 4
        assert bs["last_height"] == 12
        assert bs["p50_ms"] is not None and bs["p50_ms"] > 0
        assert snap["slo"]["requests"] == 1
        assert snap["slo"]["violations"] == 0

    def test_queue_snapshot_source(self):
        hub = TelemetryHub()
        sched = VerifyScheduler(
            spec=BackendSpec("cpu"), flush_us=500, telemetry=hub
        )
        hub.register_source("scheduler", sched.queue_snapshot)
        sched.start()
        try:
            sched.submit(_make_items(2)).result(timeout=60)
        finally:
            sched.stop()
        q = hub.snapshot()["sources"]["scheduler"]
        assert q["queue_depth"] == 0
        assert q["dispatches"] >= 1
        assert q["lane_budget"] > 0


class TestSupervisorIntegration:
    def test_cpu_pseudo_device_and_capacity_source(self):
        hub = TelemetryHub()
        sup = BackendSupervisor(spec=BackendSpec("cpu"), telemetry=hub)
        try:
            mask = sup.verify_items(_make_items(3))
            assert mask == [True, True, True]
        finally:
            sup.stop()
        snap = hub.snapshot()
        cpu = snap["devices"]["cpu"]
        assert cpu["window_sigs"] == 3
        assert cpu["busy_s"] > 0
        cap = snap["sources"]["supervisor"]
        assert cap["state"] == "healthy"
        assert cap["healthy_capacity_fraction"] == pytest.approx(1.0)
        assert cap["domains"]  # at least device 0
        for dom in cap["domains"].values():
            assert dom["state"] == "healthy"
            assert dom["failures"] == 0

    def test_headroom_scales_by_supervisor_fraction(self):
        hub = TelemetryHub()
        sup = BackendSupervisor(spec=BackendSpec("cpu"), telemetry=hub)
        try:
            assert hub._capacity_fn is not None
            assert hub._capacity_fn() == pytest.approx(
                sup.healthy_capacity_fraction()
            )
        finally:
            sup.stop()


class TestDebugVerifyEndpoint:
    def test_served_snapshot(self):
        r = Registry("cometbft")
        hub = TelemetryHub(metrics=telemetrylib.Metrics(r))
        hub.note_request(8, 0.0, 0.002, True,
                         subsystem="consensus", height=3)
        srv = MetricsServer(r, telemetry=hub)
        port = srv.serve("127.0.0.1", 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/verify", timeout=5
            ).read().decode()
        finally:
            srv.stop()
        doc = json.loads(body)
        assert doc["subsystems"]["consensus"]["last_height"] == 3
        assert doc["slo"]["target_ms"] == hub.slo.target_ms
        assert "headroom" in doc and "devices" in doc

    def test_absent_without_hub(self):
        srv = MetricsServer(Registry("cometbft"))
        port = srv.serve("127.0.0.1", 0)
        try:
            with pytest.raises(Exception):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/verify", timeout=5
                )
        finally:
            srv.stop()


class TestVerifyTopCLI:
    def test_once_renders_live_endpoint(self, tmp_path):
        r = Registry("cometbft")
        hub = TelemetryHub(metrics=telemetrylib.Metrics(r))
        hub.note_request(64, 0.0005, 0.004, True,
                         subsystem="consensus", height=41)
        hub.note_device_busy("dev0", hub._clock() - 0.01,
                             hub._clock(), 64)
        hub.note_chunk("dev0", 64, 64)
        srv = MetricsServer(r, telemetry=hub)
        port = srv.serve("127.0.0.1", 0)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        try:
            res = subprocess.run(
                [sys.executable, os.path.join(repo, "tools",
                                              "verify_top.py"),
                 f"http://127.0.0.1:{port}", "--once"],
                capture_output=True, text=True, timeout=60, cwd=repo,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
        finally:
            srv.stop()
        assert res.returncode == 0, res.stderr[-400:]
        out = res.stdout
        assert "verify-path capacity" in out
        assert "SLO" in out and "target=" in out
        assert "consensus" in out
        assert "dev0" in out
        assert "41" in out  # last_height rendered

    def test_once_renders_snapshot_file(self, tmp_path):
        hub = TelemetryHub()
        hub.note_request(4, 0.0, 0.001, True, subsystem="light")
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(hub.snapshot()))
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "verify_top.py"),
             str(path), "--once"],
            capture_output=True, text=True, timeout=60, cwd=repo,
        )
        assert res.returncode == 0, res.stderr[-400:]
        assert "light" in res.stdout

    def test_json_one_shot_round_trips_snapshot(self, tmp_path):
        # PR 15 satellite: `verify_top --json` must emit ONE parseable
        # machine-readable snapshot (route_audit's input contract)
        from cometbft_tpu.crypto.decisions import DecisionLedger

        hub = TelemetryHub()
        hub.note_request(8, 0.0, 0.002, True, subsystem="consensus")
        led = DecisionLedger(ring_interval_s=0.0)
        for _ in range(4):
            dec = led.open(n=8, reason="size")
            dec.taken = "cpu"
            led.finish(dec, 0.002)
        hub.register_source("decisions", led.snapshot)
        hub.register_source(
            "keystore",
            lambda: {"resident": True, "entries": [],
                     "stats": {"hits": 3, "misses": 1}},
        )
        path = tmp_path / "snap.json"
        path.write_text(json.dumps(hub.snapshot()))
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "verify_top.py"),
             str(path), "--json"],
            capture_output=True, text=True, timeout=60, cwd=repo,
        )
        assert res.returncode == 0, res.stderr[-400:]
        doc = json.loads(res.stdout)  # exactly one JSON document
        assert doc["sources"]["decisions"]["counts"] == {"cpu": 4}
        assert doc["sources"]["keystore"]["resident"] is True
        assert "slo" in doc
        # and the human rendering carries the new sections
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "verify_top.py"),
             str(path), "--once"],
            capture_output=True, text=True, timeout=60, cwd=repo,
        )
        assert res.returncode == 0, res.stderr[-400:]
        assert "decision plane" in res.stdout
        assert "keystore" in res.stdout

    def test_rejects_non_snapshot(self, tmp_path):
        path = tmp_path / "junk.json"
        path.write_text('{"hello": 1}')
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        res = subprocess.run(
            [sys.executable, os.path.join(repo, "tools", "verify_top.py"),
             str(path), "--once"],
            capture_output=True, text=True, timeout=60, cwd=repo,
        )
        assert res.returncode == 1
        assert "not a verify capacity snapshot" in res.stderr
