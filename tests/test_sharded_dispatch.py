"""Multi-device sharded megabatch dispatch.

Contract under test (crypto/tpu/mesh.py dispatch_sharded/shard_plan,
crypto/tpu/topology.py quarantine+generation, crypto/scheduler.py
three-way routing, crypto/supervisor.py _verify_mesh, crypto/faults.py
run_chaos_sharded, crypto/tpu/aot.py sharded warm plan):

  - shard_bucket pads each device's shard to a pow2 bucket (floored at
    min_pad); warm boot uses the SAME arithmetic, so a warmed sharded
    ladder covers every shape dispatch_sharded can produce;
  - shard_plan slices the mesh over the HEALTHY fault domains in stable
    index order, cached per topology generation: quarantining a domain
    bumps the generation and the next dispatch re-slices over the
    survivors (no whole-plane trip);
  - dispatch_sharded honors the dispatch_batch contract: per-device
    chunk caps clamp the per-shard lane count, the thread's cancel
    event is checked at every chunk boundary, verdicts are ground-truth
    exact at non-pow2 n (shard-boundary coverage);
  - the scheduler routes each coalesced flush three ways (cpu / single /
    sharded) on the learned crossover with env > config > calibration
    precedence, and CBFT_MESH_ROUTE overrides;
  - a warmed (kernel, bucket, mesh) triple serves a sharded dispatch
    with ZERO new AOT registry misses;
  - the supervised sharded path verifies bit-identically to the CPU
    backend, attributes a mid-flow device kill to the offending fault
    domain, and keeps serving on the re-sliced mesh within the partial-
    degradation throughput bound (run_chaos_sharded).

Runs on the virtual 8-device CPU mesh the suite conftest forces via
XLA_FLAGS=--xla_force_host_platform_device_count — no hardware needed.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import BackendSpec, CPUBatchVerifier
from cometbft_tpu.crypto.faults import FaultPlan, install, run_chaos_sharded
from cometbft_tpu.crypto.scheduler import (
    DEFAULT_SHARD_MIN_BATCH,
    VerifyScheduler,
    shard_min_batch_default,
)
from cometbft_tpu.crypto.supervisor import BackendSupervisor
from cometbft_tpu.crypto.tpu import aot, mesh, topology


def _make_items(n, tag=b"", poison_at=()):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"sharded-msg-" + tag + i.to_bytes(4, "big")
        sig = k.sign(msg)
        if i in poison_at:
            sig = b"\x00" * 64
        items.append((k.pub_key(), msg, sig))
    return items


def _cpu_mask(items):
    bv = CPUBatchVerifier()
    for pk, m, s in items:
        bv.add(pk, m, s)
    _, mask = bv.verify()
    return mask


_seq = [0]


def _faulty_sharded(n_domains, plan=None, **sup_kwargs):
    """A fresh FaultyBackend + supervisor over an n-domain virtual
    topology (unique backend name per call), tuned for sharded tests."""
    _seq[0] += 1
    name = f"test-sharded-{_seq[0]}"
    plan = install(name=name, inner="cpu",
                   plan=plan if plan is not None else FaultPlan(seed=_seq[0]))
    topo = topology.DeviceTopology.virtual(n_domains)
    sup_kwargs.setdefault("dispatch_timeout_ms", 2000)
    sup_kwargs.setdefault("breaker_threshold", 1)
    sup_kwargs.setdefault("audit_pct", 0)
    sup_kwargs.setdefault("hedge_pct", 0)
    sup_kwargs.setdefault("probe_base_ms", 60_000)
    sup_kwargs.setdefault("probe_max_ms", 120_000)
    sup = BackendSupervisor(spec=BackendSpec(name), topology=topo,
                            **sup_kwargs)
    return plan, sup, topo


@pytest.fixture(autouse=True)
def _restore_default_topology():
    """Sharded routing resolves the process-default topology (that is
    what a node installs at start); don't leak one into the suite."""
    before = topology.default_topology()
    yield
    topology.set_default_topology(before)


# a trivially-cheap elementwise kernel: exercises the full sharded
# dispatch/AOT machinery without the minutes-long curve-kernel compile
@jax.jit
def _mod3_kernel(x):
    return (x % 3).astype(jnp.int32) != 1


def _mod3_truth(xs):
    return (np.asarray(xs) % 3) != 1


class TestShardBucket:
    def test_per_shard_bucket_is_minimal_pow2(self):
        for n in (1, 7, 63, 64, 65, 771, 999, 4097, 10000):
            for nsh in (2, 3, 7, 8):
                total = mesh.shard_bucket(n, nsh, 64)
                per = total // nsh
                assert total % nsh == 0
                assert per & (per - 1) == 0, f"per-shard {per} not pow2"
                assert per >= 64
                assert total >= n
                # minimal: halving the per-shard bucket would not fit
                assert per == 64 or (per // 2) * nsh < n

    def test_warm_plan_and_dispatch_arithmetic_lockstep(self):
        # the zero-compiles-after-warm guarantee: for every ladder
        # bucket, the shape dispatch_sharded produces for a chunk of
        # that many real lanes is one of the totals warmup_plan warms
        ndev = mesh.n_devices()
        assert ndev == 8  # conftest forces the 8-way virtual plane
        for bucket in aot.bucket_ladder(floor=64):
            warmed = {-(-bucket // ndev) * ndev,
                      mesh.shard_bucket(bucket, ndev, 64)}
            assert mesh.shard_bucket(bucket, ndev, 64) in warmed


class TestShardPlan:
    def test_plan_caches_per_generation(self):
        topo = topology.DeviceTopology.virtual(8)
        p1 = mesh.shard_plan(topo)
        assert p1 is not None and p1.n_shards == 8
        assert mesh.shard_plan(topo) is p1  # same generation: cached

    def test_quarantine_bumps_generation_and_reslices(self):
        topo = topology.DeviceTopology.virtual(8)
        p1 = mesh.shard_plan(topo)
        gen = topo.generation()
        assert topo.set_quarantined(5)  # changed -> True
        assert not topo.set_quarantined(5)  # idempotent -> no change
        assert topo.generation() == gen + 1
        p2 = mesh.shard_plan(topo)
        assert p2 is not p1
        assert p2.n_shards == 7
        assert "dev5" not in p2.labels()
        topo.set_quarantined(5, False)
        assert mesh.shard_plan(topo).n_shards == 8

    def test_healthy_devices_stable_index_order(self):
        topo = topology.DeviceTopology.virtual(8)
        topo.set_quarantined(2)
        topo.set_quarantined(6)
        labels = [h.label for h in topo.healthy_devices()]
        assert labels == ["dev0", "dev1", "dev3", "dev4", "dev5", "dev7"]
        assert labels == [h.label for h in topo.healthy_devices()]

    def test_unavailable_below_two_healthy(self):
        topo = topology.DeviceTopology.virtual(8)
        for i in range(7):
            topo.set_quarantined(i)
        assert mesh.shard_plan(topo) is None
        assert not mesh.sharded_available(topo)
        topo.set_quarantined(0, False)
        assert mesh.sharded_available(topo)


class TestDispatchShardedParity:
    def test_non_pow2_parity_across_shard_boundaries(self):
        # 999 real lanes over 8 shards: 7 full pow2 shards + a ragged
        # tail shard; every boundary must land in the right lane
        topo = topology.DeviceTopology.virtual(8)
        xs = np.arange(999, dtype=np.int32)
        out = mesh.dispatch_sharded(
            _mod3_kernel, [xs], 999, max_chunk=8192, min_pad=64,
            topology=topo,
        )
        assert np.array_equal(out, _mod3_truth(xs))

    def test_multi_chunk_megabatch_parity(self):
        # cap the per-shard lanes so the megabatch spans several
        # sharded chunks (exercises the double-buffered retire loop)
        topo = topology.DeviceTopology.virtual(8)
        xs = np.arange(3000, dtype=np.int32)
        out = mesh.dispatch_sharded(
            _mod3_kernel, [xs], 3000, max_chunk=128, min_pad=64,
            topology=topo,
        )
        assert np.array_equal(out, _mod3_truth(xs))

    def test_one_domain_quarantined_reslice_parity(self):
        topo = topology.DeviceTopology.virtual(8)
        topo.set_quarantined(3)
        plan = mesh.shard_plan(topo)
        assert plan is not None and plan.n_shards == 7
        xs = np.arange(771, dtype=np.int32)
        out = mesh.dispatch_sharded(
            _mod3_kernel, [xs], 771, max_chunk=8192, min_pad=64,
            topology=topo,
        )
        assert np.array_equal(out, _mod3_truth(xs))

    def test_cancel_honored_mid_dispatch(self):
        # the cancel event trips DURING the flow (while packing chunk 1,
        # after chunk 0 already dispatched); the chunk-boundary check
        # before chunk 2 must abandon the rest of the megabatch
        topo = topology.DeviceTopology.virtual(8)
        ev = threading.Event()
        xs = np.arange(1500, dtype=np.int32)
        packs = []

        def packed(start, end):
            packs.append((start, end))
            if start > 0:
                ev.set()
            return [xs[start:end]]

        with mesh.cancel_scope(ev):
            with pytest.raises(mesh.DispatchCancelled):
                mesh.dispatch_sharded(
                    _mod3_kernel, packed, 1500, max_chunk=64, min_pad=64,
                    topology=topo,
                )
        # mega-chunk = 64 lanes/shard * 8 shards = 512: chunks 0 and 1
        # packed, the cancel fired before chunk 2 was ever packed
        assert packs == [(0, 512), (512, 1024)]


class TestWarmBootZeroMiss:
    def test_sharded_dispatch_after_warm_has_zero_registry_misses(self):
        name = "test-sharded-zero-miss"
        aot.register_kernel(
            name, _mod3_kernel,
            bucket_shapes=lambda b: [((b,), np.int32)],
        )
        topo = topology.DeviceTopology.virtual(8)
        plan = mesh.shard_plan(topo)
        assert plan is not None and plan.n_shards == 8
        reg = aot.default_registry()
        # the warm-boot ladder stage for this kernel at bucket 512
        targets = [t for t in aot.warmup_plan(sizes=[512])
                   if t.name == name]
        assert any(t.sharded for t in targets)
        for t in targets:
            reg.warm(t.kernel, t.shapes, donate_from=t.donate_from,
                     sharded=t.sharded)
        misses_before = reg.stats()["misses"]
        # 500 real lanes -> pow2 per-shard bucket 64 -> global 512:
        # exactly the warmed executable; the dispatch must not compile
        xs = np.arange(500, dtype=np.int32)
        out = mesh.dispatch_sharded(
            name and _mod3_kernel, [xs], 500, max_chunk=512, min_pad=64,
            topology=topo,
        )
        assert np.array_equal(out, _mod3_truth(xs))
        assert reg.stats()["misses"] == misses_before, (
            "post-warm sharded dispatch took an AOT registry miss"
        )


class TestThreeWayRouting:
    def test_shard_min_batch_precedence(self, monkeypatch):
        # env > config > calibration > built-in default
        monkeypatch.setenv("CBFT_SHARD_MIN_BATCH", "777")
        assert shard_min_batch_default(5000) == 777
        monkeypatch.delenv("CBFT_SHARD_MIN_BATCH")
        assert shard_min_batch_default(1234) == 1234
        from cometbft_tpu.crypto.tpu import calibrate
        monkeypatch.setattr(calibrate, "shard_min_batch", lambda: 2222)
        assert shard_min_batch_default(0) == 2222
        monkeypatch.setattr(calibrate, "shard_min_batch", lambda: None)
        assert shard_min_batch_default(0) == DEFAULT_SHARD_MIN_BATCH
        assert shard_min_batch_default(None) == DEFAULT_SHARD_MIN_BATCH

    def test_route_override_env(self, monkeypatch):
        monkeypatch.delenv("CBFT_MESH_ROUTE", raising=False)
        assert mesh.route_override() is None
        monkeypatch.setenv("CBFT_MESH_ROUTE", "single")
        assert mesh.route_override() == mesh.ROUTE_SINGLE
        monkeypatch.setenv("CBFT_MESH_ROUTE", "sharded")
        assert mesh.route_override() == mesh.ROUTE_SHARDED
        monkeypatch.setenv("CBFT_MESH_ROUTE", "auto")
        assert mesh.route_override() is None
        monkeypatch.setenv("CBFT_MESH_ROUTE", "bogus")
        with pytest.raises(ValueError):
            mesh.route_override()

    def test_scheduler_routes_flush_three_ways(self, monkeypatch):
        monkeypatch.delenv("CBFT_MESH_ROUTE", raising=False)
        monkeypatch.delenv("CBFT_SHARD_MIN_BATCH", raising=False)
        _, sup, topo = _faulty_sharded(8)
        sched = VerifyScheduler(spec=BackendSpec(sup.spec.name),
                                supervisor=sup, shard_min_batch=100)
        try:
            assert sched.shard_min_batch == 100
            # below the crossover -> single-chip; at/above -> sharded
            assert sched._route_for(99) is None
            assert sched._route_for(100) == mesh.ROUTE_SHARDED
            # explicit override beats the size rule, both ways
            monkeypatch.setenv("CBFT_MESH_ROUTE", "single")
            assert sched._route_for(10_000) == mesh.ROUTE_SINGLE
            monkeypatch.setenv("CBFT_MESH_ROUTE", "sharded")
            assert sched._route_for(1) == mesh.ROUTE_SHARDED
            # malformed override: route on size, never raise
            monkeypatch.setenv("CBFT_MESH_ROUTE", "bogus")
            assert sched._route_for(10_000) == mesh.ROUTE_SHARDED
            monkeypatch.delenv("CBFT_MESH_ROUTE")
            # mesh gone (all but one domain quarantined) -> single
            for i in range(1, 8):
                topo.set_quarantined(i)
            assert sched._route_for(10_000) is None
        finally:
            sched.on_stop()
            sup.stop()

    def test_cpu_spec_never_routes_to_mesh(self):
        sched = VerifyScheduler(spec=BackendSpec("cpu"))
        try:
            assert sched._route_for(1_000_000) is None
            snap = sched.queue_snapshot()
            assert snap["routes"] == {
                "cpu": 0, "single": 0, "sharded": 0, "indexed": 0,
                "service": 0,
            }
        finally:
            sched.on_stop()

    def test_sharded_flush_counted_and_ground_truth(self, monkeypatch):
        monkeypatch.delenv("CBFT_MESH_ROUTE", raising=False)
        _, sup, topo = _faulty_sharded(8)
        sched = VerifyScheduler(spec=BackendSpec(sup.spec.name),
                                supervisor=sup, shard_min_batch=4)
        dispatched_before = sup.metrics.sharded_dispatches.value()
        try:
            items = _make_items(64, tag=b"route", poison_at=(7, 40))
            fut = sched.submit(items, subsystem="test", height=1)
            ok, mask = fut.result(timeout=60)
            assert mask == _cpu_mask(items)
            assert not ok
            assert sched.queue_snapshot()["routes"]["sharded"] == 1
            assert (sup.metrics.sharded_dispatches.value()
                    == dispatched_before + 1)
        finally:
            sched.on_stop()
            sup.stop()

    def test_route_falls_back_when_mesh_unavailable(self):
        # one healthy domain: a sharded request must still be served
        # (single-chip fallback), counted as a sharded_fallback
        _, sup, topo = _faulty_sharded(2)
        topo.set_quarantined(1)
        fallbacks_before = sup.metrics.sharded_fallbacks.value()
        try:
            items = _make_items(32, tag=b"fb", poison_at=(5,))
            mask = sup.verify_items(items, reason="test", route="sharded")
            assert mask == _cpu_mask(items)
            assert (sup.metrics.sharded_fallbacks.value()
                    == fallbacks_before + 1)
        finally:
            sup.stop()


class TestSupervisedShardedParity:
    def test_megabatch_ground_truth_with_invalids_attributed(self):
        # the real curve kernel over the full supervised sharded path:
        # non-pow2 n with invalid signatures planted mid-shard and at a
        # shard boundary; verdicts must match the CPU backend exactly
        topo = topology.DeviceTopology.virtual(8)
        topology.set_default_topology(topo)
        sup = BackendSupervisor(
            spec=BackendSpec("tpu"), topology=topo,
            dispatch_timeout_ms=600_000, hedge_pct=0, audit_pct=0,
            probe_base_ms=600_000,
        )
        dispatched_before = sup.metrics.sharded_dispatches.value()
        try:
            items = _make_items(771, tag=b"mega", poison_at=(3, 97, 500))
            mask = sup.verify_items(items, reason="test", route="sharded")
            truth = _cpu_mask(items)
            assert mask == truth
            assert [i for i, v in enumerate(mask) if not v] == [3, 97, 500]
            assert (sup.metrics.sharded_dispatches.value()
                    == dispatched_before + 1)
        finally:
            sup.stop()


class TestChaosSharded:
    def test_chaos_sharded_acceptance(self):
        # the full degradation story: kill one domain mid-sharded-flow,
        # failure attributed to it, plan re-sliced to N-1, verdicts
        # stay ground-truth, throughput >= 0.6 x (N-1)/N of full mesh,
        # canary re-admits and the plan re-slices back to N.
        # run_chaos_sharded asserts every invariant inline.
        summary = run_chaos_sharded(
            devices=8, kill=3, seed=7, inner="cpu", rounds=2,
        )
        assert summary["wrong_verdicts"] == 0
        assert summary["cpu_routed"] == 0
        assert set(summary["quarantines"]) == {"dev3"}
        assert summary["topology_mirrored_quarantine"]
        assert summary["resliced_shards"] == 7
        assert summary["restored_shards"] == 8
        assert summary["throughput_ok"]
