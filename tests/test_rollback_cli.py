"""State rollback + maintenance CLI commands (rollback, reset,
gen-node-key, reindex-event).

Model: reference state/rollback_test.go and cmd/cometbft/commands/
{rollback,reset,reindex_event}.go.
"""

import base64
import json
import os
import tempfile
import time
import urllib.request

import pytest

from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.cmd.commands import _load_config, main as cli_main
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.rollback import rollback
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import BlockID, Commit
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.libs.net import free_ports as _free_ports

GENESIS_TIME = Timestamp(1_700_000_000, 0)


def _build_chain(n_blocks):
    vals, privs = test_util.deterministic_validator_set(3, 10)
    doc = GenesisDoc(
        genesis_time=GENESIS_TIME,
        chain_id="rollback-chain",
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    state = make_genesis_state(doc)
    ss = Store(MemDB())
    ss.save(state)
    bs = BlockStore(MemDB())
    client = LocalClient(KVStoreApplication())
    client.start()
    ex = BlockExecutor(ss, AppConnConsensus(client))
    last_commit = Commit(height=0, round=0)
    for h in range(1, n_blocks + 1):
        proposer = state.validators.validators[h % 3].address
        block, parts = state.make_block(h, [], last_commit, [], proposer)
        bid = BlockID(block.hash(), parts.header())
        seen = test_util.make_commit(
            bid, h, 0, state.validators, privs, doc.chain_id,
            now=Timestamp(GENESIS_TIME.seconds + h, 0),
        )
        bs.save_block(block, parts, seen)
        state, _ = ex.apply_block(state, bid, block)
        last_commit = seen
    client.stop()
    return state, ss, bs


class TestRollback:
    def test_rolls_back_one_height(self):
        state, ss, bs = _build_chain(8)
        assert state.last_block_height == 8
        height, app_hash = rollback(bs, ss)
        assert height == 7
        rolled = ss.load()
        assert rolled.last_block_height == 7
        # app hash for height 7 comes from header 8
        assert app_hash == bs.load_block_meta(8).header.app_hash
        # validator bookkeeping shifted one height back
        assert rolled.validators.hash() == state.last_validators.hash()

    def test_early_return_when_block_store_is_ahead(self):
        """Non-atomic stop: block N+1 saved but state still at N — nothing
        to roll back (rollback.go:26-31)."""
        state, ss, bs = _build_chain(5)
        older = Store(MemDB())
        # simulate the state store lagging one height behind the blockstore
        s4 = state.copy()
        s4.last_block_height = 4
        older.save(s4)
        height, _ = rollback(bs, older)
        assert height == 4
        assert older.load().last_block_height == 4

    def test_errors_without_state(self):
        with pytest.raises(ValueError):
            rollback(BlockStore(MemDB()), Store(MemDB()))


def _rpc_post(port, method, params):
    body = json.dumps(
        {"jsonrpc": "2.0", "id": 1, "method": method, "params": params}
    ).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body,
        headers={"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=10).read())


@pytest.mark.slow
class TestMaintenanceCLI:
    def test_gen_node_key_and_resets(self, capsys):
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "cli-chain"])
            rc = cli_main(["--home", d, "gen-node-key"])
            assert rc == 0
            out = capsys.readouterr().out.strip().splitlines()[-1]
            node_id = out.split()[0]
            assert len(node_id) == 40  # hex address

            # drop a file into data/ then reset-state clears it
            with open(os.path.join(d, "data", "junk.db"), "w") as f:
                f.write("x")
            assert cli_main(["--home", d, "reset-state"]) == 0
            # only the freshly-reset signer state survives in data/
            assert os.listdir(os.path.join(d, "data")) == [
                "priv_validator_state.json"
            ]
            # keys survive the reset
            assert cli_main(["--home", d, "show-node-id"]) == 0
            assert capsys.readouterr().out.strip().splitlines()[-1] == node_id

            assert cli_main(["--home", d, "unsafe-reset-all"]) == 0

    def test_reindex_and_rollback_on_real_home(self):
        """Run a node to commit real blocks + a tx, then reindex-event into
        fresh index DBs and rollback the state by one height."""
        from cometbft_tpu.node import default_new_node

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "maint-chain"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.base.db_backend = "sqlite"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            node = default_new_node(cfg)
            node.start()
            try:
                deadline = time.monotonic() + 60
                committed = None
                while time.monotonic() < deadline and committed is None:
                    try:
                        committed = _rpc_post(
                            rpc_port, "broadcast_tx_commit",
                            {"tx": base64.b64encode(b"ri=1").decode()},
                        )["result"]
                    except Exception:
                        time.sleep(0.3)
                assert committed is not None
                tx_height = int(committed["height"])
                # let a couple more blocks commit so rollback has room
                time.sleep(2.0)
            finally:
                node.stop()
            time.sleep(0.5)

            # wipe the index DBs, then rebuild them from stored blocks
            data = os.path.join(d, "data")
            for name in ("tx_index.db", "block_index.db"):
                # sqlite sidecar files must go with the main db or a fresh
                # open sees a stale WAL and errors
                for suffix in ("", "-wal", "-shm"):
                    path = os.path.join(data, name + suffix)
                    if os.path.exists(path):
                        os.remove(path)
            assert cli_main(["--home", d, "reindex-event"]) == 0
            from cometbft_tpu.libs.db import SQLiteDB
            from cometbft_tpu.libs.pubsub.query import parse_query
            from cometbft_tpu.state.indexer import KVTxIndexer

            idx = KVTxIndexer(SQLiteDB(os.path.join(data, "tx_index.db")))
            found = idx.search(parse_query(f"tx.height={tx_height}"))
            assert len(found) == 1 and found[0].tx == b"ri=1"

            # replay into a FRESH app: the chain re-executes end to end
            # and reports the final heights (commands/replay.go analog)
            assert cli_main([
                "--home", d, "replay", "--fresh-app",
                "--proxy_app", "kvstore",
            ]) == 0

            # rollback: state height drops by one
            from cometbft_tpu.state.store import Store as StateStore

            before = StateStore(
                SQLiteDB(os.path.join(data, "state.db"))
            ).load().last_block_height
            assert cli_main(["--home", d, "rollback"]) == 0
            after = StateStore(
                SQLiteDB(os.path.join(data, "state.db"))
            ).load().last_block_height
            assert after == before - 1
