"""verifyd daemon ops surface (PR 19): the programmatic Daemon builder,
its /metrics + /debug/verify + /debug/traces endpoints (per-tenant
service panel, incident timeline), and the event-triggered incident
dump embedding the service view. Runs the real HTTP server on a free
port and a real client over a Unix socket."""

import json
import os
import sys
import time
import urllib.request

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import service as svc

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)

import verifyd  # noqa: E402


def _batch(n, tag=b"vd", bad=()):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    items = []
    for i, k in enumerate(keys):
        msg = tag + b" msg %d" % i
        sig = k.sign(msg)
        if i in bad:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 0x01])
        items.append((k.pub_key(), msg, sig))
    return items


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as resp:
        return resp.read().decode("utf-8")


def _wait(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture
def daemon(tmp_path):
    path = "/tmp/cbft-test-verifyd-%d.sock" % os.getpid()
    d = verifyd.Daemon(
        "unix://" + path,
        backend="cpu",
        flush_us=200,
        metrics_addr="127.0.0.1:0",
        trace_sample=1.0,
        dump_dir=str(tmp_path),
    )
    d.start()
    clients = []

    def client(tenant):
        c = svc.RemoteVerifier(
            d.service.address(), tenant=tenant, timeout_ms=15_000,
            retry_s=0.05,
        )
        clients.append(c)
        return c

    d.test_client = client
    yield d
    for c in clients:
        c.close()
    d.stop()
    try:
        os.unlink(path)
    except OSError:
        pass


class TestDaemonOpsSurface:
    def test_metrics_and_debug_verify_serve_the_service_panel(
        self, daemon
    ):
        c = daemon.test_client("panel-t")
        items = _batch(5, bad=(1,))
        ok, mask = c.submit(items, subsystem="consensus").result(timeout=30)
        assert not ok and mask.count(False) == 1

        assert daemon.metrics_port is not None
        text = _get(daemon.metrics_port, "/metrics")
        assert "verify_service_frames" in text
        assert "verify_service_lanes" in text
        assert "verify_service_bytes_per_lane" in text

        doc = json.loads(_get(daemon.metrics_port, "/debug/verify"))
        panel = doc["sources"]["service"]["tenants_panel"]
        assert "panel-t" in panel
        row = panel["panel-t"]
        assert row["requests"] >= 1 and row["responses"] >= 1
        assert row["mean_ms"] > 0.0
        assert row["refusals"] == {}
        assert doc["sources"]["service"]["protocol_version"] == svc.VERSION
        assert "timeline" in doc

    def test_debug_traces_capture_adopted_requests(self, daemon):
        c = daemon.test_client("traced-t")
        c.submit(_batch(3)).result(timeout=30)
        assert _wait(lambda: json.loads(
            _get(daemon.metrics_port, "/debug/traces")
        ).get("traces"))
        doc = json.loads(_get(daemon.metrics_port, "/debug/traces"))
        names = {
            s["name"] for tr in doc["traces"] for s in tr.get("spans", ())
        }
        assert "request" in names

    def test_midflight_disconnect_lands_on_the_timeline(self, tmp_path):
        """Kill a client with a request provably in flight (the device
        pool is gated shut): the server's teardown must put a
        ``disconnect`` event on the hub timeline and /debug/verify must
        surface it."""
        import threading

        gate = threading.Event()
        inner = svc.host_row_verifier()

        def verifier(rows):
            gate.wait(20)
            return inner(rows)

        path = "/tmp/cbft-test-verifyd-gate-%d.sock" % os.getpid()
        d = verifyd.Daemon(
            "unix://" + path, backend="cpu", flush_us=200,
            metrics_addr="127.0.0.1:0", dump_dir=str(tmp_path),
            row_verifier=verifier,
        )
        d.start()
        c = svc.RemoteVerifier(
            d.service.address(), tenant="churn-t", timeout_ms=15_000,
            retry_s=0.05,
        )
        try:
            fut = c.submit(_batch(3, tag=b"gate"))
            assert _wait(lambda: d.service.pending_requests() > 0)
            c.kill_connection()
            assert _wait(lambda: any(
                ev.get("kind") == "disconnect"
                and ev.get("tenant") == "churn-t"
                for ev in d.hub.timeline()
            ))
            gate.set()
            ok, _mask = fut.result(timeout=30)  # local CPU fallback
            assert ok
            doc = json.loads(_get(d.metrics_port, "/debug/verify"))
            kinds = {ev.get("kind") for ev in doc["timeline"]}
            assert "disconnect" in kinds
        finally:
            gate.set()
            c.close()
            d.stop()
            try:
                os.unlink(path)
            except OSError:
                pass

    def test_incident_event_dumps_with_the_service_view(self, daemon):
        c = daemon.test_client("incident-t")
        c.submit(_batch(4)).result(timeout=30)
        daemon.hub.note_event("brownout_trip", {"qclass": "mempool"})
        assert _wait(lambda: daemon.last_dump is not None, timeout=10)
        with open(daemon.last_dump, "r", encoding="utf-8") as f:
            doc = json.load(f)
        assert doc["reason"] == "brownout_trip"
        assert doc["event"]["qclass"] == "mempool"
        assert "incident-t" in doc["service"]["tenants_panel"]
        assert any(
            ev.get("kind") == "brownout_trip" for ev in doc["timeline"]
        )

    def test_non_incident_events_do_not_dump(self, daemon):
        daemon.hub.note_event("valset_registered", {"tenant": "x"})
        time.sleep(0.1)
        assert daemon.last_dump is None

    def test_stop_is_clean_and_idempotent_endpoints_die(self, daemon):
        port = daemon.metrics_port
        assert _get(port, "/metrics")


class TestDaemonCli:
    def test_bad_address_is_a_usage_error(self, capsys):
        assert verifyd.main(["--address", "ftp://nope"]) == 2
        assert "error" in capsys.readouterr().err
