"""Daemon kill/restart recovery (PR 18 satellite): a verifyd crash
mid-storm must cost at most the in-flight requests — resolved locally
with the distinct ``disconnected`` reason and ground-truth verdicts —
and the client must walk disconnected -> local fallback -> reconnect ->
re-register -> indexed resume against the restarted daemon, all by
itself. Runs over a real Unix socket on the virtual CPU mesh
(conftest.py); the restarted daemon's keystore is cold (invalidate),
so the walk exercises the generation handshake too."""

import os
import threading
import time

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import service as svc
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.crypto.tpu import keystore


def _batch(n, tag=b"rst", bad=()):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    items = []
    for i, k in enumerate(keys):
        msg = tag + b" msg %d" % i
        sig = k.sign(msg)
        if i in bad:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 0x01])
        items.append((k.pub_key(), msg, sig))
    return items


def _expected(items):
    return [
        ed.PubKeyEd25519(svc._pk_bytes(pk)).verify_signature(m, s)
        for pk, m, s in items
    ]


class _Epoch:
    """One daemon lifetime: scheduler + service on a shared socket
    path, pool gated so requests are provably in flight at the kill."""

    def __init__(self, path, gate):
        inner = svc.host_row_verifier()

        def verifier(rows):
            gate.wait(20)
            return inner(rows)

        self.sched = VerifyScheduler(
            spec="cpu", flush_us=200, lane_budget=256, max_queue=256,
            qos="off", row_verifier=verifier,
        )
        self.service = svc.VerifyService(
            self.sched, "unix://" + path, coalesce=True,
            row_verifier=verifier,
        )
        self.sched.start()
        self.service.start()

    def stop(self):
        self.service.stop()
        self.sched.stop()


class TestDaemonRestartRecovery:
    def test_kill_restart_walks_reconnect_reregister_indexed(self):
        path = "/tmp/cbft-test-restart-%d.sock" % os.getpid()
        gate = threading.Event()
        gate.set()
        store = keystore.default_store()
        store.invalidate()
        epoch = _Epoch(path, gate)
        client = svc.RemoteVerifier(
            "unix://" + path, tenant="restart", timeout_ms=15_000,
            retry_s=0.05,
        )
        items = _batch(8, bad=(2,))
        pks = [svc._pk_bytes(pk) for pk, _, _ in items]
        want = _expected(items)
        try:
            # epoch 1: registered valset, indexed wire, remote verdicts
            client.register_valset(pks)
            ok, mask = client.submit(
                items, subsystem="consensus"
            ).result(timeout=30)
            assert not ok and mask == want
            s = client.stats()
            assert s.get("connects", 0) == 1
            assert s.get("registrations", 0) == 1
            remote_ok_e1 = s.get("remote_ok", 0)
            assert remote_ok_e1 >= 1
            assert epoch.service.snapshot()["lanes"].get("indexed", 0) == 8

            # freeze the pool, park a request, then kill the daemon
            # out from under it
            gate.clear()
            fut = client.submit(items, subsystem="consensus")
            deadline = time.monotonic() + 10
            while (epoch.service.pending_requests() < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert epoch.service.pending_requests() >= 1
            # sever the wire first (the crash), THEN release the dead
            # epoch's pool so its scheduler can drain and join fast
            epoch.service.stop()
            gate.set()
            epoch.sched.stop()

            # the in-flight request resolves LOCALLY with the distinct
            # reason and ground-truth verdicts — never an error, never
            # a wrong verdict
            ok, mask = fut.result(timeout=30)
            assert fut.reason == "disconnected"
            assert not ok and mask == want
            assert client.stats().get("disconnected", 0) >= 1

            # restart on the same socket with a COLD keystore: the
            # restarted daemon knows nothing about the client's valset
            store.invalidate()
            epoch = _Epoch(path, gate)
            time.sleep(0.2)  # let the client's retry backoff lapse

            # the client walks back unaided: reconnect -> re-register
            # (generation handshake against the cold store) -> indexed.
            # Any interim submit may resolve via the stale fallback —
            # with correct verdicts — but the walk must converge.
            last = None
            for _ in range(3):
                last = client.submit(items, subsystem="consensus")
                ok, mask = last.result(timeout=30)
                assert not ok and mask == want  # verdicts exact throughout
                if getattr(last, "reason", None) is None:
                    break
                assert last.reason in ("stale", "disconnected"), last.reason
            assert getattr(last, "reason", None) is None, (
                "client never resumed remote verification", client.stats()
            )
            s = client.stats()
            assert s.get("connects", 0) >= 2
            assert s.get("registrations", 0) >= 2
            assert s.get("remote_ok", 0) > remote_ok_e1
            assert s.get("resync_failed", 0) == 0
            # the resumed wire is indexed on the NEW daemon
            assert epoch.service.snapshot()["lanes"].get("indexed", 0) >= 8
        finally:
            gate.set()
            client.close()
            epoch.stop()
            store.invalidate()
            try:
                os.unlink(path)
            except OSError:
                pass
