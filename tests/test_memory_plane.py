"""Device-memory plane, incident profiler, and regression sentinel.

Covers PR 9's observability plane end to end on the virtual CPU mesh:

* the calibrated footprint model (static Straus seed, EWMA correction,
  calibration-table round trip);
* the pre-dispatch memory guard demoting the reactive OOM rung: under a
  CBFT_FAULT_OOM_RATE/CBFT_FAULT_OOM_ABOVE allocator-model injection the
  guard shrinks the chunk cap BEFORE dispatch, so zero
  RESOURCE_EXHAUSTED ever reaches the supervisor's breaker
  (crypto/faults.py run_chaos_memory_guard — the same proof
  tools/chaos.py --memory-guard runs);
* model-only degradation on stats-less backends;
* ProfilerCapture gating, retention, and the /debug/profile endpoint
  (the real jax.profiler capture is `slow`-marked);
* the tools/bench_history.py sentinel: self-test (synthetic 20%
  regression must flag, clean and single-blip ledgers must pass) and
  the --append stage-record writer bench.py uses.
"""

import json
import os
import subprocess
import sys

import pytest

from cometbft_tpu.crypto import faults as faultlib
from cometbft_tpu.crypto.tpu import calibrate as caliblib
from cometbft_tpu.crypto.tpu import memory as memlib
from cometbft_tpu.crypto.tpu import topology as topolib
from cometbft_tpu.libs import profiling as proflib
from cometbft_tpu.libs.metrics import MetricsServer, Registry

_TOOLS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
)


@pytest.fixture
def handle():
    """Fault-domain 0's device handle, guard/shrink state restored."""
    h = topolib.default_topology().device(0)
    h.reset_chunk_shrink()
    yield h
    h.reset_chunk_shrink()


class TestFootprintModel:
    def test_static_seed_matches_straus_estimate(self):
        plane = memlib.MemoryPlane(stats=False)
        # ~70 MB per 16384-lane Straus chunk (ed25519_batch.py)
        assert plane.bytes_per_lane("ed25519", 16384) == pytest.approx(
            memlib.SEED_BYTES_PER_LANE
        )
        assert memlib.SEED_BYTES_PER_LANE * 16384 == pytest.approx(
            70 * 1024 * 1024, rel=0.2
        )

    def test_projection_scales_with_bucket(self):
        plane = memlib.MemoryPlane(stats=False)
        small = plane.projected_bytes("ed25519", 1024)
        big = plane.projected_bytes("ed25519", 8192)
        assert big == pytest.approx(small * 8, rel=0.01)

    def test_ewma_correction_and_export(self):
        plane = memlib.MemoryPlane(stats=False)
        assert plane.export_footprints() == {}  # seed-only: nothing learned
        plane.observe_footprint("ed25519", 1024, 1024 * 9000)
        assert plane.bytes_per_lane("ed25519", 1024) == pytest.approx(9000.0)
        # EWMA folds the next observation toward the new peak
        plane.observe_footprint("ed25519", 1024, 1024 * 5000)
        bpl = plane.bytes_per_lane("ed25519", 1024)
        assert 5000.0 < bpl < 9000.0
        exported = plane.export_footprints()
        assert exported["ed25519"][1024] == pytest.approx(bpl)

    def test_nonpositive_observations_ignored(self):
        plane = memlib.MemoryPlane(stats=False)
        plane.observe_footprint("ed25519", 1024, 0)
        plane.observe_footprint("ed25519", 0, 4096)
        assert plane.export_footprints() == {}

    def test_calibration_round_trip(self, tmp_path, monkeypatch):
        monkeypatch.setenv(
            "CBFT_TPU_CALIBRATION", str(tmp_path / "calib.json")
        )
        plane = memlib.MemoryPlane(stats=False)
        plane.observe_footprint("ed25519", 2048, 2048 * 7777)
        assert caliblib.merge_memory_footprints(
            plane.export_footprints()
        ) is not None
        loaded = caliblib.load_memory_footprints()
        assert loaded["ed25519"][2048] == pytest.approx(7777.0, abs=0.1)
        # a fresh plane seeds its model from the persisted table
        warm = memlib.MemoryPlane(stats=False)
        assert warm.bytes_per_lane("ed25519", 2048) == pytest.approx(
            7777.0, abs=0.1
        )


class TestModelOnlyDegradation:
    def test_stats_less_backend_reports_model_mode(self, handle):
        plane = memlib.MemoryPlane(
            stats=False, model_limit_bytes=1 << 30, headroom_fraction=0.5
        )
        doc = plane.device_view(handle)
        assert doc["mode"] == "model"
        assert doc["bytes_in_use"] == 0
        assert plane.free_headroom_bytes(handle) == (1 << 30) // 2

    def test_env_limit_drives_model(self, monkeypatch):
        monkeypatch.setenv("CBFT_MEM_LIMIT_BYTES", str(1 << 20))
        assert memlib.model_limit_bytes_default() == 1 << 20

    def test_snapshot_shape(self, handle):
        plane = memlib.MemoryPlane(stats=False)
        snap = plane.snapshot()
        assert snap["seed_bytes_per_lane"] > 0
        doc = snap["devices"][handle.label]
        assert {"mode", "bytes_in_use", "headroom_bytes", "guard_cap"} \
            <= set(doc)


class TestPreDispatchGuard:
    def test_guard_shrinks_cap_to_fit_headroom(self, handle):
        # headroom fits ~256 lanes × pipeline depth: the guard must
        # halve 8192 down until the projection fits, and clamp the
        # handle so every cap consumer (mesh dispatch) sees it
        from cometbft_tpu.crypto.tpu import mesh

        try:
            depth = mesh.pipeline_depth()
        except ValueError:
            depth = 2
        limit = int(memlib.SEED_BYTES_PER_LANE * 256 * depth / 0.9) + 1
        plane = memlib.MemoryPlane(
            stats=False, model_limit_bytes=limit, poll_ms=0
        )
        cap = plane.refresh_guard(handle, 8192, 64)
        assert cap <= 256
        assert handle.memory_guard_cap() == cap
        assert handle.chunk_cap(8192, 64) == cap
        # labeled counters accumulate in with_labels() children — sum
        # the series for the total
        shrinks = sum(
            c.value() for c in plane.metrics.guard_shrinks._series()
        )
        assert shrinks >= 5  # 8192 -> 256 is five halvings

    def test_guard_releases_when_headroom_returns(self, handle):
        plane = memlib.MemoryPlane(
            stats=False, model_limit_bytes=1 << 40, poll_ms=0
        )
        cap = plane.refresh_guard(handle, 8192, 64)
        assert cap == handle.chunk_cap(8192, 64)
        assert handle.memory_guard_cap() is None

    def test_guard_floors_at_min_pad(self, handle):
        plane = memlib.MemoryPlane(
            stats=False, model_limit_bytes=1, poll_ms=0
        )
        # nothing fits: the guard floors at min_pad and the reactive
        # rung stays the backstop instead of wedging dispatch at 0
        assert plane.refresh_guard(handle, 8192, 64) == 64


class TestGuardPreemptsInjectedOom:
    def test_chaos_memory_guard(self):
        """The PR's headline invariant, via the same harness
        tools/chaos.py --memory-guard runs: with the allocator-model
        OOM injection armed (oom_rate=1.0, oom_above_lanes=256), the
        reactive rung pays one real RESOURCE_EXHAUSTED per halving,
        then the guard-on phase dispatches the identical workload with
        ZERO OOMs fired and zero reactive shrinks."""
        summary = faultlib.run_chaos_memory_guard(seed=11, inner="cpu")
        assert summary["wrong_verdicts"] == 0
        assert summary["reactive_ooms"] > 0
        assert summary["reactive_shrinks"] > 0
        assert summary["guard_cap"] <= 256
        assert summary["guarded_ooms"] == 0
        assert summary["guarded_shrinks"] == 0
        assert summary["guard_shrink_events"] > 0
        assert summary["state_final"] == "healthy"

    def test_fault_plan_env_round_trip(self, monkeypatch):
        monkeypatch.setenv("CBFT_FAULT_OOM_RATE", "1.0")
        monkeypatch.setenv("CBFT_FAULT_OOM_ABOVE", "128")
        plan = faultlib.FaultPlan.from_env()
        assert plan.oom_rate == 1.0
        assert plan.oom_above_lanes == 128

    def test_allocator_model_respects_guarded_cap(self, handle):
        """An injected OOM (rate 1.0) must NOT fire once the guard has
        clamped the cap to the allocator threshold — the workload fits
        in modeled HBM, so the fault's own model agrees it fits."""
        from cometbft_tpu.crypto import batch as cryptobatch
        import cometbft_tpu.crypto.ed25519 as ed

        plan = faultlib.FaultPlan(
            seed=3, oom_rate=1.0, oom_above_lanes=256
        )
        key = ed.gen_priv_key_from_secret(b"memory-guard-test")
        pk = key.pub_key()
        msg = b"guarded dispatch"
        sig = key.sign(msg)

        def dispatch():
            bv = faultlib.FaultyBackend(
                plan, cryptobatch.new_batch_verifier("cpu")
            )
            bv.add(pk, msg, sig)
            return bv.verify()

        with pytest.raises(Exception):
            dispatch()  # unguarded cap 8192 > 256: the fault fires
        assert plan.ooms_fired == 1
        handle.set_memory_guard_cap(256)
        ok, mask = dispatch()  # fits in modeled HBM: never fires
        assert ok and mask == [True]
        assert plan.ooms_fired == 1


class TestProfilerCapture:
    def test_unavailable_without_profile_dir(self):
        prof = proflib.ProfilerCapture(profile_dir=None)
        assert not prof.available()
        assert prof.capture(duration_ms=10) is None

    def test_burn_gating(self, tmp_path):
        prof = proflib.ProfilerCapture(
            profile_dir=str(tmp_path), on_burn_threshold=0.0
        )
        assert not prof.on_burn(99.0)  # threshold 0 = disabled
        armed = proflib.ProfilerCapture(
            profile_dir=str(tmp_path), on_burn_threshold=2.0
        )
        assert not armed.on_burn(1.5)  # below threshold

    def test_endpoint_503_when_unavailable(self):
        import urllib.error
        import urllib.request

        srv = MetricsServer(
            Registry("cometbft"),
            profiler=proflib.ProfilerCapture(profile_dir=None),
        )
        port = srv.serve("127.0.0.1", 0)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile", timeout=5
                )
            assert exc_info.value.code == 503
        finally:
            srv.stop()

    @pytest.mark.slow
    def test_capture_e2e_and_retention(self, tmp_path):
        """A real bounded jax.profiler capture: the dir must contain a
        loadable trace (an .xplane.pb under plugins/profile is what the
        JAX toolchain's trace viewer opens), and keep-N retention must
        prune the oldest captures."""
        prof = proflib.ProfilerCapture(profile_dir=str(tmp_path), keep=2)
        assert prof.available()
        paths = [
            prof.capture(duration_ms=50, reason=f"test{i}")
            for i in range(3)
        ]
        assert all(p is not None for p in paths)
        files = []
        for root, _dirs, names in os.walk(paths[-1]):
            files.extend(os.path.join(root, n) for n in names)
        assert files, "capture produced no trace files"
        assert any(f.endswith(".xplane.pb") for f in files)
        kept = [
            d for d in os.listdir(tmp_path) if d.startswith("profile_")
        ]
        assert len(kept) == 2  # keep-N pruned the oldest
        last = prof.last_capture()
        assert last is not None and last["path"] == paths[-1]

    @pytest.mark.slow
    def test_endpoint_runs_capture(self, tmp_path):
        import urllib.request

        srv = MetricsServer(
            Registry("cometbft"),
            profiler=proflib.ProfilerCapture(profile_dir=str(tmp_path)),
        )
        port = srv.serve("127.0.0.1", 0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/profile?ms=50", timeout=30
            ).read()
            doc = json.loads(body)
            assert os.path.isdir(doc["path"])
        finally:
            srv.stop()


class TestBenchHistorySentinel:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(_TOOLS, "bench_history.py"),
             *args],
            capture_output=True, text=True, timeout=60,
        )

    def test_self_test_passes(self):
        """Satellite 6's fast tier-1 check: the synthetic ledger with an
        injected 20% regression must flag (and the clean/blip ledgers
        must pass) inside the tool's own --self-test."""
        res = self._run("--self-test")
        assert res.returncode == 0, res.stdout + res.stderr
        assert "SELF-TEST PASS" in res.stdout

    def test_real_ledger_check_passes(self):
        res = self._run("--check")
        assert res.returncode == 0, res.stdout + res.stderr

    def test_append_wraps_stage_records(self, tmp_path):
        ledger = tmp_path / "hist.jsonl"
        rec = tmp_path / "stage.json"
        rec.write_text(json.dumps({"first_verdict_ms": 120.0}))
        res = self._run(
            "--append", str(rec), "--stage", "coldboot",
            "--ledger", str(ledger),
        )
        assert res.returncode == 0, res.stdout + res.stderr
        lines = ledger.read_text().splitlines()
        assert len(lines) == 1
        row = json.loads(lines[0])
        assert row["metric"] == "bench_stage_coldboot"
        assert row["stages"]["coldboot"]["first_verdict_ms"] == 120.0

    def test_synthetic_sustained_regression_flagged(self, tmp_path):
        ledger = tmp_path / "hist.jsonl"
        rows = [
            {"metric": "m", "unit": "sigs/sec", "value": 1000.0 + i}
            for i in range(5)
        ] + [
            {"metric": "m", "unit": "sigs/sec", "value": 800.0},
            {"metric": "m", "unit": "sigs/sec", "value": 799.0},
        ]
        ledger.write_text(
            "".join(json.dumps(r) + "\n" for r in rows)
        )
        res = self._run("--check", "--ledger", str(ledger))
        assert res.returncode == 1
        assert '"path": "value"' in res.stdout
