"""Compact uint8 wire — bit-identical to the legacy u32 word wire.

The compact format (PR 13) ships raw 32-byte little-endian encodings as
uint8 rows and reconstructs u32 words on device (bytes_to_words) before
the shared limb-unpack / sign-extract / digit-window prologue. These
tests pin the property the whole device-resident hot path rests on: for
any batch — across chunk boundaries, non-canonical s, all-zero and
all-ones rows — the on-device decompress produces bit-identical words
and verdicts vs the host prepare_batch word wire. Runs on the virtual
CPU mesh (conftest.py).
"""

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import ed25519_batch as eb

# group order L: the canonical-s boundary
_L = 2**252 + 27742317777372353535851937790883648493


def _batch(n, tag=b"wf", corrupt_every=0):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    msgs = [b"wire format msg %d" % i for i in range(n)]
    sigs = [k.sign(m) for k, m in zip(keys, msgs)]
    if corrupt_every:
        for i in range(0, n, corrupt_every):
            b = bytearray(sigs[i])
            b[7] ^= 1
            sigs[i] = bytes(b)
    return [k.pub_key().bytes() for k in keys], msgs, sigs


def _cpu(pks, msgs, sigs):
    return [
        ed.PubKeyEd25519(p).verify_signature(m, s)
        for p, m, s in zip(pks, msgs, sigs)
    ]


def _words_from_compact(wire_c):
    import jax.numpy as jnp

    return np.asarray(eb.bytes_to_words(jnp.asarray(wire_c)))


def _kernel_verdicts(pks, msgs, sigs):
    """(word-kernel mask, compact-kernel mask, shared valid) for one
    un-chunked dispatch of both formats on identical inputs."""
    import jax.numpy as jnp

    wire_w, valid_w = eb.prepare_batch(pks, msgs, sigs)
    wire_c, valid_c = eb.prepare_batch_compact(pks, msgs, sigs)
    np.testing.assert_array_equal(valid_w, valid_c)
    got_w = np.asarray(eb.verify_kernel(jnp.asarray(wire_w)))
    got_c = np.asarray(eb.verify_kernel_compact(jnp.asarray(wire_c)))
    return got_w, got_c, valid_w


class TestWordReconstruction:
    """bytes_to_words(compact rows) must equal the host word pack —
    the limb planes downstream are then identical by construction."""

    def test_bit_identical_words(self):
        pks, msgs, sigs = _batch(17, corrupt_every=5)
        wire_w, valid_w = eb.prepare_batch(pks, msgs, sigs)
        wire_c, valid_c = eb.prepare_batch_compact(pks, msgs, sigs)
        assert wire_c.dtype == np.uint8
        assert wire_c.shape == (128, 17)
        assert wire_w.shape == (32, 17)
        np.testing.assert_array_equal(_words_from_compact(wire_c), wire_w)
        np.testing.assert_array_equal(valid_c, valid_w)

    def test_row_layout(self):
        # rows 0:32 A, 32:64 R, 64:96 S — raw bytes, lane-minor
        pks, msgs, sigs = _batch(3)
        wire_c, _ = eb.prepare_batch_compact(pks, msgs, sigs)
        for lane in range(3):
            assert wire_c[0:32, lane].tobytes() == pks[lane]
            assert wire_c[32:64, lane].tobytes() == sigs[lane][:32]
            assert wire_c[64:96, lane].tobytes() == sigs[lane][32:]

    def test_device_hash_wire_shares_point_rows(self):
        # the 96-row device-hash wire is the host-hash wire minus h
        pks, msgs, sigs = _batch(5)
        full, _ = eb.prepare_batch_compact(pks, msgs, sigs)
        wire, msg, mlen, valid = eb.prepare_batch_device_hash_compact(
            pks, msgs, sigs
        )
        assert wire.shape == (96, 5)
        np.testing.assert_array_equal(wire, full[:96])
        assert np.all(valid)
        assert list(mlen) == [len(m) for m in msgs]


class TestVerdictParity:
    """Both kernels, identical batch → identical accept/reject masks,
    and (& valid) identical to the serial CPU verifier."""

    def test_mixed_valid_invalid(self):
        pks, msgs, sigs = _batch(13, corrupt_every=4)
        got_w, got_c, valid = _kernel_verdicts(pks, msgs, sigs)
        np.testing.assert_array_equal(got_w, got_c)
        want = _cpu(pks, msgs, sigs)
        assert list(got_c & valid) == want

    def test_non_canonical_s(self):
        # s' = s + L encodes the same residue but MUST reject (the CPU
        # path enforces canonical s); both wires carry the raw bytes and
        # both must agree lane-for-lane
        pks, msgs, sigs = _batch(4, tag=b"noncanon")
        bad = list(sigs)
        for i in (1, 3):
            s_int = int.from_bytes(sigs[i][32:], "little")
            bad[i] = sigs[i][:32] + (s_int + _L).to_bytes(32, "little")
        got_w, got_c, valid = _kernel_verdicts(pks, msgs, bad)
        np.testing.assert_array_equal(got_w, got_c)
        assert list(valid) == [True, False, True, False]
        assert list(got_c & valid) == _cpu(pks, msgs, bad)
        assert _cpu(pks, msgs, bad) == [True, False, True, False]

    def test_all_zero_and_all_ones_rows(self):
        # degenerate encodings: zero A (identity-adjacent y=0), zero
        # R/S, and 0xFF everywhere (y ≥ p, s ≥ L). No semantics asserted
        # beyond: both formats produce the same words and the same
        # verdicts, and nothing accepts that the CPU path rejects.
        pks = [b"\x00" * 32, b"\xff" * 32, b"\x00" * 32, b"\xff" * 32]
        sigs = [b"\x00" * 64, b"\xff" * 64, b"\xff" * 64, b"\x00" * 64]
        msgs = [b"z", b"o", b"zo", b"oz"]
        wire_w, _ = eb.prepare_batch(pks, msgs, sigs)
        wire_c, _ = eb.prepare_batch_compact(pks, msgs, sigs)
        np.testing.assert_array_equal(_words_from_compact(wire_c), wire_w)
        got_w, got_c, valid = _kernel_verdicts(pks, msgs, sigs)
        np.testing.assert_array_equal(got_w, got_c)
        assert list(got_c & valid) == _cpu(pks, msgs, sigs)

    def test_device_hash_compact_parity(self):
        # fused on-device SHA-512 route, ragged message lengths
        # straddling the 1-block/2-block boundary
        import jax.numpy as jnp

        rng = np.random.default_rng(23)
        keys = [ed.gen_priv_key_from_secret(b"dh-%d" % i) for i in range(9)]
        msgs = [bytes(rng.bytes(int(rng.integers(0, 200)))) for _ in keys]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        pks = [k.pub_key().bytes() for k in keys]
        b = bytearray(sigs[2])
        b[40] ^= 0x80
        sigs[2] = bytes(b)

        wire, msg, mlen, valid = eb.prepare_batch_device_hash_compact(
            pks, msgs, sigs
        )
        got = np.asarray(
            eb.verify_full_kernel_compact(
                jnp.asarray(wire), jnp.asarray(msg), jnp.asarray(mlen)
            )
        )
        _, host_c, _ = _kernel_verdicts(pks, msgs, sigs)
        np.testing.assert_array_equal(got, host_c)
        assert list(got & valid) == _cpu(pks, msgs, sigs)


class TestChunkedCompactDispatch:
    """verify_batch with the compact wire (the default) across chunk
    boundaries: the staged-prefetch reassembly must keep lane order and
    never smear a verdict onto a neighbor chunk."""

    @pytest.mark.parametrize("size", [63, 64, 65, 129])
    def test_boundary_sizes(self, size, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "64")
        monkeypatch.setenv("CBFT_TPU_WIRE", "compact")
        monkeypatch.setenv("CBFT_TPU_HASH", "host")
        pks, msgs, sigs = _batch(size, tag=b"chunk", corrupt_every=9)
        got = eb.verify_batch(pks, msgs, sigs)
        assert got == _cpu(pks, msgs, sigs)

    def test_words_and_compact_agree_chunked(self, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "64")
        monkeypatch.setenv("CBFT_TPU_HASH", "host")
        pks, msgs, sigs = _batch(100, tag=b"agree", corrupt_every=7)
        monkeypatch.setenv("CBFT_TPU_WIRE", "compact")
        got_c = eb.verify_batch(pks, msgs, sigs)
        monkeypatch.setenv("CBFT_TPU_WIRE", "words")
        got_w = eb.verify_batch(pks, msgs, sigs)
        assert got_c == got_w == _cpu(pks, msgs, sigs)

    def test_wire_format_env_validation(self, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_WIRE", "gzip")
        with pytest.raises(ValueError, match="CBFT_TPU_WIRE"):
            eb.wire_format()
