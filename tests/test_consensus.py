"""Consensus engine: WAL framing, ticker, and the in-process multi-
validator network — the reference's core fixture (consensus/common_test.go
randConsensusNet): N validators in one process with perfect gossip, no
networking, driving real blocks through real kvstore apps.
"""

import os
import tempfile
import threading
import time

import pytest

from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.consensus.messages import (
    BlockPartMessage,
    EndHeightMessage,
    MsgInfo,
    ProposalMessage,
    TimeoutInfo,
    VoteMessage,
    decode_consensus_message,
    decode_wal_message,
    encode_consensus_message,
    encode_wal_message,
)
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import WAL, NilWAL, WALDecodeError
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.part_set import PartSet, PartSetHeader
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PREVOTE


class TestWALCodec:
    def test_roundtrip_messages(self):
        msgs = [
            EndHeightMessage(7),
            TimeoutInfo(1.5, 3, 2, 4),
            MsgInfo(ProposalMessage(Proposal(height=5, round=1)), "peer1"),
            MsgInfo(VoteMessage(None), ""),
            MsgInfo(
                BlockPartMessage(
                    9, 0, PartSet.from_data(b"some block data").get_part(0)
                ),
                "p2",
            ),
        ]
        for m in msgs:
            enc = encode_wal_message(m)
            dec = decode_wal_message(enc)
            assert type(dec) is type(m)

    def test_consensus_message_envelope(self):
        msg = ProposalMessage(Proposal(height=5, round=1))
        dec = decode_consensus_message(encode_consensus_message(msg))
        assert isinstance(dec, ProposalMessage)
        assert dec.proposal.height == 5

    def test_all_gossip_messages_roundtrip(self):
        """Every consensus wire message — including the BitArray-bearing ones
        (NewValidBlock/ProposalPOL/VoteSetBits) that only appear after the
        first commit — must encode and decode losslessly."""
        from cometbft_tpu.consensus.messages import (
            HasVoteMessage,
            NewRoundStepMessage,
            NewValidBlockMessage,
            ProposalPOLMessage,
            VoteSetBitsMessage,
            VoteSetMaj23Message,
        )
        from cometbft_tpu.libs.bits import BitArray

        ba = BitArray(10)
        ba.set_index(0, True)
        ba.set_index(7, True)
        bid = BlockID(
            hash=b"\x01" * 32, part_set_header=PartSetHeader(3, b"\x02" * 32)
        )
        msgs = [
            NewRoundStepMessage(5, 2, 3, 17, 1),
            NewValidBlockMessage(5, 2, PartSetHeader(3, b"\x02" * 32), ba, True),
            ProposalMessage(Proposal(height=5, round=1)),
            ProposalPOLMessage(5, 1, ba),
            BlockPartMessage(
                9, 0, PartSet.from_data(b"some block data").get_part(0)
            ),
            HasVoteMessage(5, 2, SIGNED_MSG_TYPE_PREVOTE, 3),
            VoteSetMaj23Message(5, 2, SIGNED_MSG_TYPE_PREVOTE, bid),
            VoteSetBitsMessage(5, 2, SIGNED_MSG_TYPE_PREVOTE, bid, ba),
        ]
        for m in msgs:
            dec = decode_consensus_message(encode_consensus_message(m))
            assert type(dec) is type(m), m
        # BitArray contents survive
        dec = decode_consensus_message(
            encode_consensus_message(VoteSetBitsMessage(5, 2, 1, bid, ba))
        )
        assert dec.votes.size == 10
        assert dec.votes.get_index(0) and dec.votes.get_index(7)
        assert not dec.votes.get_index(1)
        # all-zero bitmaps (fresh part sets) must round-trip to full length
        empty = BitArray(100)
        dec = decode_consensus_message(
            encode_consensus_message(
                NewValidBlockMessage(5, 0, PartSetHeader(2, b"\x02" * 32), empty)
            )
        )
        assert dec.block_parts.size == 100
        assert not any(dec.block_parts.get_index(i) for i in range(100))

    def test_bit_array_decode_hardening(self):
        """Packed elems parse correctly; hostile/ambiguous inputs raise."""
        from cometbft_tpu.consensus.messages import (
            _decode_bit_array,
            _encode_bit_array,
        )
        from cometbft_tpu.libs import protoio
        from cometbft_tpu.libs.bits import BitArray

        # our encoder emits packed; decode round-trips bit-exactly
        ba = BitArray(130)
        for i in (0, 64, 129):
            ba.set_index(i, True)
        dec = _decode_bit_array(_encode_bit_array(ba))
        assert [dec.get_index(i) for i in range(130)] == [
            ba.get_index(i) for i in range(130)
        ]
        # unpacked (one varint per elem) still accepted
        unpacked = protoio.field_varint(1, 70)
        for e in ba.elems()[:2]:
            unpacked += protoio.tag(2, protoio.WIRE_VARINT) + protoio.encode_varint(e)
        dec = _decode_bit_array(unpacked)
        assert dec.get_index(0) and dec.get_index(64)
        # a 12-byte message must not drive a multi-GB allocation
        hostile = protoio.field_varint(1, 1 << 40)
        with pytest.raises(ValueError):
            _decode_bit_array(hostile)
        # partially-omitted elems are ambiguous (interior zeros shift the
        # bitmap) — hard error, not silent padding
        partial = protoio.field_varint(1, 128) + protoio.field_bytes(
            2, protoio.encode_varint(1)
        )
        with pytest.raises(ValueError):
            _decode_bit_array(partial)


class TestWAL:
    def test_write_read_search(self):
        with tempfile.TemporaryDirectory() as d:
            wal = WAL(os.path.join(d, "wal"))
            wal.start()
            wal.write_sync(EndHeightMessage(1))
            wal.write(MsgInfo(ProposalMessage(Proposal(height=2)), "p"))
            wal.write_sync(EndHeightMessage(2))
            wal.write(MsgInfo(ProposalMessage(Proposal(height=3)), "p"))
            wal.flush_and_sync()

            msgs = list(wal.iter_messages())
            # initial EndHeight(0) sentinel + our four
            assert isinstance(msgs[0], EndHeightMessage) and msgs[0].height == 0
            assert len(msgs) == 5

            tail, found = wal.search_for_end_height(2)
            assert found
            assert len(tail) == 1
            assert isinstance(tail[0], MsgInfo)
            tail, found = wal.search_for_end_height(9)
            assert not found
            wal.stop()

    def test_rotation_preserves_search_and_replay(self):
        """A long-running node's WAL must rotate (reference: the autofile
        group's processTicks) and search_for_end_height must find markers
        that rotated out of the head into .NNN chunks."""
        with tempfile.TemporaryDirectory() as d:
            wal = WAL(os.path.join(d, "wal"), group_head_size=2_000)
            wal.start()
            filler = ProposalMessage(Proposal(height=1))
            for h in range(1, 8):
                for _ in range(10):
                    wal.write(MsgInfo(filler, "p"))
                wal.write_sync(EndHeightMessage(h))
                # the production trigger is the flush loop's 10s tick;
                # drive the same call directly for a fast test
                wal.group().check_head_size_limit()
            paths = wal.group().all_paths()
            assert len(paths) > 1, "head never rotated"
            # markers living in rotated chunks are still found, with the
            # tail positioned after them exactly as in a single file
            for h in (1, 3, 6):
                tail, found = wal.search_for_end_height(h)
                assert found, h
                assert len(tail) == 10 * (7 - h) + (7 - h - 1) + 1
            _, found = wal.search_for_end_height(99)
            assert not found
            wal.stop()

    def test_repair_in_rotated_chunk_recreates_head(self):
        """Corruption in a rotated .NNN chunk: repair truncates it and
        drops every LATER file including the head — the head fd must be
        closed/recreated, or subsequent writes land on an unlinked
        inode and vanish."""
        from cometbft_tpu.consensus.wal import repair_wal_tail

        with tempfile.TemporaryDirectory() as d:
            wal = WAL(os.path.join(d, "wal"), group_head_size=600)
            wal.start()
            for h in range(1, 6):
                for _ in range(4):
                    wal.write(MsgInfo(ProposalMessage(Proposal(height=h)), "p"))
                wal.write_sync(EndHeightMessage(h))
                wal.group().check_head_size_limit()
            paths = wal.group().all_paths()
            assert len(paths) >= 3, paths
            # corrupt the FIRST rotated chunk mid-file
            with open(paths[0], "r+b") as f:
                size = os.path.getsize(paths[0])
                f.seek(size // 2)
                f.write(b"\xff" * 12)
            assert repair_wal_tail(wal)
            # the head was recreated: new writes must be durable+readable
            wal.write_sync(EndHeightMessage(99))
            msgs = list(wal.iter_messages())  # no decode error anywhere
            assert any(
                isinstance(m, EndHeightMessage) and m.height == 99
                for m in msgs
            ), "post-repair write lost (head on unlinked inode?)"
            _, found = wal.search_for_end_height(99)
            assert found
            wal.stop()

    def test_corruption_detected(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "wal")
            wal = WAL(path)
            wal.start()
            wal.write_sync(EndHeightMessage(1))
            wal.stop()
            with open(path, "r+b") as f:
                f.seek(-3, 2)
                f.write(b"\xff\xff\xff")
            wal2 = WAL(path)
            wal2._group.flush_and_sync()
            with pytest.raises(WALDecodeError):
                list(wal2.iter_messages())


# --- in-process consensus network ------------------------------------------


def _make_network(n=4):
    vals, privs = test_util.deterministic_validator_set(n, 10)
    doc = GenesisDoc(
        genesis_time=Timestamp(1_700_000_000, 0),
        chain_id="cs-test-chain",
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    nodes = []
    for i in range(n):
        cfg = make_test_config().consensus
        cfg.wal_path = ""  # NilWAL
        state = make_genesis_state(doc)
        store = Store(MemDB())
        store.save(state)
        bstore = BlockStore(MemDB())
        client = LocalClient(KVStoreApplication())
        client.start()
        executor = BlockExecutor(store, AppConnConsensus(client))
        # align privval with this node's slot in the (sorted) validator set
        pv = privs[i]
        cs = ConsensusState(
            cfg, state, executor, bstore, wal=NilWAL()
        )
        cs.set_priv_validator(pv)
        nodes.append(cs)

    # perfect gossip: everything a node emits internally is replicated to
    # every peer's message queue (the reactor's job in a real deployment)
    for i, cs in enumerate(nodes):
        orig = cs.send_internal

        def fan_out(msg, _orig=orig, _i=i):
            _orig(msg)
            for j, other in enumerate(nodes):
                if j != _i:
                    other.send_peer_message(msg, f"node{_i}")

        cs.send_internal = fan_out
    return nodes


def _wait_for_height(nodes, height, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(cs.height() > height for cs in nodes):
            return True
        time.sleep(0.05)
    return False


class TestConsensusNetwork:
    def test_four_validators_commit_blocks(self):
        nodes = _make_network(4)
        for cs in nodes:
            cs.start()
        try:
            assert _wait_for_height(nodes, 3), [cs.height() for cs in nodes]
            # all nodes agree on every committed block
            for h in (1, 2, 3):
                hashes = {cs.block_store.load_block_meta(h).block_id.hash for cs in nodes}
                assert len(hashes) == 1, f"height {h} diverged"
            # app state advanced identically
            app_hashes = {cs.state.app_hash for cs in nodes}
            assert len(app_hashes) == 1
        finally:
            for cs in nodes:
                cs.stop()

    def test_commits_with_one_node_down(self):
        # 4 validators, 1 silent (< 1/3) — liveness must hold
        nodes = _make_network(4)
        for cs in nodes[:3]:
            cs.start()
        try:
            assert _wait_for_height(nodes[:3], 2, timeout=60), [
                cs.height() for cs in nodes[:3]
            ]
        finally:
            for cs in nodes[:3]:
                cs.stop()


class TestCrashRecovery:
    """Reference: consensus/replay_test.go — kill a node, restart from
    WAL + stores, verify it continues producing blocks."""

    def _build_node(self, d, doc, retain_blocks: int = 0):
        from cometbft_tpu.libs.db import SQLiteDB

        state_store = Store(SQLiteDB(os.path.join(d, "state.db")))
        bstore = BlockStore(SQLiteDB(os.path.join(d, "blocks.db")))
        app_db = SQLiteDB(os.path.join(d, "app.db"))
        app = KVStoreApplication(app_db)
        app.retain_blocks = retain_blocks
        client = LocalClient(app)
        client.start()

        state = state_store.load()
        if state is None:
            state = make_genesis_state(doc)
            state_store.save(state)
        executor = BlockExecutor(state_store, AppConnConsensus(client))
        cfg = make_test_config().consensus
        wal = WAL(os.path.join(d, "cs.wal", "wal"))
        wal.start()
        cs = ConsensusState(cfg, state, executor, bstore, wal=wal)
        return cs, state_store, bstore, client

    def test_double_sign_check_refuses_stale_sign_state(self):
        """consensus/state.go:2286 checkDoubleSigningRisk: with
        double_sign_check_height set, a restart whose recent commits
        carry OUR signature refuses to start (stale/backup sign state →
        equivocation risk). Off by default."""
        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="dsc-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 3), cs.height()
            cs.stop()
            client.stop()
            time.sleep(0.1)
            # restart with the guard ON: the last commits carry our sig
            cs2, _, _, client2 = self._build_node(d, doc)
            cs2.config.double_sign_check_height = 10
            cs2.set_priv_validator(privs[0])
            with pytest.raises(Exception, match="double_sign_check"):
                cs2.start()
            client2.stop()

    def test_retain_height_prunes_blocks_and_states(self):
        """App-driven pruning (ResponseCommit.retain_height) must prune
        BOTH the block store and the state store's per-height artifacts
        (reference consensus/state.go:1708-1717 — pruneBlocks then
        PruneStates over the same span); without the latter, validators/
        params/responses grow forever on a pruning chain."""
        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="prune-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            # retain only the last two heights — the app requests pruning
            cs, state_store, bstore, client = self._build_node(
                d, doc, retain_blocks=2
            )
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 5, timeout=60), cs.height()
            cs.stop()
            client.stop()
            base = bstore.base()
            assert base > 1, "blocks were never pruned"
            # pruned heights lost their state artifacts...
            from cometbft_tpu.state.store import ErrNoABCIResponsesForHeight

            with pytest.raises(ErrNoABCIResponsesForHeight):
                state_store.load_abci_responses(1)
            # ...while surviving heights still resolve fully
            h = bstore.height()
            assert state_store.load_validators(h) is not None
            assert state_store.load_consensus_params(h) is not None

    def test_start_replays_wal_automatically(self):
        """The production path: cs.start() alone must run the WAL
        catch-up (reference State.OnStart doWALCatchup) — no manual
        catchup_replay call."""
        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="auto-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 2), cs.height()
            h_before = cs.height()
            cs.stop()
            client.stop()
            time.sleep(0.1)
            cs2, state_store2, bstore2, client2 = self._build_node(d, doc)
            cs2.set_priv_validator(privs[0])
            cs2.start()  # on_start replays; chain continues
            assert getattr(cs2, "_wal_catchup_done", False)
            assert _wait_for_height([cs2], h_before + 1, timeout=30), cs2.height()
            cs2.stop()
            client2.stop()

    def test_start_repairs_corrupt_wal_tail(self):
        """A torn/corrupted WAL tail gets ONE repair (truncate after the
        last valid record — reference repairWalFile) and the node
        proceeds instead of failing to start."""
        from cometbft_tpu.consensus.wal import repair_wal_tail

        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="repair-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 3), cs.height()
            cs.stop()
            client.stop()
            time.sleep(0.1)
            # corrupt the WAL mid-file: flip bytes well inside the head
            # so records from some point on (incl. height markers) are
            # unreadable — replay must hit WALDecodeError
            head = os.path.join(d, "cs.wal", "wal")
            size = os.path.getsize(head)
            with open(head, "r+b") as f:
                f.seek(size // 2)
                f.write(b"\xde\xad\xbe\xef" * 8)
            cs2, state_store2, bstore2, client2 = self._build_node(d, doc)
            cs2.set_priv_validator(privs[0])
            cs2.start()  # must repair + proceed, not raise
            assert getattr(cs2, "_wal_catchup_done", False)
            # after repair every surviving record decodes cleanly
            msgs = list(cs2.wal.iter_messages())
            assert msgs, "repair left an unreadable WAL"
            # and the node still makes progress
            assert _wait_for_height([cs2], cs2.height() + 1, timeout=30)
            cs2.stop()
            client2.stop()
            assert not repair_wal_tail(cs2.wal), "second repair found damage"

    def test_stop_waits_for_inflight_finalize_wal_write(self):
        """Stop-order guarantee: after stop() returns, every message of
        the batch the receive routine was processing has fully handled
        AND its durable WAL writes landed. The old order (wal.stop()
        without joining the routine) violated this whenever stop()'s
        flag-flip won the state mutex between two batch messages — a
        later message could then finalize a commit whose
        write_sync(#ENDHEIGHT) the stopped WAL silently dropped while
        apply_block persisted state (the load-only restart flake:
        "WAL has no #ENDHEIGHT h-1"). Lock-acquisition fairness makes
        that loss probabilistic, so this test pins the guarantee the
        join provides rather than re-rolling the race."""
        from cometbft_tpu.consensus.messages import EndHeightMessage
        from cometbft_tpu.consensus.state import MsgInfo

        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="stop-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            wrote = threading.Event()
            m1_entered = threading.Event()

            def handler(mi):
                if mi.msg == "m1":
                    m1_entered.set()
                    time.sleep(1.0)  # stop() arrives while this holds _mtx
                elif mi.msg == "m2":
                    # the race window: by now the old stop order has
                    # already stopped the WAL; give wal.stop a head
                    # start so the old code loses deterministically
                    time.sleep(0.3)
                    cs.wal.write_sync(EndHeightMessage(4242))
                    wrote.set()

            cs._handle_msg = handler
            cs._batch_preverify_votes = lambda batch: None
            # the pre-handler message log would try to proto-encode the
            # string fixtures; neutralize it — the assertion is about
            # the handler's own write_sync landing, not the message log
            cs.wal.write = lambda mi: None
            # both messages must land in ONE drained batch
            cs.peer_msg_queue.put(MsgInfo("m1", "peer"))
            cs.peer_msg_queue.put(MsgInfo("m2", "peer"))
            cs.start()
            # deterministic in both directions: stop() must land while
            # m1's handler is mid-sleep (batch in flight), not before
            # the batch started nor after it drained
            assert m1_entered.wait(10.0), "receive routine never ran m1"
            cs.stop()  # must join the routine, THEN stop the WAL
            client.stop()
            assert wrote.is_set(), "stop() did not wait for the batch tail"
            _, found = cs.wal.search_for_end_height(4242)
            assert found, "in-flight #ENDHEIGHT was dropped by stop()"

    def test_restart_continues_chain(self):
        from cometbft_tpu.consensus.replay import Handshaker, catchup_replay

        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="wal-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 2), cs.height()
            h_before = cs.height()
            # hard stop (no graceful teardown of in-flight height)
            cs.stop()
            client.stop()
            time.sleep(0.1)

            # restart: fresh objects over the same persistent artifacts
            cs2, state_store2, bstore2, client2 = self._build_node(d, doc)
            cs2.set_priv_validator(privs[0])
            catchup_replay(cs2, cs2.height())
            cs2.start()
            assert _wait_for_height([cs2], h_before + 1, timeout=30), cs2.height()
            # chain is continuous across the restart
            for h in range(1, cs2.height() - 1):
                assert bstore2.load_block_meta(h) is not None, f"missing block {h}"
            cs2.stop()
            client2.stop()

    def test_handshake_replays_app(self):
        """App db wiped → handshake replays all blocks from the store."""
        from cometbft_tpu.consensus.replay import Handshaker
        from cometbft_tpu.libs.db import SQLiteDB
        from cometbft_tpu.proxy import AppConns, new_local_client_creator

        vals, privs = test_util.deterministic_validator_set(1, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id="hs-chain",
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        with tempfile.TemporaryDirectory() as d:
            cs, state_store, bstore, client = self._build_node(d, doc)
            cs.set_priv_validator(privs[0])
            cs.start()
            assert _wait_for_height([cs], 3), cs.height()
            cs.stop()
            client.stop()
            time.sleep(0.1)

            # fresh app with EMPTY db — Info returns height 0
            state = state_store.load()
            fresh_app = KVStoreApplication()  # memdb
            conns = AppConns(new_local_client_creator(fresh_app))
            conns.start()
            hs = Handshaker(state_store, state, bstore, doc)
            hs.handshake(conns)
            assert hs.n_blocks >= 3
            info = conns.query().info_sync(
                __import__("cometbft_tpu.abci.types", fromlist=["RequestInfo"]).RequestInfo()
            )
            assert info.last_block_height == bstore.height()
            conns.stop()


# --- POL / lock-unlock state machine ---------------------------------------


class _RecordingBus:
    """NopEventBus that records which round-state events fired, in order."""

    def __init__(self):
        self.events = []

    def __getattr__(self, name):
        if name.startswith("publish_event_"):
            kind = name[len("publish_event_"):]
            return lambda *a, **k: self.events.append(kind)
        raise AttributeError(name)

    def count(self, kind):
        return self.events.count(kind)


class TestPOLLocking:
    """Direct walks of _enter_precommit's lock/relock/unlock decisions —
    the reference's TestStateLockNoPOL / TestStateLockPOLRelock /
    TestStateLockPOLUnlock family (consensus/state_test.go), driven as
    unit tests on one ConsensusState with votes injected from the other
    three validators (3-of-4 × power 10 = 30 > 2/3 of 40)."""

    CHAIN = "pol-chain"

    def _make_cs(self):
        from cometbft_tpu.consensus.round_state import RoundStepType
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        vals, privs = test_util.deterministic_validator_set(4, 10)
        doc = GenesisDoc(
            genesis_time=Timestamp(1_700_000_000, 0),
            chain_id=self.CHAIN,
            validators=[
                GenesisValidator(v.address, v.pub_key, v.voting_power, "")
                for v in vals.validators
            ],
        )
        cfg = make_test_config().consensus
        cfg.wal_path = ""
        state = make_genesis_state(doc)
        store = Store(MemDB())
        store.save(state)
        client = LocalClient(KVStoreApplication())
        client.start()
        executor = BlockExecutor(store, AppConnConsensus(client))
        bus = _RecordingBus()
        cs = ConsensusState(
            cfg, state, executor, BlockStore(MemDB()),
            wal=NilWAL(), event_bus=bus,
        )
        cs.set_priv_validator(privs[0])
        return cs, privs, bus

    def _proposal_block(self, cs, privs, round_=0):
        """A real height-1 proposal block + Proposal, installed in rs."""
        from cometbft_tpu.types.block import Commit

        block, parts = cs.block_exec.create_proposal_block(
            1, cs.state, Commit(0, 0, BlockID(), []),
            privs[0].get_pub_key().address(),
        )
        bid = BlockID(block.hash(), parts.header())
        cs.rs.proposal = Proposal(
            height=1, round=round_, pol_round=-1, block_id=bid
        )
        cs.rs.proposal_block = block
        cs.rs.proposal_block_parts = parts
        return bid

    def _prevote(self, cs, privs, idxs, round_, bid):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PREVOTE

        for i in idxs:
            v = test_util.make_vote(
                privs[i], self.CHAIN, i, 1, round_,
                SIGNED_MSG_TYPE_PREVOTE, bid,
            )
            assert cs._add_vote(v, f"peer{i}")

    def _own_votes(self, cs):
        """Drain the internal queue; return this node's signed votes."""
        out = []
        while not cs.internal_msg_queue.empty():
            mi = cs.internal_msg_queue.get_nowait()
            msg = mi.msg if isinstance(mi, MsgInfo) else mi
            if isinstance(msg, VoteMessage):
                out.append(msg.vote)
        return out

    def _at_prevote(self, cs, round_=0):
        from cometbft_tpu.consensus.round_state import RoundStepType

        cs.rs.round = round_
        cs.rs.step = RoundStepType.PREVOTE

    def test_lock_on_polka(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)  # 30/40 > 2/3 → polka
        assert cs.rs.locked_block is not None
        assert cs.rs.locked_block.hash() == bid.hash
        assert cs.rs.locked_round == 0
        assert "polka" in bus.events and "lock" in bus.events
        precommits = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PRECOMMIT
        ]
        assert precommits and precommits[-1].block_id.hash == bid.hash

    def test_relock_same_block_later_round(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)
        assert cs.rs.locked_round == 0
        # round 1: polka for the SAME block → relock, not unlock
        self._at_prevote(cs, round_=1)
        self._prevote(cs, privs, (1, 2, 3), 1, bid)
        assert cs.rs.locked_block is not None
        assert cs.rs.locked_round == 1
        assert bus.count("relock") == 1
        assert bus.count("unlock") == 0
        precommits = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PRECOMMIT and v.round == 1
        ]
        assert precommits and precommits[-1].block_id.hash == bid.hash

    def test_unlock_on_nil_polka(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)
        assert cs.rs.locked_block is not None
        # round 1: +2/3 prevote nil → unlock, precommit nil
        self._at_prevote(cs, round_=1)
        self._prevote(cs, privs, (1, 2, 3), 1, BlockID())
        assert cs.rs.locked_block is None
        assert cs.rs.locked_round == -1
        assert bus.count("unlock") >= 1
        precommits = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PRECOMMIT and v.round == 1
        ]
        assert precommits and precommits[-1].block_id.is_zero()

    def test_unlock_on_polka_for_unseen_block(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)
        assert cs.rs.locked_block is not None
        # round 1: polka for a block this node has never seen
        unseen = test_util.make_block_id(b"\xaa" * 32, 7, b"\xbb" * 32)
        self._at_prevote(cs, round_=1)
        self._prevote(cs, privs, (1, 2, 3), 1, unseen)
        # the later-round-different-block rule unlocks immediately
        assert cs.rs.locked_block is None
        assert bus.count("unlock") >= 1
        # and the part-set has been re-primed to fetch the unseen block
        assert cs.rs.proposal_block is None
        assert cs.rs.proposal_block_parts.has_header(unseen.part_set_header)
        # entering precommit without the block precommits nil
        cs._enter_precommit(1, 1)
        precommits = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PRECOMMIT and v.round == 1
        ]
        assert precommits and precommits[-1].block_id.is_zero()

    def test_prevote_follows_lock(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PREVOTE

        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)
        assert cs.rs.locked_block is not None
        self._own_votes(cs)  # drain
        # round 1 arrives with a DIFFERENT proposal; locked node must
        # still prevote its locked block (defaultDoPrevote rule)
        cs.rs.round = 1
        cs.rs.proposal_block = None
        cs.rs.proposal_block_parts = None
        cs._do_prevote(1, 1)
        prevotes = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PREVOTE
        ]
        assert prevotes and prevotes[-1].block_id.hash == bid.hash

    def test_precommit_nil_without_polka(self):
        from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

        cs, privs, bus = self._make_cs()
        self._proposal_block(cs, privs)
        self._at_prevote(cs)
        # no prevotes at all → precommit nil, no lock, no polka event
        cs._enter_precommit(1, 0)
        assert cs.rs.locked_block is None
        assert "polka" not in bus.events
        precommits = [
            v for v in self._own_votes(cs)
            if v.type == SIGNED_MSG_TYPE_PRECOMMIT
        ]
        assert precommits and precommits[-1].block_id.is_zero()

    def test_polka_below_two_thirds_does_not_lock(self):
        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2), 0, bid)  # 20/40 — no polka
        assert cs.rs.locked_block is None
        assert "lock" not in bus.events

    def test_unlock_only_for_later_round_polka(self):
        """A polka from an EARLIER round must not unlock (the :2074 rule
        requires locked_round < vote.round)."""
        cs, privs, bus = self._make_cs()
        bid = self._proposal_block(cs, privs)
        self._at_prevote(cs)
        self._prevote(cs, privs, (1, 2, 3), 0, bid)
        assert cs.rs.locked_round == 0
        # move to round 2 and lock there via relock
        self._at_prevote(cs, round_=2)
        self._prevote(cs, privs, (1, 2, 3), 2, bid)
        assert cs.rs.locked_round == 2
        # now a late nil polka for round 1 (< locked_round) arrives
        self._prevote(cs, privs, (1, 2, 3), 1, BlockID())
        assert cs.rs.locked_block is not None, "early-round polka must not unlock"
        assert cs.rs.locked_round == 2


class TestInvalidBlockParts:
    """Reference: consensus/invalid_test.go — a byzantine peer floods
    corrupted block parts; honest nodes must reject them (merkle proof
    check in PartSet.AddPart) and keep committing."""

    def test_corrupt_parts_rejected_and_chain_advances(self):
        from cometbft_tpu.types.part_set import PartSet

        nodes = _make_network(4)
        for cs in nodes:
            cs.start()
        try:
            assert _wait_for_height(nodes, 1, timeout=60)
            evil = PartSet.from_data(b"not the real block" * 100)
            # keep spraying until corrupt parts were PROVABLY delivered
            # at nodes that had a live proposal part set (a vacuous run
            # — every node mid-gap with no part set — must not pass)
            delivered = 0
            deadline = time.monotonic() + 30
            while delivered < 8 and time.monotonic() < deadline:
                for cs in nodes:
                    rs = cs.rs
                    if rs.proposal_block_parts is None:
                        continue
                    for i in range(evil.total()):
                        part = evil.get_part(i)
                        part.index = min(
                            i, rs.proposal_block_parts.total() - 1
                        )
                        cs.send_peer_message(
                            BlockPartMessage(rs.height, rs.round, part),
                            "evil-peer",
                        )
                        delivered += 1
                time.sleep(0.05)
            assert delivered >= 8, "no corrupt parts ever delivered"
            # the merkle-proof check must discard every corrupt part and
            # consensus keeps committing
            target = max(cs.height() for cs in nodes) + 2
            assert _wait_for_height(nodes, target, timeout=90), [
                cs.height() for cs in nodes
            ]
            hashes = {
                cs.block_store.load_block_meta(target).block_id.hash
                for cs in nodes
            }
            assert len(hashes) == 1
        finally:
            for cs in nodes:
                cs.stop()
