"""Decision plane: routing-decision ledger, prediction accuracy, and
the anomaly watchdog (crypto/decisions.py + the scheduler/supervisor
feeders).

Contract under test:

  - every coalesced flush through VerifyScheduler._verify lands exactly
    ONE RouteDecision whose taken route is the same label _note_route
    counted, so ledger counts reconcile with queue_snapshot()['routes']
    to the unit — including when the dispatch raises or falls back;
  - a supervised sharded dispatch that falls back (quarantined mesh)
    still produces exactly one record: taken='sharded', final='single',
    the fallback event attributed, the ORIGINAL candidate prices kept;
  - prediction ladder: the ledger's own per-(route, bucket) wall EWMA
    once >= MIN_SELF_OBS observations, then the wire CostProfile, then
    None (cold decisions record no error);
  - APE is normalized by the PREDICTION (a world slower than the model
    claims reads unbounded, not saturated below 1.0), and the watchdog
    trips hysteretically: >= MIN_TRIP_OBS windowed observations, one
    on_anomaly fire per episode, re-arm only after REARM_CLEAN clean
    samples below half the trip level;
  - the time-series ring is bounded at RING_CAPACITY and samples on the
    finish path (lazy clock-compare — no background thread);
  - the chaos staleness rung (crypto/faults.py run_chaos_stale_model /
    tools/chaos.py --stale-model) passes end to end: injected jitter
    trips the watchdog, fires exactly one incident dump, re-arms.

Runs CPU-only — no device plane required.
"""

import pytest

from cometbft_tpu.crypto import decisions as declib
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.batch import BackendSpec
from cometbft_tpu.crypto.decisions import (
    MIN_SELF_OBS,
    MIN_TRIP_OBS,
    REARM_CLEAN,
    RING_CAPACITY,
    DecisionLedger,
    RouteDecision,
)
from cometbft_tpu.crypto.scheduler import VerifyScheduler


def _make_items(n, tag=b"dec"):
    items = []
    for i in range(n):
        k = ed.gen_priv_key_from_secret(tag + bytes([i & 0xFF, i >> 8]))
        msg = b"decision-msg-" + i.to_bytes(4, "big")
        items.append((k.pub_key(), msg, k.sign(msg)))
    return items


@pytest.fixture(autouse=True)
def _no_default_ledger():
    """Tests install their own ledger; never leak one into the suite."""
    prev = declib.set_default_ledger(None)
    yield
    declib.set_default_ledger(prev)


class _StubProfile:
    """CostProfile stand-in with fixed per-route prices."""

    def __init__(self, prices):
        self.prices = prices

    def predict_ms(self, route, bucket):
        return self.prices.get(route)


# ---------------------------------------------------------------------------
# record + ledger core
# ---------------------------------------------------------------------------


class TestRouteDecisionRecord:
    def test_as_dict_final_defaults_to_taken(self):
        dec = RouteDecision(
            seq=1, n=17, reason="size", capacity=0.5, breakers=None,
            keystore=None, qos=None, predicted={"cpu": 1.0},
        )
        dec.taken = "cpu"
        d = dec.as_dict()
        assert d["bucket"] == 32
        assert d["final"] == "cpu" and d["diverted"] is False

    def test_diverted_when_final_differs(self):
        dec = RouteDecision(
            seq=1, n=4, reason="size", capacity=None, breakers=None,
            keystore=None, qos=None, predicted={},
        )
        dec.taken = "sharded"
        dec.final = "single"
        assert dec.diverted is True
        assert dec.as_dict()["final"] == "single"


class TestLedgerCore:
    def test_candidates_always_price_all_three_rungs(self):
        led = DecisionLedger(cost_profile=_StubProfile({"single": 3.0}))
        dec = led.open(n=10, reason="size")
        assert set(dec.predicted) == {"cpu", "single", "sharded"}
        assert dec.predicted["single"] == 3.0
        assert dec.predicted["cpu"] is None

    def test_sub_routes_priced_only_when_known(self):
        led = DecisionLedger(
            cost_profile=_StubProfile({"single": 3.0, "indexed": 2.0})
        )
        dec = led.open(n=10, reason="size")
        assert dec.predicted["indexed"] == 2.0
        assert "device_hash" not in dec.predicted

    def test_self_ewma_outranks_wire_profile_once_warm(self):
        led = DecisionLedger(cost_profile=_StubProfile({"cpu": 100.0}))
        assert led.predict_ms("cpu", 16) == 100.0
        for _ in range(MIN_SELF_OBS):
            dec = led.open(n=16, reason="size")
            dec.taken = "cpu"
            led.finish(dec, 0.002)
        pred = led.predict_ms("cpu", 16)
        assert pred == pytest.approx(2.0, rel=0.05)

    def test_cold_decision_records_no_error(self):
        led = DecisionLedger()
        dec = led.open(n=8, reason="size")
        dec.taken = "cpu"
        led.finish(dec, 0.001)
        assert dec.error_ms is None
        assert led.snapshot()["windowed"]["observations"] == 0

    def test_regret_is_taken_minus_best_candidate(self):
        led = DecisionLedger(
            cost_profile=_StubProfile({"cpu": 10.0, "single": 2.0})
        )
        dec = led.open(n=16, reason="size")
        dec.taken = "cpu"
        led.finish(dec, 0.010)
        assert dec.regret_ms == pytest.approx(8.0)
        win = led.snapshot()["windowed"]
        assert win["regret_ms"] == pytest.approx(8.0)
        assert win["regret_rate"] == 1.0  # 8ms > 10% of the 10ms claim

    def test_ape_normalized_by_prediction_not_wall(self):
        # a 2ms claim measured at 10ms must read APE 4.0 (unbounded
        # regime), NOT |10-2|/10 = 0.8 (saturating regime)
        led = DecisionLedger(cost_profile=_StubProfile({"cpu": 2.0}))
        dec = led.open(n=16, reason="size")
        dec.taken = "cpu"
        led.finish(dec, 0.010)
        assert dec.error_ms == pytest.approx(8.0)
        assert led.snapshot()["windowed"]["mape"] == pytest.approx(4.0)

    def test_diverted_wall_never_folds_into_taken_profile(self):
        led = DecisionLedger(cost_profile=_StubProfile({"sharded": 2.0}))
        dec = led.open(n=16, reason="size")
        dec.taken = "sharded"
        led.note_event(dec, "sharded_fallback", final="single")
        led.finish(dec, 0.500)  # includes the failed sharded attempt
        snap = led.snapshot()
        assert snap["fallbacks"] == {"sharded": 1}
        st = [p for p in snap["profiles"] if p["route"] == "sharded"]
        assert st and st[0]["n"] == 0  # no wall folded
        assert dec.error_ms is None


# ---------------------------------------------------------------------------
# scheduler feed + reconciliation
# ---------------------------------------------------------------------------


class TestSchedulerFeed:
    def test_one_decision_per_flush_reconciles_with_routes(self):
        led = DecisionLedger()
        declib.set_default_ledger(led)
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=300)
        sched.start()
        try:
            for _ in range(5):
                ok, mask = sched.submit(
                    _make_items(8), subsystem="test"
                ).result(timeout=60)
                assert ok and all(mask)
            routes = sched.queue_snapshot()["routes"]
        finally:
            sched.stop()
        counts = led.counts()
        assert sum(counts.values()) == sum(routes.values()) > 0
        for route in set(counts) | set(routes):
            assert counts.get(route, 0) == routes.get(route, 0)

    def test_decision_carries_flush_inputs(self):
        led = DecisionLedger()
        declib.set_default_ledger(led)
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=300)
        sched.start()
        try:
            sched.submit(
                _make_items(6), subsystem="consensus", height=42
            ).result(timeout=60)
        finally:
            sched.stop()
        rec = led.snapshot()["recent"][-1]
        assert rec["n"] == 6 and rec["bucket"] == 8
        assert rec["taken"] == "cpu" and rec["diverted"] is False
        assert rec["wall_ms"] > 0.0
        assert set(rec["predicted_ms"]) >= {"cpu", "single", "sharded"}

    def test_no_ledger_installed_costs_nothing_and_verifies(self):
        sched = VerifyScheduler(spec=BackendSpec("cpu"), flush_us=300)
        sched.start()
        try:
            ok, mask = sched.submit(_make_items(4)).result(timeout=60)
        finally:
            sched.stop()
        assert ok and all(mask)

    def test_unsupervised_backend_death_is_one_cpu_fallback_record(self):
        # the backend raises on construction -> scheduler CPU ground
        # truth; the record must show the divergence, not a second row
        led = DecisionLedger()
        declib.set_default_ledger(led)
        sched = VerifyScheduler(
            spec=BackendSpec("no-such-backend"), flush_us=300
        )
        sched.start()
        try:
            ok, mask = sched.submit(_make_items(4)).result(timeout=60)
            routes = sched.queue_snapshot()["routes"]
        finally:
            sched.stop()
        assert ok and all(mask)
        counts = led.counts()
        assert counts == {"single": 1}          # the taken label
        assert routes["single"] == 1            # reconciles to the unit
        rec = led.snapshot()["recent"][-1]
        assert rec["final"] == "cpu" and rec["diverted"] is True
        assert "cpu_fallback" in rec["events"]


class TestShardedFallbackDecision:
    def test_quarantined_mesh_fallback_is_one_record(self, monkeypatch):
        # satellite 4: a sharded dispatch that falls back must produce
        # exactly one decision record carrying the final route AND the
        # original candidate set
        from cometbft_tpu.crypto.faults import FaultPlan, install
        from cometbft_tpu.crypto.supervisor import BackendSupervisor
        from cometbft_tpu.crypto.tpu import topology

        name = "dec-sharded-fb"
        install(name=name, inner="cpu", plan=FaultPlan(seed=3))
        topo = topology.DeviceTopology.virtual(2)
        topo.set_quarantined(1)  # one healthy domain: sharded must fall back
        before = topology.default_topology()
        sup = BackendSupervisor(
            spec=BackendSpec(name), topology=topo,
            dispatch_timeout_ms=60_000, hedge_pct=0, audit_pct=0,
            probe_base_ms=60_000, probe_max_ms=120_000,
        )
        led = DecisionLedger()
        declib.set_default_ledger(led)
        monkeypatch.setenv("CBFT_MESH_ROUTE", "sharded")
        sched = VerifyScheduler(
            spec=BackendSpec(name), supervisor=sup, flush_us=300,
        )
        sched.start()
        try:
            ok, mask = sched.submit(
                _make_items(32, tag=b"fb"), subsystem="test"
            ).result(timeout=60)
            routes = sched.queue_snapshot()["routes"]
        finally:
            sched.stop()
            sup.stop()
            topology.set_default_topology(before)
        assert ok and all(mask)
        counts = led.counts()
        assert counts == {"sharded": 1}
        assert routes["sharded"] == 1  # reconciles with the counter
        recent = led.snapshot()["recent"]
        assert len(recent) == 1  # exactly one record for the flush
        rec = recent[0]
        assert rec["taken"] == "sharded"
        assert rec["final"] == "single" and rec["diverted"] is True
        assert "sharded_fallback" in rec["events"]
        # the ORIGINAL candidates survive on the record
        assert set(rec["predicted_ms"]) >= {"cpu", "single", "sharded"}


# ---------------------------------------------------------------------------
# watchdog + ring
# ---------------------------------------------------------------------------


def _feed(led, wall_ms, n=1, route="cpu", bucket_n=16):
    for _ in range(n):
        dec = led.open(n=bucket_n, reason="size")
        dec.taken = route
        led.finish(dec, wall_ms / 1e3)


class TestAnomalyWatchdog:
    def test_hysteretic_trip_fire_once_and_rearm(self):
        fires = []
        led = DecisionLedger(
            window=MIN_TRIP_OBS,
            ring_interval_s=0.0,  # evaluate on every finish
            on_anomaly=lambda cause, value: fires.append((cause, value)),
        )
        _feed(led, 2.0, n=MIN_TRIP_OBS + MIN_SELF_OBS)  # converge clean
        assert led.watchdog_state()["tripped"] is None
        _feed(led, 50.0, n=4)  # stale world: APE (50-2)/2 = 24 >> trip
        wd = led.watchdog_state()
        assert wd["tripped"] == "mape" and wd["trips"] == 1
        assert len(fires) == 1 and fires[0][0] == "mape"
        # staying hot never re-fires the episode
        _feed(led, 50.0, n=4)
        assert led.watchdog_state()["trips"] == 1 and len(fires) == 1
        # recovery: walls return to the (now adapted) prediction; the
        # window drains below half the trip, REARM_CLEAN samples re-arm
        pred = led.predict_ms("cpu", 16)
        _feed(led, pred, n=led.window + REARM_CLEAN)
        wd = led.watchdog_state()
        assert wd["tripped"] is None and wd["trips"] == 1
        # a second stale regime is a second episode with its own fire
        _feed(led, pred * 40.0, n=2)
        assert led.watchdog_state()["trips"] == 2 and len(fires) == 2

    def test_no_trip_below_min_observations(self):
        fires = []
        led = DecisionLedger(
            window=MIN_TRIP_OBS,
            ring_interval_s=0.0,
            cost_profile=_StubProfile({"cpu": 1.0}),
            on_anomaly=lambda *a: fires.append(a),
        )
        # wildly wrong predictions, but fewer than MIN_TRIP_OBS of them
        _feed(led, 100.0, n=MIN_TRIP_OBS - 1)
        assert led.watchdog_state()["tripped"] is None
        assert not fires

    def test_regret_rate_trips_on_its_own_axis(self):
        fires = []
        led = DecisionLedger(
            window=MIN_TRIP_OBS,
            ring_interval_s=0.0,
            # cpu claims 10ms, single claims 1ms: taking cpu every time
            # is a 9ms regret event per decision (rate 1.0 > 0.5), while
            # APE stays 0 (wall == claim) so only regret can trip
            cost_profile=_StubProfile({"cpu": 10.0, "single": 1.0}),
            on_anomaly=lambda cause, value: fires.append(cause),
        )
        _feed(led, 10.0, n=MIN_TRIP_OBS)
        wd = led.watchdog_state()
        assert wd["tripped"] == "regret"
        assert fires == ["regret"]

    def test_on_anomaly_exception_never_escapes(self):
        def boom(cause, value):
            raise RuntimeError("capture path died")

        led = DecisionLedger(
            window=MIN_TRIP_OBS, ring_interval_s=0.0, on_anomaly=boom,
        )
        _feed(led, 2.0, n=MIN_TRIP_OBS + MIN_SELF_OBS)
        _feed(led, 80.0, n=2)  # fires boom through the trip path
        assert led.watchdog_state()["trips"] == 1


class TestTimeSeriesRing:
    def test_ring_samples_on_finish_and_is_bounded(self):
        led = DecisionLedger(ring_interval_s=0.0)
        _feed(led, 2.0, n=RING_CAPACITY + 20)
        ring = led.snapshot()["ring"]
        assert len(ring) == RING_CAPACITY
        s = ring[-1]
        assert {
            "ts", "duty_cycle", "p99_ms", "burn_rate", "mape",
            "regret_rate", "regret_ms",
        } <= set(s)

    def test_interval_gates_sampling(self):
        t = [0.0]
        led = DecisionLedger(ring_interval_s=10.0, clock=lambda: t[0])
        _feed(led, 2.0, n=5)  # all at t=0: only the first passes the gate
        assert len(led.snapshot()["ring"]) == 1
        t[0] = 11.0
        _feed(led, 2.0, n=1)
        assert len(led.snapshot()["ring"]) == 2

    def test_snapshot_is_json_clean(self):
        import json

        led = DecisionLedger(ring_interval_s=0.0)
        _feed(led, 2.0, n=5)
        json.dumps(led.snapshot())  # /debug/verify must serialize it


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------


class TestKnobs:
    def test_env_overrides_win(self, monkeypatch):
        monkeypatch.setenv("CBFT_DECISION_LEDGER", "off")
        assert declib.decision_ledger_default(True) is False
        monkeypatch.setenv("CBFT_DECISION_WINDOW", "128")
        assert declib.decision_window_default(32) == 128
        monkeypatch.setenv("CBFT_DECISION_MAPE_TRIP", "3.5")
        assert declib.decision_mape_trip_default(1.0) == 3.5

    def test_config_values_flow_through(self, monkeypatch):
        monkeypatch.delenv("CBFT_DECISION_LEDGER", raising=False)
        monkeypatch.delenv("CBFT_DECISION_WINDOW", raising=False)
        monkeypatch.delenv("CBFT_DECISION_MAPE_TRIP", raising=False)
        assert declib.decision_ledger_default(False) is False
        assert declib.decision_window_default(32) == 32
        assert declib.decision_mape_trip_default(1.5) == 1.5
        assert declib.decision_window_default(None) == declib.DEFAULT_WINDOW

    def test_malformed_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("CBFT_DECISION_WINDOW", "not-a-number")
        assert declib.decision_window_default(None) == declib.DEFAULT_WINDOW
        monkeypatch.setenv("CBFT_DECISION_MAPE_TRIP", "-2")
        assert (
            declib.decision_mape_trip_default(None)
            == declib.DEFAULT_MAPE_TRIP
        )


# ---------------------------------------------------------------------------
# bench history direction rules (satellite 5b)
# ---------------------------------------------------------------------------


class TestBenchHistoryDecisionDirection:
    @staticmethod
    def _load():
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_history_decisions_test",
            os.path.join(repo, "tools", "bench_history.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_decision_quality_leaves_are_lower_is_better(self):
        bh = self._load()
        for leaf in ("decisions_worst_mape", "decisions_regret_ms",
                     "stages.decisions.decisions_worst_mape",
                     "verify_route_mape"):
            assert bh.direction(leaf) == bh.LOWER_IS_BETTER, leaf
        # booleans / counts stay directionless
        assert bh.direction("profiles_scored") is None


# ---------------------------------------------------------------------------
# chaos staleness rung
# ---------------------------------------------------------------------------


class TestChaosStaleModelRung:
    def test_jitter_trips_watchdog_once_and_rearms(self):
        from cometbft_tpu.crypto.faults import run_chaos_stale_model

        summary = run_chaos_stale_model(seed=11)
        assert summary["ok"] is True
        assert summary["wrong_verdicts"] == 0
        assert summary["trips"] == 1
        assert summary["anomaly_fires"] == 1
        assert summary["incident_dumps"] == 1
        assert summary["rearmed"] is True
        assert summary["trip_cause"] in ("mape", "regret")
        # ISSUE 16: the trip must also roll the PRICED live router back
        # to thresholds exactly once, and recovery must re-admit it
        assert summary["router_rollbacks"] == 1
        assert summary["router_readmits"] == 1
        assert summary["router_live"] == "priced"
        assert summary["router_priced_records"] > 0
