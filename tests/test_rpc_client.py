"""RPC HTTP client + HTTP light provider against a live node.

Model: reference rpc/client/http tests + light/provider/http — the
client's parsed types must round-trip the server's JSON bit-exactly
(header hashes recompute, commits verify).
"""

import base64
import tempfile
import time

import pytest

from cometbft_tpu.cmd.commands import _load_config, main as cli_main
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.light.client import Client as LightClient, TrustOptions
from cometbft_tpu.light.provider import HTTPProvider
from cometbft_tpu.light.store import DBStore
from cometbft_tpu.node import default_new_node
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.rpc.client import HTTPClient, RPCClientError
from cometbft_tpu.libs.net import free_ports as _free_ports


def _now() -> Timestamp:
    ns = time.time_ns()
    return Timestamp(ns // 1_000_000_000, ns % 1_000_000_000)


@pytest.mark.slow
class TestHTTPClientAgainstLiveNode:
    def test_client_parses_and_light_client_verifies(self):
        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "rpc-client-chain"])
            rpc_port, p2p_port = _free_ports(2)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            node = default_new_node(cfg)
            node.start()
            try:
                client = HTTPClient(f"127.0.0.1:{rpc_port}")
                deadline = time.monotonic() + 60
                height = 0
                while time.monotonic() < deadline and height < 4:
                    try:
                        st = client.status()
                        height = int(st["sync_info"]["latest_block_height"])
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert height >= 4

                # typed wrappers work end to end
                res = client.broadcast_tx_commit(b"rc=1")
                assert res["deliver_tx"]["code"] == 0
                q = client.abci_query("/store", b"rc")
                assert base64.b64decode(q["response"]["value"]) == b"1"
                with pytest.raises(RPCClientError):
                    client.call("no_such_method")

                # the HTTP light provider reconstructs light blocks whose
                # header hashes + commits are cryptographically valid:
                # verify height 3 via the light client with trust root @1
                provider = HTTPProvider("rpc-client-chain", f"127.0.0.1:{rpc_port}")
                lb1 = provider.light_block(1)
                # parsed header re-hashes to the chain's real block hash
                chain_b1 = client.block(1)
                assert (
                    lb1.signed_header.header.hash().hex().upper()
                    == chain_b1["block_id"]["hash"]
                )
                lc = LightClient(
                    "rpc-client-chain",
                    TrustOptions(
                        period_ns=10**18,
                        height=1,
                        hash=lb1.signed_header.header.hash(),
                    ),
                    provider,
                    [HTTPProvider("rpc-client-chain", f"127.0.0.1:{rpc_port}")],
                    DBStore(MemDB()),
                )
                verified = lc.verify_light_block_at_height(3, _now())
                assert verified.height == 3
                # consensus params ride the same client
                params = provider.consensus_params(3)
                assert params.block.max_bytes > 0
            finally:
                node.stop()


class TestOpenAPISpec:
    def test_spec_covers_every_route(self):
        from cometbft_tpu.rpc.openapi import spec, to_yaml
        from cometbft_tpu.rpc.server import _ROUTES

        doc = spec()
        assert set(doc["paths"]) == {f"/{m}" for m in _ROUTES}
        for path, item in doc["paths"].items():
            op = item["get"]
            assert op["summary"], path
            assert "200" in op["responses"]
        # the committed YAML is the generator's output (no drift)
        import os

        committed = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "..", "cometbft_tpu", "rpc", "openapi.yaml",
        )
        with open(committed) as f:
            assert f.read() == to_yaml()
