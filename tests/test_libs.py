"""Tests for the support runtime (reference test models: libs/*/… _test.go)."""

import io
import os
import threading
import time

import pytest

from cometbft_tpu.libs import protoio
from cometbft_tpu.libs.autofile import Group
from cometbft_tpu.libs.bits import BitArray
from cometbft_tpu.libs.clist import CList
from cometbft_tpu.libs.db import MemDB, SQLiteDB, prefix_end
from cometbft_tpu.libs.events import EventSwitch
from cometbft_tpu.libs.pubsub import Empty, Server, SubscriptionCancelled, parse_query
from cometbft_tpu.libs.service import AlreadyStartedError, BaseService


class TestService:
    def test_lifecycle(self):
        s = BaseService("svc")
        s.start()
        assert s.is_running()
        with pytest.raises(AlreadyStartedError):
            s.start()
        s.stop()
        assert not s.is_running()
        s.reset()
        s.start()
        assert s.is_running()
        s.stop()


class TestVarint:
    @pytest.mark.parametrize("n", [0, 1, 127, 128, 300, 2**32, 2**63 - 1])
    def test_uvarint_roundtrip(self, n):
        enc = protoio.encode_uvarint(n)
        val, pos = protoio.decode_uvarint(enc)
        assert val == n and pos == len(enc)

    @pytest.mark.parametrize("n", [0, 1, -1, 300, -300, 2**62, -(2**62)])
    def test_signed_roundtrip(self, n):
        enc = protoio.encode_varint(n)
        val, pos = protoio.decode_varint(enc)
        assert val == n and pos == len(enc)

    def test_delimited_stream(self):
        buf = io.BytesIO()
        msgs = [b"hello", b"", b"x" * 300]
        for m in msgs:
            protoio.write_delimited(buf, m)
        buf.seek(0)
        out = [protoio.read_delimited(buf) for _ in msgs]
        assert out == msgs
        with pytest.raises(EOFError):
            protoio.read_delimited(buf)

    def test_known_encodings(self):
        # protobuf reference values
        assert protoio.encode_uvarint(300) == b"\xac\x02"
        assert protoio.encode_varint(-1) == b"\xff" * 9 + b"\x01"


class TestBitArray:
    def test_basic(self):
        ba = BitArray(70)
        assert not ba.get_index(0)
        assert ba.set_index(0, True)
        assert ba.set_index(69, True)
        assert not ba.set_index(70, True)
        assert ba.get_index(0) and ba.get_index(69)
        assert ba.num_true_bits() == 2
        assert ba.true_indices() == [0, 69]

    def test_algebra(self):
        a, b = BitArray(10), BitArray(10)
        a.set_index(1, True)
        a.set_index(3, True)
        b.set_index(3, True)
        b.set_index(5, True)
        assert (a.or_(b)).true_indices() == [1, 3, 5]
        assert (a.and_(b)).true_indices() == [3]
        assert (a.sub(b)).true_indices() == [1]
        assert (a.not_()).num_true_bits() == 8

    def test_full_empty(self):
        ba = BitArray(5)
        assert ba.is_empty() and not ba.is_full()
        for i in range(5):
            ba.set_index(i, True)
        assert ba.is_full()

    def test_elems_roundtrip(self):
        ba = BitArray(130)
        ba.set_index(0, True)
        ba.set_index(129, True)
        ba2 = BitArray.from_elems(130, ba.elems())
        assert ba == ba2

    def test_pick_random(self):
        ba = BitArray(64)
        assert ba.pick_random() is None
        ba.set_index(17, True)
        assert ba.pick_random() == 17


class TestCList:
    def test_push_iterate_remove(self):
        cl = CList()
        elems = [cl.push_back(i) for i in range(5)]
        assert len(cl) == 5
        assert [e.value for e in cl] == list(range(5))
        cl.remove(elems[2])
        assert [e.value for e in cl] == [0, 1, 3, 4]
        assert elems[2].removed

    def test_wait_semantics(self):
        cl = CList()
        got = []

        def reader():
            e = cl.front_wait(2.0)
            while e is not None and len(got) < 3:
                got.append(e.value)
                nxt = e.next_wait(2.0)
                e = nxt

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.05)
        for i in range(3):
            cl.push_back(i)
        t.join(3.0)
        assert got == [0, 1, 2]


class TestEvents:
    def test_fire(self):
        sw = EventSwitch()
        seen = []
        sw.add_listener_for_event("a", "ev1", lambda d: seen.append(("a", d)))
        sw.add_listener_for_event("b", "ev1", lambda d: seen.append(("b", d)))
        sw.fire_event("ev1", 42)
        assert seen == [("a", 42), ("b", 42)]
        sw.remove_listener("a")
        sw.fire_event("ev1", 43)
        assert seen[-1] == ("b", 43)


class TestQuery:
    def test_parse_and_match(self):
        q = parse_query("tm.event='NewBlock' AND tx.height>5")
        assert q.matches({"tm.event": ["NewBlock"], "tx.height": ["10"]})
        assert not q.matches({"tm.event": ["NewBlock"], "tx.height": ["5"]})
        assert not q.matches({"tm.event": ["Tx"], "tx.height": ["10"]})

    def test_ops(self):
        assert parse_query("a.b='x'").matches({"a.b": ["y", "x"]})
        assert parse_query("a.b CONTAINS 'ell'").matches({"a.b": ["hello"]})
        assert parse_query("a.b EXISTS").matches({"a.b": [""]})
        assert not parse_query("a.b EXISTS").matches({"c": ["1"]})
        assert parse_query("a.h<=3").matches({"a.h": ["3"]})
        assert parse_query("a.h>=3").matches({"a.h": ["3"]})

    def test_empty(self):
        assert Empty().matches({"anything": ["x"]})

    def test_bad_queries(self):
        for bad in ["AND", "a.b=", "a.b = 'x' AND", "=3"]:
            with pytest.raises(ValueError):
                parse_query(bad)


class TestPubSub:
    def test_subscribe_publish(self):
        s = Server()
        s.start()
        sub = s.subscribe("client1", parse_query("tm.event='Tx'"), out_capacity=4)
        s.publish_with_events("data1", {"tm.event": ["Tx"]})
        s.publish_with_events("data2", {"tm.event": ["NewBlock"]})
        msg = sub.next(timeout=1.0)
        assert msg.data == "data1"
        assert sub.try_next() is None
        s.stop()

    def test_unsubscribe_cancels(self):
        s = Server()
        s.start()
        q = parse_query("a.b='c'")
        sub = s.subscribe("c1", q)
        s.unsubscribe("c1", q)
        with pytest.raises(SubscriptionCancelled):
            sub.next(timeout=0.2)

    def test_slow_client_evicted(self):
        s = Server()
        s.start()
        sub = s.subscribe("slow", Empty(), out_capacity=0)
        s.publish_with_events("m1", {"x": ["1"]})
        s.publish_with_events("m2", {"x": ["1"]})  # queue full → evict
        # drains the buffered message then reports cancellation
        assert sub.next(timeout=1.0).data == "m1"
        with pytest.raises(SubscriptionCancelled):
            sub.next(timeout=1.0)


class TestAutofile:
    def test_write_read_rotate(self, tmp_path):
        head = str(tmp_path / "wal")
        g = Group(head, head_size_limit=100, group_size_limit=100000)
        g.write(b"a" * 80)
        g.flush_and_sync()
        g.check_head_size_limit()  # under limit, no rotation
        g.write(b"b" * 40)
        g.check_head_size_limit()  # now over → rotated
        g.write(b"c" * 10)
        g.flush()
        with g.reader() as r:
            data = r.read()
        assert data == b"a" * 80 + b"b" * 40 + b"c" * 10
        assert g.min_max_index() == (1, 1)
        g.close()

    def test_reader_snapshot_survives_concurrent_rotation(self, tmp_path):
        """A reader opened before a rotation must see the group's content
        as of the snapshot — the rename must not swap the (new, empty)
        head in under it. This is the WAL-replay-during-rotation race:
        the flush loop now rotates in production, and replay reads the
        group while it runs."""
        head = str(tmp_path / "wal")
        g = Group(head, head_size_limit=10_000)
        g.write(b"A" * 100)
        g.flush()
        r = g.reader()
        assert r.read(10) == b"A" * 10  # reader is mid-head
        g.rotate_file()  # head renamed; fresh empty head created
        g.write(b"B" * 50)
        g.flush()
        assert r.read() == b"A" * 90  # snapshot complete, no Bs, no loss
        r.close()
        with g.reader() as r2:  # a fresh reader sees everything
            assert r2.read() == b"A" * 100 + b"B" * 50
        g.close()

    def test_group_size_limit_prunes(self, tmp_path):
        head = str(tmp_path / "wal")
        g = Group(head, head_size_limit=50, group_size_limit=120)
        for _ in range(6):
            g.write(b"z" * 50)
            g.check_head_size_limit()
        paths = g.all_paths()
        total = sum(os.path.getsize(p) for p in paths)
        assert total <= 120 + 50
        g.close()

    def test_write_retries_reopen_after_failed_rotation(self, tmp_path):
        """A double OSError during rotation parks the group headless;
        the NEXT write must retry the reopen (one transient ENOSPC must
        not turn every later WAL write into a dead assert), surfacing
        OSError only while the reopen keeps failing."""
        head = str(tmp_path / "wal")
        g = Group(head, head_size_limit=10_000)
        g.write(b"before")
        g.flush()
        real_open = g._open_head

        def boom():
            raise OSError("disk full")

        g._open_head = boom
        try:
            with pytest.raises(OSError):
                g.rotate_file()  # rename ok, reopen fails twice → headless
            assert g._head is None
            # reopen still failing: the typed error, not AssertionError
            with pytest.raises(OSError):
                g.write(b"lost?")
        finally:
            g._open_head = real_open
        # fs recovered: the very next write reopens and lands
        assert g.write(b"after") == 5
        g.flush()
        with g.reader() as r:
            assert r.read() == b"before" + b"after"
        g.close()


class TestDB:
    @pytest.mark.parametrize("make", [lambda p: MemDB(), lambda p: SQLiteDB(str(p / "x.db"))])
    def test_crud_and_iteration(self, tmp_path, make):
        db = make(tmp_path)
        db.set(b"b", b"2")
        db.set(b"a", b"1")
        db.set(b"c", b"3")
        assert db.get(b"b") == b"2"
        assert db.has(b"a")
        db.delete(b"b")
        assert db.get(b"b") is None
        assert list(db.iterator()) == [(b"a", b"1"), (b"c", b"3")]
        assert list(db.reverse_iterator()) == [(b"c", b"3"), (b"a", b"1")]
        db.close()

    def test_prefix_iteration(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "p.db"))
        for k in [b"H:1", b"H:2", b"P:1", b"H:3"]:
            db.set(k, k)
        assert [k for k, _ in db.prefix_iterator(b"H:")] == [b"H:1", b"H:2", b"H:3"]
        db.close()

    def test_batch_atomicity(self, tmp_path):
        db = SQLiteDB(str(tmp_path / "b.db"))
        db.set(b"x", b"old")
        b = db.new_batch()
        b.set(b"y", b"1")
        b.delete(b"x")
        assert db.get(b"x") == b"old"  # not applied yet
        b.write()
        assert db.get(b"x") is None and db.get(b"y") == b"1"
        db.close()

    def test_prefix_end(self):
        assert prefix_end(b"ab") == b"ac"
        assert prefix_end(b"a\xff") == b"b"
        assert prefix_end(b"\xff\xff") is None

    def test_sqlite_persistence(self, tmp_path):
        path = str(tmp_path / "persist.db")
        db = SQLiteDB(path)
        db.set_sync(b"k", b"v")
        db.close()
        db2 = SQLiteDB(path)
        assert db2.get(b"k") == b"v"
        db2.close()


class TestAsyncParallel:
    def test_results_in_order_and_concurrent(self):
        import threading
        import time as _time

        from cometbft_tpu.libs.async_ import first_error, parallel

        barrier = threading.Barrier(2, timeout=5)

        def a():
            barrier.wait()  # deadlocks unless b runs CONCURRENTLY
            return "a"

        def b():
            barrier.wait()
            return "b"

        t0 = _time.monotonic()
        results, ok = parallel(a, b)
        assert ok
        assert [r.value for r in results] == ["a", "b"]
        assert first_error(results) is None
        assert _time.monotonic() - t0 < 5

    def test_exception_captured_not_raised(self):
        from cometbft_tpu.libs.async_ import first_error, parallel

        def boom():
            raise RuntimeError("x")

        results, ok = parallel(lambda: 1, boom)
        assert not ok
        assert results[0].value == 1
        assert isinstance(results[1].error, RuntimeError)
        assert isinstance(first_error(results), RuntimeError)


class TestThrottleTimer:
    def test_coalesces_and_throttles(self):
        import time as _time

        from cometbft_tpu.libs.timer import ThrottleTimer

        fires = []
        t = ThrottleTimer("t", 0.15, lambda: fires.append(_time.monotonic()))
        try:
            for _ in range(20):
                t.set()  # storm of sets → coalesced
            _time.sleep(0.1)
            assert len(fires) == 1  # first fire is immediate
            for _ in range(20):
                t.set()
            _time.sleep(0.3)
            assert len(fires) == 2  # second waits out the interval
        finally:
            t.stop()

    def test_unset_cancels(self):
        import time as _time

        from cometbft_tpu.libs.timer import ThrottleTimer

        fires = []
        t = ThrottleTimer("t", 10.0, lambda: fires.append(1))
        try:
            t.set()          # fires immediately (no prior fire)
            _time.sleep(0.1)
            t.set()          # pending for +10s
            t.unset()        # cancelled
            _time.sleep(0.2)
            assert len(fires) == 1
        finally:
            t.stop()
