"""Types-layer tests, including the reference's golden sign-bytes vectors
(types/vote_test.go:60 TestVoteSignBytesTestVectors) — byte-for-byte parity
with gogoproto canonical encodings is consensus-critical.
"""

import pytest

from cometbft_tpu.proto.gogo import Timestamp, ZERO_TIME
from cometbft_tpu.types import (
    Block,
    BlockID,
    Commit,
    CommitSig,
    Header,
    PartSet,
    PartSetHeader,
    Proposal,
    Validator,
    ValidatorSet,
    Vote,
)
from cometbft_tpu.types.block import (
    BLOCK_ID_FLAG_COMMIT,
    BLOCK_ID_FLAG_NIL,
    make_block,
)
from cometbft_tpu.types.part_set import Part
from cometbft_tpu.types.params import ConsensusParams
from cometbft_tpu.types.test_util import (
    deterministic_validator_set,
    make_block_id,
    make_commit,
)
from cometbft_tpu.types.validator_set import (
    ErrNotEnoughVotingPowerSigned,
    Fraction,
)
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
)
from cometbft_tpu.types.tx import Txs


class TestVoteSignBytesGoldenVectors:
    """The exact vectors from types/vote_test.go:60."""

    def test_empty_vote(self):
        v = Vote()
        want = bytes(
            [0xD, 0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
        )
        assert v.sign_bytes("") == want

    def test_precommit(self):
        v = Vote(height=1, round=1, type=SIGNED_MSG_TYPE_PRECOMMIT)
        want = bytes(
            [0x21, 0x8, 0x2, 0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x19]
            + [0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0]
            + [0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
        )
        assert v.sign_bytes("") == want

    def test_prevote(self):
        v = Vote(height=1, round=1, type=SIGNED_MSG_TYPE_PREVOTE)
        want = bytes(
            [0x21, 0x8, 0x1, 0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x19]
            + [0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0]
            + [0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
        )
        assert v.sign_bytes("") == want

    def test_no_type(self):
        v = Vote(height=1, round=1)
        want = bytes(
            [0x1F, 0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x19]
            + [0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0]
            + [0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
        )
        assert v.sign_bytes("") == want

    def test_with_chain_id(self):
        v = Vote(height=1, round=1)
        want = bytes(
            [0x2E, 0x11, 0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x19]
            + [0x1, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0, 0x0]
            + [0x2A, 0xB, 0x8, 0x80, 0x92, 0xB8, 0xC3, 0x98, 0xFE, 0xFF, 0xFF, 0xFF, 0x1]
            + [0x32, 0xD]
            + list(b"test_chain_id")
        )
        assert v.sign_bytes("test_chain_id") == want

    def test_vote_proposal_not_eq(self):
        """canonical.go invariant: a vote and proposal with the same fields
        produce different sign bytes (types/vote_test.go TestVoteProposalNotEq)."""
        bid = make_block_id()
        v = Vote(height=1, round=1, block_id=bid, timestamp=ZERO_TIME)
        p = Proposal(height=1, round=1, block_id=bid, timestamp=ZERO_TIME)
        assert v.sign_bytes("chain") != p.sign_bytes("chain")


class TestRoundTrips:
    def test_vote_roundtrip(self):
        v = Vote(
            type=SIGNED_MSG_TYPE_PRECOMMIT,
            height=12345,
            round=2,
            block_id=make_block_id(),
            timestamp=Timestamp(1700000000, 123456789),
            validator_address=b"\xaa" * 20,
            validator_index=3,
            signature=b"\x55" * 64,
        )
        assert Vote.decode(v.encode()) == v

    def test_header_roundtrip(self):
        h = Header(
            chain_id="test",
            height=7,
            time=Timestamp(1700000000, 5),
            last_block_id=make_block_id(),
            validators_hash=b"\x01" * 32,
            next_validators_hash=b"\x02" * 32,
            consensus_hash=b"\x03" * 32,
            app_hash=b"\x04" * 32,
            proposer_address=b"\x05" * 20,
        )
        assert Header.decode(h.encode()) == h
        assert h.hash() is not None
        assert Header().hash() is None  # no validators hash -> nil

    def test_commit_roundtrip(self):
        _, privs = deterministic_validator_set(4)
        vs, privs = deterministic_validator_set(4)
        commit = make_commit(make_block_id(), 5, 1, vs, privs, "chain")
        commit2 = Commit.decode(commit.encode())
        assert commit2.height == 5 and commit2.round == 1
        assert commit2.hash() == commit.hash()

    def test_proposal_roundtrip(self):
        p = Proposal(
            height=3,
            round=1,
            pol_round=0,
            block_id=make_block_id(),
            timestamp=Timestamp(1000, 1),
            signature=b"\x11" * 64,
        )
        assert Proposal.decode(p.encode()) == p

    def test_params_roundtrip_and_hash(self):
        cp = ConsensusParams()
        assert ConsensusParams.decode(cp.encode()) == cp
        assert len(cp.hash()) == 32
        cp.validate_basic()

    def test_validator_set_roundtrip(self):
        vs, _ = deterministic_validator_set(5)
        vs2 = ValidatorSet.decode(vs.encode())
        assert vs2.hash() == vs.hash()
        assert [v.address for v in vs2.validators] == [
            v.address for v in vs.validators
        ]


class TestValidatorSet:
    def test_proposer_rotation_is_fair(self):
        vs, _ = deterministic_validator_set(4, power=100)
        seen = {}
        for _ in range(40):
            p = vs.get_proposer()
            seen[p.address] = seen.get(p.address, 0) + 1
            vs.increment_proposer_priority(1)
        # equal power -> equal share (10 each over 40 rounds)
        assert all(c == 10 for c in seen.values())

    def test_proposer_weighted_rotation(self):
        from cometbft_tpu.crypto import ed25519 as edlib
        from cometbft_tpu.types.validator import Validator as V

        k1 = edlib.gen_priv_key_from_secret(b"a").pub_key()
        k2 = edlib.gen_priv_key_from_secret(b"b").pub_key()
        vs = ValidatorSet([V.new(k1, 3), V.new(k2, 1)])
        counts = {k1.address(): 0, k2.address(): 0}
        for _ in range(40):
            counts[vs.get_proposer().address] += 1
            vs.increment_proposer_priority(1)
        assert counts[k1.address()] == 30
        assert counts[k2.address()] == 10

    def test_update_with_change_set(self):
        from cometbft_tpu.crypto import ed25519 as edlib
        from cometbft_tpu.types.validator import Validator as V

        vs, _ = deterministic_validator_set(3, power=10)
        old_hash = vs.hash()
        new_key = edlib.gen_priv_key_from_secret(b"new").pub_key()
        vs.update_with_change_set([V.new(new_key, 50)])
        assert vs.size() == 4
        assert vs.hash() != old_hash
        assert vs.total_voting_power() == 80
        # power-desc order puts the 50-power validator first
        assert vs.validators[0].address == new_key.address()
        # removal
        vs.update_with_change_set([V.new(new_key, 0)])
        assert vs.size() == 3
        assert vs.total_voting_power() == 30

    def test_duplicate_changes_rejected(self):
        from cometbft_tpu.crypto import ed25519 as edlib
        from cometbft_tpu.types.validator import Validator as V

        vs, _ = deterministic_validator_set(3)
        k = edlib.gen_priv_key_from_secret(b"dup").pub_key()
        with pytest.raises(ValueError, match="duplicate"):
            vs.update_with_change_set([V.new(k, 5), V.new(k, 6)])


class TestVerifyCommit:
    CHAIN = "test_chain"

    def _setup(self, n=10):
        vs, privs = deterministic_validator_set(n)
        block_id = make_block_id()
        commit = make_commit(block_id, 5, 0, vs, privs, self.CHAIN)
        return vs, privs, block_id, commit

    def test_verify_commit_ok(self):
        vs, _, block_id, commit = self._setup()
        vs.verify_commit(self.CHAIN, block_id, 5, commit)
        vs.verify_commit_light(self.CHAIN, block_id, 5, commit)
        vs.verify_commit_light_trusting(self.CHAIN, commit, Fraction(1, 3))

    def test_wrong_height(self):
        vs, _, block_id, commit = self._setup()
        with pytest.raises(ValueError, match="wrong height"):
            vs.verify_commit(self.CHAIN, block_id, 6, commit)

    def test_wrong_block_id(self):
        vs, _, block_id, commit = self._setup()
        other = make_block_id(b"\x09" * 32)
        with pytest.raises(ValueError, match="wrong block ID"):
            vs.verify_commit(self.CHAIN, other, 5, commit)

    def test_wrong_set_size(self):
        vs, _, block_id, commit = self._setup()
        commit.signatures.append(CommitSig.absent())
        with pytest.raises(ValueError, match="wrong set size"):
            vs.verify_commit(self.CHAIN, block_id, 5, commit)

    def test_bad_signature_detected(self):
        vs, _, block_id, commit = self._setup()
        sig = commit.signatures[3].signature
        commit.signatures[3].signature = sig[:-1] + bytes([sig[-1] ^ 1])
        with pytest.raises(ValueError, match=r"wrong signature \(#3\)"):
            vs.verify_commit(self.CHAIN, block_id, 5, commit)

    def test_insufficient_power(self):
        from cometbft_tpu.types.test_util import make_vote

        vs, privs, block_id, commit = self._setup(n=10)
        # 4 of 10 equal-power validators genuinely voted nil:
        # tallied 600 <= needed (2/3 of 1000 = 666)
        for i in range(4):
            nil_vote = make_vote(
                privs[i], self.CHAIN, i, 5, 0, SIGNED_MSG_TYPE_PRECOMMIT, BlockID()
            )
            commit.signatures[i] = nil_vote.to_commit_sig()
        with pytest.raises(ErrNotEnoughVotingPowerSigned):
            vs.verify_commit(self.CHAIN, block_id, 5, commit)

    def test_light_ignores_bad_sig_after_quorum(self):
        """VerifyCommitLight early-exits at +2/3: a bad sig after quorum is
        NOT checked (validator_set.go:758-761), unlike VerifyCommit."""
        vs, _, block_id, commit = self._setup(n=10)
        sig = commit.signatures[9].signature
        commit.signatures[9].signature = sig[:-1] + bytes([sig[-1] ^ 1])
        vs.verify_commit_light(self.CHAIN, block_id, 5, commit)  # passes
        with pytest.raises(ValueError, match=r"wrong signature \(#9\)"):
            vs.verify_commit(self.CHAIN, block_id, 5, commit)

    def test_light_trusting_different_valset(self):
        """Trusting verification uses address lookup — works when the
        trusted set only overlaps the commit's set."""
        vs, privs, block_id, commit = self._setup(n=10)
        # trusted set = 6 of the 10 validators
        subset = ValidatorSet([vs.validators[i].copy() for i in range(6)])
        subset.verify_commit_light_trusting(self.CHAIN, commit, Fraction(1, 3))

    def test_absent_sigs_ok(self):
        vs, _, block_id, commit = self._setup(n=10)
        commit.signatures[0] = CommitSig.absent()
        vs.verify_commit(self.CHAIN, block_id, 5, commit)


class TestPartSet:
    def test_split_and_reassemble(self):
        data = bytes(range(256)) * 1000  # 256000 bytes -> 4 parts at 64KiB
        ps = PartSet.from_data(data)
        assert ps.total() == 4
        assert ps.is_complete()
        assert ps.get_reader() == data
        # rebuild from header + parts (gossip path)
        ps2 = PartSet.from_header(ps.header())
        for i in range(ps.total()):
            added, err = ps2.add_part(ps.get_part(i))
            assert added, err
        assert ps2.is_complete()
        assert ps2.get_reader() == data

    def test_bad_part_rejected(self):
        data = b"z" * 100000
        ps = PartSet.from_data(data)
        ps2 = PartSet.from_header(ps.header())
        part = ps.get_part(0)
        bad = Part(part.index, part.bytes_[:-1] + b"\x00", part.proof)
        added, err = ps2.add_part(bad)
        assert not added and "invalid part proof" in err

    def test_duplicate_part(self):
        ps = PartSet.from_data(b"q" * 1000)
        added, err = ps.add_part(ps.get_part(0))
        assert not added and err is None


class TestBlock:
    def test_block_hash_and_validate(self):
        vs, privs = deterministic_validator_set(4)
        block_id = make_block_id()
        commit = make_commit(block_id, 9, 0, vs, privs, "chain")
        block = make_block(10, [b"tx1", b"tx2"], commit, [])
        block.header.validators_hash = vs.hash()
        block.header.next_validators_hash = vs.hash()
        block.header.consensus_hash = b"\x01" * 32
        block.header.proposer_address = vs.validators[0].address
        block.header.last_block_id = block_id
        block.fill_header()
        assert block.hash() is not None
        block.validate_basic()
        # roundtrip
        b2 = Block.decode(block.encode())
        assert b2.hash() == block.hash()
        assert b2.data.txs == block.data.txs

    def test_txs_hash_is_merkle_of_tx_hashes(self):
        from cometbft_tpu.crypto import merkle
        from cometbft_tpu.types.tx import Tx

        txs = Txs([b"a", b"b"])
        assert txs.hash() == merkle.hash_from_byte_slices(
            [Tx(b"a").hash(), Tx(b"b").hash()]
        )

    def test_compute_proto_size_for_txs(self):
        """types/tx.go ComputeProtoSizeForTxs: per tx one tag byte, a
        length varint, then the payload — the size mempool reaping and
        MaxDataBytes budgeting must agree on."""
        from cometbft_tpu.types.tx import (
            compute_proto_size_for_txs,
            proto_framed_size,
        )

        assert compute_proto_size_for_txs([]) == 0
        assert compute_proto_size_for_txs([b"ab"]) == 4  # 1 + 1 + 2
        big = b"x" * 300  # 300 needs a 2-byte varint
        assert compute_proto_size_for_txs([big, b"ab"]) == (1 + 2 + 300) + 4
        assert proto_framed_size(300) == 1 + 2 + 300

    def test_commit_to_vote_set_roundtrip(self):
        from cometbft_tpu.types.block import commit_to_vote_set

        vs, privs = deterministic_validator_set(4)
        block_id = make_block_id()
        commit = make_commit(block_id, 3, 0, vs, privs, "chain")
        vote_set = commit_to_vote_set("chain", commit, vs)
        maj, ok = vote_set.two_thirds_majority()
        assert ok and maj == block_id


class TestVoteSetSemantics:
    """Reference-exact equivocation and commit-construction semantics
    (vote_set.go addVerifiedVote / MakeCommit)."""

    CHAIN = "vs_chain"

    def _setup(self, n=4):
        from cometbft_tpu.types.vote_set import VoteSet

        vs, privs = deterministic_validator_set(n)
        vset = VoteSet(self.CHAIN, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, vs)
        return vs, privs, vset

    def test_conflicting_vote_raises(self):
        from cometbft_tpu.types.test_util import make_vote
        from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes

        _, privs, vset = self._setup()
        a = make_block_id(b"\x0a" * 32)
        b = make_block_id(b"\x0b" * 32)
        v1 = make_vote(privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a)
        added, _ = vset.add_vote(v1)
        assert added
        v2 = make_vote(privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, b)
        with pytest.raises(ErrVoteConflictingVotes) as ei:
            vset.add_vote(v2)
        assert ei.value.added is False
        assert ei.value.vote_a.block_id == a

    def test_conflicting_vote_tracked_for_peer_maj23_still_raises(self):
        from cometbft_tpu.types.test_util import make_vote
        from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes

        _, privs, vset = self._setup()
        a = make_block_id(b"\x0a" * 32)
        b = make_block_id(b"\x0b" * 32)
        v1 = make_vote(privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a)
        vset.add_vote(v1)
        vset.set_peer_maj23("peer1", b)
        v2 = make_vote(privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, b)
        with pytest.raises(ErrVoteConflictingVotes) as ei:
            vset.add_vote(v2)
        # tracked under the peer-claimed block -> added=True, still an error
        assert ei.value.added is True
        assert vset.bit_array_by_block_id(b).get_index(0)

    def test_non_deterministic_signature_rejected(self):
        from cometbft_tpu.types.test_util import make_vote

        _, privs, vset = self._setup()
        a = make_block_id(b"\x0a" * 32)
        v1 = make_vote(privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a)
        vset.add_vote(v1)
        # same vote content, different timestamp -> different signature
        v2 = make_vote(
            privs[0], self.CHAIN, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a,
            timestamp=Timestamp(123, 0),
        )
        added, err = vset.add_vote(v2)
        assert not added and "non-deterministic" in (err or "")
        # identical vote -> plain duplicate
        added, err = vset.add_vote(v1)
        assert not added and err is None

    def test_make_commit_excludes_other_block_sigs(self):
        from cometbft_tpu.types.test_util import make_vote
        from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes

        vs, privs, vset = self._setup(4)
        a = make_block_id(b"\x0a" * 32)
        b = make_block_id(b"\x0b" * 32)
        # validator 3 votes for block B first
        vset.add_vote(make_vote(privs[3], self.CHAIN, 3, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, b))
        for i in range(3):
            vset.add_vote(make_vote(privs[i], self.CHAIN, i, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a))
        maj, ok = vset.two_thirds_majority()
        assert ok and maj == a
        commit = vset.make_commit()
        # validator 3's B-vote must be excluded (absent), not kept
        assert commit.signatures[3].is_absent()
        vs.verify_commit(self.CHAIN, a, 1, commit)

    def test_conflicting_vote_for_maj23_replaces_master(self):
        from cometbft_tpu.types.test_util import make_vote
        from cometbft_tpu.types.vote_set import ErrVoteConflictingVotes

        vs, privs, vset = self._setup(4)
        a = make_block_id(b"\x0a" * 32)
        b = make_block_id(b"\x0b" * 32)
        # validator 3 votes B, then 3 validators reach maj23 on A
        vset.add_vote(make_vote(privs[3], self.CHAIN, 3, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, b))
        for i in range(3):
            vset.add_vote(make_vote(privs[i], self.CHAIN, i, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a))
        # now validator 3 also votes A (the maj23 block): conflict error
        # (added=False, vote_set.go:249 returns before by-block tracking) but
        # the master list is replaced so MakeCommit includes their signature
        with pytest.raises(ErrVoteConflictingVotes) as ei:
            vset.add_vote(make_vote(privs[3], self.CHAIN, 3, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT, a))
        assert ei.value.added is False
        commit = vset.make_commit()
        assert not commit.signatures[3].is_absent()
        vs.verify_commit(self.CHAIN, a, 1, commit)


class TestEvidenceHashable:
    def test_evidence_set_semantics(self):
        from cometbft_tpu.types.evidence import DuplicateVoteEvidence

        e1 = DuplicateVoteEvidence(total_voting_power=10)
        e2 = DuplicateVoteEvidence(total_voting_power=10)
        assert e1 == e2 and len({e1, e2}) == 1
