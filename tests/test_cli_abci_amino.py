"""abci console command + amino-compatible JSON.

Model: reference abci/tests/test_cli (echo/info/deliver_tx/commit/query
against a socket app) and libs/json (registered type tags round-trip).
"""

import threading
import time

import pytest

from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.abci.server import SocketServer
from cometbft_tpu.cmd.commands import main as cli_main
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.libs import amino_json

from cometbft_tpu.libs.net import free_ports


class TestAbciCLI:
    def test_console_commands_against_socket_app(self, capsys):
        (port,) = free_ports(1)
        addr = f"tcp://127.0.0.1:{port}"
        server = SocketServer(addr, KVStoreApplication())
        server.start()
        time.sleep(0.2)
        try:
            assert cli_main(["abci", "echo", "hello", "--address", addr]) == 0
            assert capsys.readouterr().out.strip() == "hello"

            assert cli_main(
                ["abci", "deliver_tx", "cli=works", "--address", addr]
            ) == 0
            out = capsys.readouterr().out
            assert '"code": 0' in out

            assert cli_main(["abci", "commit", "--address", addr]) == 0
            capsys.readouterr()

            assert cli_main(
                ["abci", "query", "cli", "--address", addr]
            ) == 0
            out = capsys.readouterr().out
            assert '"value": "works"' in out

            assert cli_main(["abci", "info", "--address", addr]) == 0
            out = capsys.readouterr().out
            assert '"last_block_height": 1' in out
        finally:
            server.stop()


class TestAminoJSON:
    def test_registered_key_roundtrip(self):
        k = ed25519.gen_priv_key()
        doc = {"address": k.pub_key().address().hex(), "pub_key": k.pub_key()}
        s = amino_json.marshal(doc)
        assert '"type": "tendermint/PubKeyEd25519"' in s
        back = amino_json.unmarshal(s)
        assert back["pub_key"].bytes() == k.pub_key().bytes()
        assert back["address"] == doc["address"]

    def test_nested_structures_and_bytes(self):
        k = ed25519.gen_priv_key()
        s = amino_json.marshal(
            {"vals": [{"pk": k.pub_key(), "power": 3}], "blob": b"\x01\x02"}
        )
        back = amino_json.unmarshal(s)
        assert back["vals"][0]["pk"].bytes() == k.pub_key().bytes()
        # plain bytes b64-encode without a tag (one-way, like the reference
        # treats []byte)
        assert back["blob"] == "AQI="

    def test_privkey_tag(self):
        k = ed25519.gen_priv_key()
        back = amino_json.unmarshal(amino_json.marshal(k))
        assert back.bytes() == k.bytes()
        assert back.pub_key().bytes() == k.pub_key().bytes()

    def test_unknown_tags_pass_through(self):
        back = amino_json.unmarshal(
            '{"type": "unregistered/Thing", "value": 1}'
        )
        assert back == {"type": "unregistered/Thing", "value": 1}

    def test_duplicate_registration_rejected(self):
        with pytest.raises(ValueError):
            amino_json.register_type(
                dict, "tendermint/PubKeyEd25519", lambda x: x, lambda x: x
            )
