"""Verify-as-a-service (PR 17): frame codec, cross-client demux,
disconnect containment, malformed-frame refusal, and the keystore
generation handshake.

The RPC payload IS the PR 13 wire format — compact 128 B/lane rows (or
96 B rsh + 4 B index when a registered valset covers the request), so
bytes-per-lane over the socket is exactly the device wire's. These
tests pin the frame codec against truncation/garbage at every offset,
prove one merged flush fans verdicts back out to the right client, and
walk the stale-generation resync ladder end to end over a real Unix
socket. Runs on the virtual CPU mesh (conftest.py)."""

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import service as svc
from cometbft_tpu.crypto.scheduler import VerifyScheduler

_LEN = struct.Struct("<I")


def _batch(n, tag=b"svc", bad=()):
    """(pk, msg, sig) triples; lanes in ``bad`` get a corrupted sig."""
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    items = []
    for i, k in enumerate(keys):
        msg = tag + b" msg %d" % i
        sig = k.sign(msg)
        if i in bad:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 0x01])
        items.append((k.pub_key(), msg, sig))
    return items


def _expected(items):
    return [
        ed.PubKeyEd25519(svc._pk_bytes(pk)).verify_signature(m, s)
        for pk, m, s in items
    ]


# ---------------------------------------------------------------------------
# frame codec: round-trip properties + typed refusal of garbage
# ---------------------------------------------------------------------------


class TestFrameCodec:
    def test_header_is_40_bytes(self):
        assert svc.HEADER_BYTES == 40

    @pytest.mark.parametrize("ftype", [
        svc.FT_HELLO, svc.FT_CLIENT_HELLO, svc.FT_REQ, svc.FT_RESP,
        svc.FT_ERR, svc.FT_REGISTER, svc.FT_REGISTERED,
    ])
    @pytest.mark.parametrize("nbytes", [0, 1, 100, 128, 4097])
    def test_round_trip_every_field(self, ftype, nbytes):
        payload = bytes((i * 7 + ftype) % 256 for i in range(nbytes))
        vid = bytes(range(16))
        buf = svc.encode_frame(
            ftype, qclass=3, kind=svc.KIND_INDEXED, req_id=2**63 + 9,
            n_lanes=2**31 + 1, generation=0xDEADBEEF, valset_id=vid,
            payload=payload,
        )
        (length,) = _LEN.unpack(buf[:4])
        assert length == len(buf) - 4 == svc.HEADER_BYTES + nbytes
        f = svc.decode_frame(buf[4:])
        assert f.ftype == ftype
        assert f.qclass == 3
        assert f.kind == svc.KIND_INDEXED
        assert f.req_id == 2**63 + 9
        assert f.n_lanes == 2**31 + 1
        assert f.generation == 0xDEADBEEF
        assert f.valset_id == vid
        assert f.payload == payload

    def test_valset_id_pads_and_truncates_to_16(self):
        f = svc.decode_frame(svc.encode_frame(
            svc.FT_REQ, valset_id=b"ab",
        )[4:])
        assert f.valset_id == b"ab" + b"\x00" * 14
        f = svc.decode_frame(svc.encode_frame(
            svc.FT_REQ, valset_id=b"x" * 40,
        )[4:])
        assert f.valset_id == b"x" * 16

    def test_bad_magic_is_typed_malformed(self):
        buf = bytearray(svc.encode_frame(svc.FT_REQ)[4:])
        buf[:4] = b"NOPE"
        with pytest.raises(svc.FrameError) as ei:
            svc.decode_frame(bytes(buf))
        assert ei.value.code == svc.ERR_MALFORMED

    def test_future_version_is_typed_bad_version(self):
        buf = bytearray(svc.encode_frame(svc.FT_REQ)[4:])
        buf[4] = svc.VERSION + 1
        with pytest.raises(svc.FrameError) as ei:
            svc.decode_frame(bytes(buf))
        assert ei.value.code == svc.ERR_BAD_VERSION

    def test_every_short_header_prefix_is_typed_malformed(self):
        whole = svc.encode_frame(svc.FT_REQ, payload=b"\x01" * 8)[4:]
        for cut in range(svc.HEADER_BYTES):
            with pytest.raises(svc.FrameError) as ei:
                svc.decode_frame(whole[:cut])
            assert ei.value.code == svc.ERR_MALFORMED, cut

    def test_req_payload_bytes_pins_the_wire_cost(self):
        for n in (1, 7, 64, 4096):
            assert svc.req_payload_bytes(svc.KIND_COMPACT, n) == 128 * n
            assert svc.req_payload_bytes(svc.KIND_INDEXED, n) == 100 * n
        with pytest.raises(svc.FrameError):
            svc.req_payload_bytes(9, 1)

    def test_parse_address_schemes(self):
        assert svc.parse_address("unix:///tmp/x.sock") == (
            "unix", "/tmp/x.sock"
        )
        assert svc.parse_address("tcp://127.0.0.1:7777") == (
            "tcp", ("127.0.0.1", 7777)
        )
        assert svc.parse_address("/tmp/bare.sock") == (
            "unix", "/tmp/bare.sock"
        )
        # an unrecognized scheme must not fall through to the bare-path
        # branch just because it contains slashes
        for bad in ("ftp://nope", "grpc://host:1", "unix://", "tcp://x",
                    "tcp://x:notaport", "justaname"):
            with pytest.raises(ValueError):
                svc.parse_address(bad)

    def test_error_payload_round_trip(self):
        for code, msg in [
            (svc.ERR_MALFORMED, "short frame"),
            (svc.ERR_STALE_GENERATION, "gen 3 != 4"),
            (svc.ERR_OVERSIZE, "too wide — 8193 lanes"),
            (svc.ERR_INTERNAL, ""),
        ]:
            got_code, got_msg = svc.decode_error(svc.encode_error(code, msg))
            assert (got_code, got_msg) == (code, msg)
        # a truncated error frame still yields a typed pair
        code, _ = svc.decode_error(b"\x01")
        assert code == svc.ERR_INTERNAL


# ---------------------------------------------------------------------------
# packing: the RPC payload IS the PR 13 wire format
# ---------------------------------------------------------------------------


class TestPackItems:
    @pytest.mark.parametrize("n", [1, 3, 8, 65])
    def test_compact_matches_prepare_batch_compact(self, n):
        from cometbft_tpu.crypto.tpu import ed25519_batch as eb

        items = _batch(n, tag=b"pack-%d" % n)
        wire, valid = svc.pack_items_compact(items)
        assert wire.shape == (128, n) and wire.dtype == np.uint8
        assert valid.all()
        ref_wire, ref_valid = eb.prepare_batch_compact(
            [svc._pk_bytes(pk) for pk, _, _ in items],
            [m for _, m, _ in items],
            [s for _, _, s in items],
        )
        np.testing.assert_array_equal(wire, ref_wire)
        np.testing.assert_array_equal(valid, np.asarray(ref_valid))

    def test_indexed_is_100_bytes_per_lane(self):
        items = _batch(6, tag=b"pack-idx")
        index = {svc._pk_bytes(pk): i for i, (pk, _, _) in enumerate(items)}
        rsh, idx, valid = svc.pack_items_indexed(items, index)
        assert rsh.shape == (96, 6) and rsh.dtype == np.uint8
        assert idx.dtype == np.int32 and list(idx) == list(range(6))
        assert valid.all()
        assert (rsh.nbytes + idx.nbytes) / len(items) == 100.0
        # rsh rows are the compact wire minus the 32 pubkey rows
        wire, _ = svc.pack_items_compact(items)
        np.testing.assert_array_equal(rsh, wire[32:])


class TestCachingRowVerifier:
    def test_parity_and_memoization(self):
        items = _batch(5, tag=b"cache", bad=(1, 3))
        wire, _ = svc.pack_items_compact(items)
        v = svc.CachingRowVerifier(max_entries=16)
        mask = v(wire)
        assert list(mask) == _expected(items)
        assert v.misses == 5 and v.hits == 0
        # repeats are dict hits, verdicts unchanged
        mask2 = v(wire)
        assert list(mask2) == list(mask)
        assert v.misses == 5 and v.hits == 5


# ---------------------------------------------------------------------------
# live service harness
# ---------------------------------------------------------------------------


class _Daemon:
    """One scheduler + service on a fresh Unix socket, with an optional
    gate the row verifier blocks on (freezing the 'device pool' so
    requests are provably in flight when chaos strikes)."""

    def __init__(self, tag, coalesce=True, gate=None, flush_us=200,
                 auth_key=None):
        self.gate = gate
        inner = svc.host_row_verifier()

        def verifier(rows):
            if gate is not None:
                gate.wait(20)
            return inner(rows)

        self.sched = VerifyScheduler(
            spec="cpu", flush_us=flush_us, lane_budget=256,
            max_queue=256, qos="off", row_verifier=verifier,
        )
        self.path = "/tmp/cbft-test-svc-%s-%d.sock" % (tag, os.getpid())
        self.address = "unix://" + self.path
        self.service = svc.VerifyService(
            self.sched, self.address, coalesce=coalesce,
            row_verifier=verifier, auth_key=auth_key,
        )
        self.sched.start()
        self.service.start()
        self.clients = []

    def client(self, tenant, timeout_ms=15_000, auth_key=None,
               node_id=None, retry_s=0.05):
        c = svc.RemoteVerifier(
            self.address, tenant=tenant, timeout_ms=timeout_ms,
            retry_s=retry_s, auth_key=auth_key, node_id=node_id,
        )
        self.clients.append(c)
        return c

    def stop(self):
        for c in self.clients:
            c.close()
        self.service.stop()
        self.sched.stop()
        try:
            os.unlink(self.path)
        except OSError:
            pass


@pytest.fixture
def daemon(request):
    d = _Daemon(request.node.name.replace("[", "-").replace("]", ""))
    yield d
    d.stop()


class TestServiceEndToEnd:
    def test_verdicts_and_bytes_per_lane(self, daemon):
        items = _batch(9, tag=b"e2e", bad=(0, 4))
        fut = daemon.client("t0").submit(items, subsystem="consensus")
        ok, mask = fut.result(timeout=30)
        assert not ok and mask == _expected(items)
        assert not fut.rejected
        snap = daemon.service.snapshot()
        assert snap["bytes_per_lane"]["compact"] == 128.0
        assert snap["lanes"]["compact"] == 9
        assert snap["tenants"] == ["t0"]

    def test_empty_submit_never_touches_the_wire(self, daemon):
        ok, mask = daemon.client("t0").submit([]).result(timeout=5)
        assert ok and mask == []
        assert daemon.service.snapshot()["frames"].get("req", 0) == 0

    def test_cross_client_demux(self, daemon):
        """N clients submit interleaved batches with per-client corrupt
        lanes; every future must carry exactly its OWN verdicts even
        when one coalesced flush served several clients."""
        n_clients, lanes, rounds = 4, 8, 3
        clients = [daemon.client("demux%d" % i) for i in range(n_clients)]
        batches = [
            [
                _batch(lanes, tag=b"demux-%d-%d" % (c, r), bad=(c % lanes,))
                for r in range(rounds)
            ]
            for c in range(n_clients)
        ]
        results = [[None] * rounds for _ in range(n_clients)]
        start = threading.Barrier(n_clients)

        def run(c):
            start.wait(10)
            futs = [
                clients[c].submit(batches[c][r], subsystem="consensus")
                for r in range(rounds)
            ]
            for r, f in enumerate(futs):
                results[c][r] = f.result(timeout=30)

        threads = [
            threading.Thread(target=run, args=(c,))
            for c in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for c in range(n_clients):
            want = [i != c % lanes for i in range(lanes)]
            for r in range(rounds):
                ok, mask = results[c][r]
                assert not ok and mask == want, (c, r, mask)
        snap = daemon.service.snapshot()
        assert snap["lanes"]["compact"] == n_clients * lanes * rounds
        assert snap["bytes_per_lane"]["compact"] == 128.0
        assert sorted(snap["disconnects"]) == []


class TestDisconnectContainment:
    def test_kill_mid_flight_contains_to_one_tenant(self):
        gate = threading.Event()
        d = _Daemon("kill", gate=gate)
        try:
            victim = d.client("victim")
            survivor = d.client("survivor")
            vic_items = _batch(6, tag=b"vic", bad=(2,))
            sur_items = _batch(6, tag=b"sur", bad=(5,))
            # park both requests against the gated pool
            vic_fut = victim.submit(vic_items, subsystem="blocksync")
            sur_fut = survivor.submit(sur_items, subsystem="blocksync")
            deadline = time.monotonic() + 10
            while (d.service.pending_requests() < 2
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert d.service.pending_requests() >= 2
            # sever the victim's socket abruptly, mid-flight
            victim.kill_connection()
            ok, mask = vic_fut.result(timeout=30)
            # distinct reason + ground-truth verdict via local fallback
            assert vic_fut.reason == "disconnected"
            assert not ok and mask == _expected(vic_items)
            assert victim.stats().get("disconnected", 0) >= 1
            # thaw the pool: the survivor's request — same coalesced
            # flush — still completes correctly
            gate.set()
            ok, mask = sur_fut.result(timeout=30)
            assert not ok and mask == _expected(sur_items)
            assert getattr(sur_fut, "reason", None) is None
            # the server metered the severed tenant, and only it
            deadline = time.monotonic() + 10
            while (not d.service.snapshot()["disconnects"]
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            disc = d.service.snapshot()["disconnects"]
            assert disc.get("victim", 0) >= 1
            assert "survivor" not in disc
            # the victim reconnects on its next submit (once its
            # retry_s backoff window has passed)
            time.sleep(0.2)
            ok, mask = victim.submit(
                _batch(3, tag=b"vic2"), subsystem="blocksync"
            ).result(timeout=30)
            assert ok and mask == [True] * 3
            assert victim.stats().get("connects", 0) >= 2
            assert victim.stats().get("remote_ok", 0) >= 1
        finally:
            gate.set()
            d.stop()


# ---------------------------------------------------------------------------
# malformed / truncated / oversized frames: typed refusal, accept
# loop survives
# ---------------------------------------------------------------------------


def _raw_conn(daemon):
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(5.0)
    s.connect(daemon.path)
    frame = _read_frame(s)  # server greets with HELLO
    assert frame.ftype == svc.FT_HELLO
    return s


def _read_frame(s):
    head = b""
    while len(head) < 4:
        chunk = s.recv(4 - len(head))
        if not chunk:
            return None
        head += chunk
    (length,) = _LEN.unpack(head)
    buf = b""
    while len(buf) < length:
        chunk = s.recv(length - len(buf))
        if not chunk:
            return None
        buf += chunk
    return svc.decode_frame(buf)


def _expect_err(daemon, data, code):
    s = _raw_conn(daemon)
    try:
        s.sendall(data)
        frame = _read_frame(s)
        assert frame is not None and frame.ftype == svc.FT_ERR
        got, msg = svc.decode_error(frame.payload)
        assert got == code, (svc.ERR_NAMES.get(got, got), msg)
        return frame
    finally:
        s.close()


class TestFrameFuzz:
    def test_truncation_at_every_offset_never_kills_the_accept_loop(
        self, daemon
    ):
        items = _batch(2, tag=b"fuzz")
        wire, _ = svc.pack_items_compact(items)
        for ctx in (None, (0x1234ABCD, 0x77, True)):
            # both header shapes: the v1 wire and the v2 extended header
            # carrying a trace-context extension block
            whole = svc.encode_frame(
                svc.FT_REQ, kind=svc.KIND_COMPACT, req_id=1, n_lanes=2,
                payload=wire.tobytes(), trace_ctx=ctx,
            )
            for cut in range(1, len(whole)):
                s = _raw_conn(daemon)
                s.sendall(whole[:cut])
                s.close()
        # the service survived all of it: a real client still verifies
        ok, mask = daemon.client("after-fuzz").submit(
            items, subsystem="consensus"
        ).result(timeout=30)
        assert ok and mask == [True, True]
        assert daemon.service.snapshot()["connections"] <= 2

    def test_bad_magic_is_refused_typed(self, daemon):
        buf = bytearray(svc.encode_frame(svc.FT_REQ, n_lanes=0))
        buf[4:8] = b"EVIL"
        _expect_err(daemon, bytes(buf), svc.ERR_MALFORMED)

    def test_future_version_is_refused_typed(self, daemon):
        buf = bytearray(svc.encode_frame(svc.FT_REQ, n_lanes=0))
        buf[8] = svc.VERSION + 3
        _expect_err(daemon, bytes(buf), svc.ERR_BAD_VERSION)

    def test_unknown_frame_type_is_refused_typed(self, daemon):
        _expect_err(
            daemon, svc.encode_frame(250), svc.ERR_MALFORMED,
        )

    def test_server_only_frame_type_is_refused_typed(self, daemon):
        _expect_err(
            daemon, svc.encode_frame(svc.FT_RESP), svc.ERR_MALFORMED,
        )

    def test_bad_qos_class_is_refused_typed(self, daemon):
        wire, _ = svc.pack_items_compact(_batch(1, tag=b"class"))
        _expect_err(daemon, svc.encode_frame(
            svc.FT_REQ, qclass=0x77, n_lanes=1, payload=wire.tobytes(),
        ), svc.ERR_BAD_CLASS)

    def test_payload_size_mismatch_is_refused_typed(self, daemon):
        _expect_err(daemon, svc.encode_frame(
            svc.FT_REQ, n_lanes=3, payload=b"\x00" * 100,
        ), svc.ERR_MALFORMED)

    def test_zero_and_oversize_lanes_are_refused_typed(self, daemon):
        _expect_err(daemon, svc.encode_frame(
            svc.FT_REQ, n_lanes=0,
        ), svc.ERR_MALFORMED)
        n = daemon.service.snapshot()["max_lanes"] + 1
        _expect_err(daemon, svc.encode_frame(
            svc.FT_REQ, n_lanes=n, payload=b"",
        ), svc.ERR_MALFORMED)

    def test_ragged_register_payload_is_refused_typed(self, daemon):
        _expect_err(daemon, svc.encode_frame(
            svc.FT_REGISTER, n_lanes=1, payload=b"\x01" * 33,
        ), svc.ERR_MALFORMED)

    def test_oversize_length_prefix_is_refused_typed(self, daemon):
        snap = daemon.service.snapshot()
        too_big = svc.max_frame_bytes(snap["max_lanes"]) + 1
        s = _raw_conn(daemon)
        try:
            s.sendall(_LEN.pack(too_big))
            frame = _read_frame(s)
            assert frame is not None and frame.ftype == svc.FT_ERR
            code, _ = svc.decode_error(frame.payload)
            assert code == svc.ERR_OVERSIZE
        finally:
            s.close()

    def test_auth_and_drain_frame_truncation_never_kills_the_accept_loop(
        self, daemon
    ):
        """The PR 20 frame types get the same truncation treatment as
        FT_REQ: every prefix of an AUTH / DRAINING / AUTH_OK frame, cut
        mid-header and mid-payload, must leave the accept loop alive."""
        shapes = [
            svc.encode_frame(
                svc.FT_AUTH,
                payload=b"\x5a" * svc.AUTH_MAC_BYTES + b"node-x",
            ),
            svc.encode_frame(svc.FT_DRAINING),
            svc.encode_frame(svc.FT_AUTH_OK, req_id=9),
        ]
        for whole in shapes:
            for cut in range(1, len(whole)):
                s = _raw_conn(daemon)
                s.sendall(whole[:cut])
                s.close()
        items = _batch(2, tag=b"fuzz-auth")
        ok, mask = daemon.client("after-auth-fuzz").submit(
            items, subsystem="consensus"
        ).result(timeout=30)
        assert ok and mask == [True, True]

    def test_client_sent_draining_and_auth_ok_are_refused_typed(
        self, daemon
    ):
        _expect_err(
            daemon, svc.encode_frame(svc.FT_DRAINING), svc.ERR_MALFORMED,
        )
        _expect_err(
            daemon, svc.encode_frame(svc.FT_AUTH_OK), svc.ERR_MALFORMED,
        )

    def test_connection_survives_a_typed_refusal(self, daemon):
        """Per-request refusals don't kill the connection: a good frame
        on the SAME socket still gets its verdict."""
        items = _batch(2, tag=b"survive")
        wire, _ = svc.pack_items_compact(items)
        s = _raw_conn(daemon)
        try:
            s.sendall(svc.encode_frame(
                svc.FT_REQ, req_id=7, n_lanes=5, payload=b"\x00" * 12,
            ))
            frame = _read_frame(s)
            assert frame.ftype == svc.FT_ERR and frame.req_id == 7
            s.sendall(svc.encode_frame(
                svc.FT_REQ, req_id=8, n_lanes=2, payload=wire.tobytes(),
            ))
            deadline = time.monotonic() + 20
            frame = _read_frame(s)
            assert frame is not None and frame.ftype == svc.FT_RESP
            assert frame.req_id == 8 and time.monotonic() < deadline
            assert frame.payload[0] == svc.ST_OK
            bits = np.unpackbits(
                np.frombuffer(frame.payload[1:], np.uint8),
                bitorder="little",
            )[:2]
            assert list(bits.astype(bool)) == [True, True]
        finally:
            s.close()


# ---------------------------------------------------------------------------
# keystore generation handshake: stale -> compact fallback -> resync
# -> indexed again
# ---------------------------------------------------------------------------


class TestGenerationHandshake:
    def test_stale_client_falls_back_then_upgrades_after_resync(self):
        d = _Daemon("gen")
        try:
            from cometbft_tpu.crypto.tpu import keystore

            store = keystore.default_store()
            client = d.client("valclient")
            items = _batch(8, tag=b"gen", bad=(3,))
            pks = [svc._pk_bytes(pk) for pk, _, _ in items]
            want = _expected(items)

            # register -> covered submits ship 100 B/lane indexed rows
            client.register_valset(pks)
            assert client.stats().get("registrations", 0) == 1
            ok, mask = client.submit(
                items, subsystem="consensus"
            ).result(timeout=30)
            assert not ok and mask == want
            snap = d.service.snapshot()
            assert snap["lanes"].get("indexed", 0) == 8
            assert snap["bytes_per_lane"]["indexed"] == 100.0

            # the key space changes behind the client's back: another
            # valset lands, bumping the store generation
            other = [
                ed.gen_priv_key_from_secret(b"gen-bump-%d" % i)
                .pub_key().bytes()
                for i in range(4)
            ]
            import hashlib
            store.register(
                hashlib.sha256(b"".join(other)).digest()[:16], other
            )

            # stale submit: the server REFUSES the indexed frame (typed
            # stale_generation, stale_drops metered), the client
            # resolves via local fallback with the distinct reason
            fut = client.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=30)
            assert fut.reason == "stale"
            assert not ok and mask == want
            assert client.stats().get("stale", 0) >= 1
            snap = d.service.snapshot()
            assert snap["stale_drops"] >= 1
            assert snap["errors"].get("stale_generation", 0) >= 1

            # next submit resyncs (re-register at the new generation)
            # and goes indexed again — never stuck on the fallback
            fut = client.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=30)
            assert getattr(fut, "reason", None) is None
            assert not ok and mask == want
            assert client.stats().get("registrations", 0) == 2
            snap = d.service.snapshot()
            assert snap["lanes"]["indexed"] == 16
            assert snap["bytes_per_lane"]["indexed"] == 100.0
            # compact was never needed: the resync happened client-side
            # before framing, so every lane stayed <= 100 B
            assert all(v <= 128.0 for v in snap["bytes_per_lane"].values())
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# authenticated sessions (PR 20): HMAC challenge-response on HELLO
# ---------------------------------------------------------------------------


_KEY = b"test-fleet-key-20"


class TestAuthSessions:
    def test_wrong_key_is_refused_typed_with_no_retry_storm(self):
        d = _Daemon("auth-wrong", auth_key=_KEY)
        try:
            c = d.client(
                "evil", timeout_ms=4000, auth_key=b"not-the-key",
                node_id="evil", retry_s=0.2,
            )
            items = _batch(4, tag=b"auth-w", bad=(1,))
            want = _expected(items)
            fut = c.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=20)
            # ground truth via the local CPU rung, typed reason — never
            # the failover rung (the whole fleet shares the key)
            assert fut.reason == "unauthorized"
            assert not ok and mask == want
            assert c.stats().get("unauthorized", 0) >= 1
            assert "unauthorized" not in svc.FAILOVER_REASONS
            # a burst of submits must not hammer the daemon: auth
            # refusals escalate the reconnect backoff
            for _ in range(10):
                f = c.submit(items, subsystem="consensus")
                f.result(timeout=20)
                assert f.reason == "unauthorized"
            assert c.stats().get("connect_attempts", 0) <= 4
            snap = d.service.snapshot()
            assert snap["auth_rejects"] >= 1
            # refused work never reached the scheduler
            assert sum(snap["lanes"].values()) == 0
            panel = snap.get("tenants_panel", {})
            assert (panel.get("evil", {}) or {}).get("requests", 0) == 0
        finally:
            d.stop()

    def test_right_key_tenant_is_the_authenticated_node_id(self):
        d = _Daemon("auth-right", auth_key=_KEY)
        try:
            # the CLIENT_HELLO tenant hint must not let a key holder
            # ride another tenant's quota: the authenticated id wins
            c = d.client(
                "pretender", auth_key=_KEY, node_id="node-7",
            )
            items = _batch(3, tag=b"auth-r", bad=(0,))
            fut = c.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=30)
            assert not ok and mask == _expected(items)
            assert getattr(fut, "reason", None) is None
            assert c.stats().get("auth_ok", 0) >= 1
            snap = d.service.snapshot()
            assert snap["auth_ok"] >= 1
            panel = snap["tenants_panel"]
            assert panel.get("node-7", {}).get("requests", 0) >= 1
            assert "pretender" not in panel
        finally:
            d.stop()

    def test_keyless_client_against_auth_server_is_refused_typed(self):
        d = _Daemon("auth-keyless", auth_key=_KEY)
        try:
            c = d.client("naive")
            items = _batch(3, tag=b"auth-k")
            fut = c.submit(items, subsystem="consensus")
            ok, mask = fut.result(timeout=20)
            assert fut.reason == "unauthorized"
            assert ok and mask == [True] * 3
            assert c.stats().get("err_unauthorized", 0) >= 1
            assert sum(d.service.snapshot()["lanes"].values()) == 0
        finally:
            d.stop()

    def test_keyed_client_against_open_server_interops(self, daemon):
        # v1/no-auth interop: the open server's HELLO carries no auth
        # flag, so the keyed client skips the handshake and just works
        c = daemon.client("keyed", auth_key=_KEY, node_id="keyed-1")
        items = _batch(3, tag=b"interop", bad=(2,))
        fut = c.submit(items, subsystem="consensus")
        ok, mask = fut.result(timeout=30)
        assert not ok and mask == _expected(items)
        assert getattr(fut, "reason", None) is None


# ---------------------------------------------------------------------------
# graceful drain (PR 20): in-flight answered, new work refused typed
# ---------------------------------------------------------------------------


class TestDrain:
    def test_drain_answers_inflight_and_refuses_new_typed(self):
        gate = threading.Event()
        d = _Daemon("drain", gate=gate)
        try:
            holder = d.client("holder")
            items = _batch(5, tag=b"drain", bad=(2,))
            want = _expected(items)
            fut = holder.submit(items, subsystem="consensus")
            deadline = time.monotonic() + 10
            while (d.service.pending_requests() < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert d.service.pending_requests() >= 1
            d.service.drain()
            assert d.service.snapshot()["draining"] is True
            # NEW work is refused with the typed ST_DRAINING status and
            # resolves on the caller's local CPU rung, distinct reason
            late = d.client("late")
            f2 = late.submit(items, subsystem="consensus")
            ok2, mask2 = f2.result(timeout=20)
            assert f2.reason == "draining"
            assert not ok2 and mask2 == want
            # the parked in-flight request is still answered — drain is
            # graceful, not a guillotine
            gate.set()
            ok, mask = fut.result(timeout=30)
            assert getattr(fut, "reason", None) is None
            assert not ok and mask == want
            snap = d.service.snapshot()
            assert snap["drain_refusals"] >= 1
        finally:
            gate.set()
            d.stop()

    def test_drain_broadcast_reaches_connected_clients(self):
        d = _Daemon("drain-bcast")
        try:
            c = d.client("watcher")
            ok, _ = c.submit(
                _batch(2, tag=b"bcast"), subsystem="consensus"
            ).result(timeout=30)
            assert ok
            assert not c.server_draining
            d.service.drain()
            deadline = time.monotonic() + 10
            while (not c.server_draining
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert c.server_draining
            assert c.stats().get("server_draining", 0) >= 1
            assert c.snapshot()["server_draining"] is True
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# reconnect backoff (PR 20): a dead daemon is not hammered
# ---------------------------------------------------------------------------


class TestReconnectBackoff:
    def test_dead_endpoint_backoff_bounds_connect_attempts(self):
        c = svc.RemoteVerifier(
            "unix:///tmp/cbft-test-noexist-%d.sock" % os.getpid(),
            tenant="lonely", timeout_ms=2000, retry_s=0.2,
            retry_cap_s=1.0,
        )
        try:
            items = _batch(2, tag=b"backoff")
            want = _expected(items)
            for _ in range(10):
                f = c.submit(items, subsystem="consensus")
                ok, mask = f.result(timeout=10)
                assert f.reason == "disconnected"
                assert mask == want
            # ten rapid submits, at most a few real connect() calls:
            # the capped-exponential window swallowed the rest
            assert 1 <= c.stats().get("connect_attempts", 0) <= 4
            snap = c.snapshot()
            assert snap["connected"] is False
            r = snap["reconnect"]
            assert r["connect_fails"] >= 1
            assert r["last_backoff_s"] > 0
            assert r["retry_base_s"] == 0.2
            assert r["retry_cap_s"] == 1.0
        finally:
            c.close()


# ---------------------------------------------------------------------------
# bench history: the service stage's guard directions
# ---------------------------------------------------------------------------


class TestServiceBenchDirections:
    def test_coalesce_gain_and_p99_directions(self):
        import importlib.util

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_history_service_test",
            os.path.join(repo, "tools", "bench_history.py"),
        )
        bh = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bh)
        for leaf in ("service_coalesce_gain",
                     "stages.service.service_coalesce_gain"):
            assert bh.direction(leaf) == bh.HIGHER_IS_BETTER, leaf
        for leaf in ("service_p99_ms", "service_isolated_p99_ms",
                     "stages.service.service_p99_ms"):
            assert bh.direction(leaf) == bh.LOWER_IS_BETTER, leaf
        # throughput keeps the generic per-second rule
        assert (bh.direction("service_coalesced_sigs_per_sec")
                == bh.HIGHER_IS_BETTER)
        # booleans stay directionless
        assert bh.direction("service_coalesce_gain_ok") is None
