"""Remote signing over sockets: wire codec, client/server round-trips,
double-sign guard propagation, and a node committing blocks with ONLY a
remote signer.

Model: reference privval/signer_client_test.go + signer_server tests.
"""

import os
import socket
import tempfile
import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.privval import (
    FilePV,
    RemoteSignerError,
    SignerClient,
    SignerDialerEndpoint,
    SignerListenerEndpoint,
    SignerServer,
    gen_file_pv,
)
from cometbft_tpu.privval.socket import (
    PingRequest,
    PubKeyRequest,
    PubKeyResponse,
    SignedVoteResponse,
    SignVoteRequest,
    decode_privval_message,
    encode_privval_message,
)
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import BlockID, PartSetHeader
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT as PRECOMMIT_TYPE,
    SIGNED_MSG_TYPE_PREVOTE as PREVOTE_TYPE,
    Vote,
)

CHAIN_ID = "privval-sock-chain"


def _vote(height=5, round_=0, type_=PREVOTE_TYPE):
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=BlockID(b"\x11" * 32, PartSetHeader(1, b"\x22" * 32)),
        timestamp=Timestamp(1_700_000_000, 0),
        validator_address=b"\x01" * 20,
        validator_index=0,
    )


def _pair(tmp):
    """A connected (SignerClient, SignerServer, FilePV) over a unix socket."""
    sock_path = os.path.join(tmp, "signer.sock")
    listener = SignerListenerEndpoint(f"unix://{sock_path}", timeout_read=1.0)
    pv = gen_file_pv(
        os.path.join(tmp, "key.json"), os.path.join(tmp, "state.json")
    )
    dialer = SignerDialerEndpoint(f"unix://{sock_path}", timeout_read=1.0)
    dialer.connect()
    server = SignerServer(dialer, CHAIN_ID, pv)
    server.start()
    listener.wait_for_connection(5.0)
    client = SignerClient(listener, CHAIN_ID)
    return client, server, pv, listener


class TestCodec:
    def test_roundtrip(self):
        msgs = [
            PubKeyRequest(CHAIN_ID),
            PubKeyResponse(error=(2, "no key")),
            SignVoteRequest(vote=_vote(), chain_id=CHAIN_ID),
            SignedVoteResponse(vote=_vote()),
            PingRequest(),
        ]
        for m in msgs:
            dec = decode_privval_message(encode_privval_message(m))
            assert type(dec) is type(m)
        dec = decode_privval_message(
            encode_privval_message(SignVoteRequest(vote=_vote(7), chain_id=CHAIN_ID))
        )
        assert dec.vote.height == 7 and dec.chain_id == CHAIN_ID
        with pytest.raises(Exception):
            decode_privval_message(b"")


class TestSignerClientServer:
    def test_pubkey_ping_and_vote_signing(self):
        with tempfile.TemporaryDirectory() as tmp:
            client, server, pv, listener = _pair(tmp)
            try:
                client.ping()
                pk = client.get_pub_key()
                assert pk.bytes() == pv.get_pub_key().bytes()

                vote = _vote()
                client.sign_vote(CHAIN_ID, vote)
                assert vote.signature
                # the signature is the same one the local FilePV would make,
                # and it verifies against the canonical sign bytes
                assert pk.verify_signature(
                    vote.sign_bytes(CHAIN_ID), vote.signature
                )
            finally:
                server.stop()
                listener.close()

    def test_double_sign_guard_travels_the_wire(self):
        with tempfile.TemporaryDirectory() as tmp:
            client, server, pv, listener = _pair(tmp)
            try:
                v1 = _vote(height=10, type_=PRECOMMIT_TYPE)
                client.sign_vote(CHAIN_ID, v1)
                # conflicting precommit at the same HRS → RemoteSignerError
                v2 = _vote(height=10, type_=PRECOMMIT_TYPE)
                v2.block_id = BlockID(b"\x99" * 32, PartSetHeader(1, b"\x88" * 32))
                with pytest.raises(RemoteSignerError):
                    client.sign_vote(CHAIN_ID, v2)
                # height regression also rejected
                v3 = _vote(height=9, type_=PRECOMMIT_TYPE)
                with pytest.raises(RemoteSignerError):
                    client.sign_vote(CHAIN_ID, v3)
            finally:
                server.stop()
                listener.close()

    def test_proposal_signing(self):
        from cometbft_tpu.types.proposal import Proposal

        with tempfile.TemporaryDirectory() as tmp:
            client, server, pv, listener = _pair(tmp)
            try:
                prop = Proposal(
                    height=3,
                    round=0,
                    pol_round=-1,
                    block_id=BlockID(b"\x07" * 32, PartSetHeader(1, b"\x08" * 32)),
                    timestamp=Timestamp(1_700_000_000, 0),
                )
                client.sign_proposal(CHAIN_ID, prop)
                assert prop.signature
                assert client.get_pub_key().verify_signature(
                    prop.sign_bytes(CHAIN_ID), prop.signature
                )
            finally:
                server.stop()
                listener.close()

    def test_tcp_endpoints(self):
        listener = SignerListenerEndpoint("tcp://127.0.0.1:0", timeout_read=1.0)
        port = listener.listen_port
        with tempfile.TemporaryDirectory() as tmp:
            pv = gen_file_pv(
                os.path.join(tmp, "k.json"), os.path.join(tmp, "s.json")
            )
            dialer = SignerDialerEndpoint(
                f"tcp://127.0.0.1:{port}", timeout_read=1.0
            )
            dialer.connect()
            server = SignerServer(dialer, CHAIN_ID, pv)
            server.start()
            try:
                listener.wait_for_connection(5.0)
                client = SignerClient(listener, CHAIN_ID)
                assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()
            finally:
                server.stop()
                listener.close()

    def test_secret_connection_link_with_key_pinning(self):
        """TCP link wrapped in SecretConnection; the listener pins the
        signer's key and rejects impostors (socket_dialers.go analog)."""
        node_key = ed.gen_priv_key()
        signer_key = ed.gen_priv_key()
        listener = SignerListenerEndpoint(
            "tcp://127.0.0.1:0", timeout_read=2.0,
            priv_key=node_key, authorized_key=signer_key.pub_key().bytes(),
        )
        port = listener.listen_port
        with tempfile.TemporaryDirectory() as tmp:
            pv = gen_file_pv(
                os.path.join(tmp, "k.json"), os.path.join(tmp, "s.json")
            )
            # an impostor with the wrong key is rejected by the handshake
            impostor = SignerDialerEndpoint(
                f"tcp://127.0.0.1:{port}", timeout_read=1.0,
                priv_key=ed.gen_priv_key(),
            )
            impostor.connect()
            time.sleep(0.3)
            assert not listener.is_connected()

            # the real signer authenticates and serves
            dialer = SignerDialerEndpoint(
                f"tcp://127.0.0.1:{port}", timeout_read=2.0,
                priv_key=signer_key,
            )
            dialer.connect()
            server = SignerServer(dialer, CHAIN_ID, pv)
            server.start()
            try:
                listener.wait_for_connection(5.0)
                client = SignerClient(listener, CHAIN_ID)
                assert client.get_pub_key().bytes() == pv.get_pub_key().bytes()

                # a live signer link is never displaced by a new dial
                intruder = socket.socket()
                intruder.connect(("127.0.0.1", port))
                time.sleep(0.3)
                client.ping()  # still works
                intruder.close()
            finally:
                server.stop()
                listener.close()

    def test_client_without_connection_errors(self):
        listener = SignerListenerEndpoint("tcp://127.0.0.1:0", timeout_read=0.2)
        try:
            client = SignerClient(listener, CHAIN_ID)
            with pytest.raises(RemoteSignerError):
                client.ping()
        finally:
            listener.close()


@pytest.mark.slow
class TestNodeWithRemoteSigner:
    def test_single_node_commits_with_remote_signer(self):
        """A node configured with priv_validator_laddr and NO local key
        commits blocks using only the remote signer (node.go:755,1451)."""
        import base64
        import json
        import urllib.request

        from cometbft_tpu.cmd.commands import _load_config, main as cli_main
        from cometbft_tpu.node.node import (
            Node,
            default_client_creator,
        )
        from cometbft_tpu.types.genesis import GenesisDoc
        from cometbft_tpu.p2p.key import NodeKey

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]
            s.close()
            return p

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "remote-pv-chain"])
            cfg = _load_config(d)
            rpc_port, p2p_port, pv_port = free_port(), free_port(), free_port()
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.base.priv_validator_laddr = f"tcp://127.0.0.1:{pv_port}"

            # the "HSM box": serves the initialized FilePV over TCP
            from cometbft_tpu.privval import load_file_pv

            pv = load_file_pv(
                cfg.base.priv_validator_key_path(),
                cfg.base.priv_validator_state_path(),
            )
            dialer = SignerDialerEndpoint(
                f"tcp://127.0.0.1:{pv_port}", timeout_read=2.0,
                max_retries=100, retry_wait=0.2,
            )
            server_box = {}

            def run_signer():
                dialer.connect()
                server = SignerServer(dialer, "remote-pv-chain", pv)
                server.start()
                server_box["server"] = server

            threading.Thread(target=run_signer, daemon=True).start()

            with open(cfg.base.genesis_path()) as f:
                doc = GenesisDoc.from_json(f.read())
            node_key = NodeKey.load_or_gen(
                os.path.join(d, cfg.base.node_key_file)
            )
            node = Node(
                cfg,
                None,  # NO local priv validator
                node_key,
                default_client_creator("kvstore"),
                doc,
            )
            node.start()
            try:
                deadline = time.monotonic() + 60
                height = 0
                while time.monotonic() < deadline and height < 2:
                    try:
                        body = json.dumps(
                            {"jsonrpc": "2.0", "id": 1, "method": "status",
                             "params": {}}
                        ).encode()
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{rpc_port}/", data=body,
                            headers={"Content-Type": "application/json"},
                        )
                        st = json.loads(
                            urllib.request.urlopen(req, timeout=5).read()
                        )["result"]
                        height = int(st["sync_info"]["latest_block_height"])
                    except Exception:
                        pass
                    time.sleep(0.3)
                assert height >= 2, "node with remote signer never committed"
            finally:
                node.stop()
                if "server" in server_box:
                    server_box["server"].stop()