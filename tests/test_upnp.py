"""UPnP discovery/mapping against a fake in-process gateway.

Model: reference p2p/upnp — SSDP search, device-description fetch, SOAP
GetExternalIPAddress/AddPortMapping/DeletePortMapping, and the Probe
capability report. A real gateway never exists in CI, so this spins a
loopback SSDP responder + HTTP IGD and points discovery at it.
"""

import socket
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from cometbft_tpu.p2p import upnp

_DESCRIPTION = """<?xml version="1.0"?>
<root xmlns="urn:schemas-upnp-org:device-1-0">
 <device>
  <deviceType>urn:schemas-upnp-org:device:InternetGatewayDevice:1</deviceType>
  <serviceList>
   <service>
    <serviceType>urn:schemas-upnp-org:service:WANIPConnection:1</serviceType>
    <controlURL>/control</controlURL>
   </service>
  </serviceList>
 </device>
</root>"""


class _FakeIGD(BaseHTTPRequestHandler):
    mappings = {}

    def log_message(self, *a):
        pass

    def do_GET(self):
        body = _DESCRIPTION.encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length).decode()
        action = (self.headers.get("SOAPAction") or "").strip('"').split("#")[-1]
        if action == "GetExternalIPAddress":
            payload = (
                "<NewExternalIPAddress>127.0.0.1</NewExternalIPAddress>"
            )
        elif action == "AddPortMapping":
            import re

            port = re.search(r"<NewExternalPort>(\d+)</NewExternalPort>", body)
            _FakeIGD.mappings[int(port.group(1))] = True
            payload = ""
        elif action == "DeletePortMapping":
            import re

            port = re.search(r"<NewExternalPort>(\d+)</NewExternalPort>", body)
            _FakeIGD.mappings.pop(int(port.group(1)), None)
            payload = ""
        else:
            self.send_response(500)
            self.end_headers()
            return
        out = f"<s:Envelope><s:Body>{payload}</s:Body></s:Envelope>".encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(out)))
        self.end_headers()
        self.wfile.write(out)


@pytest.fixture
def gateway(monkeypatch):
    httpd = HTTPServer(("127.0.0.1", 0), _FakeIGD)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    http_port = httpd.server_address[1]

    ssdp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    ssdp.bind(("127.0.0.1", 0))
    ssdp_port = ssdp.getsockname()[1]
    stop = threading.Event()

    def responder():
        ssdp.settimeout(0.2)
        while not stop.is_set():
            try:
                data, addr = ssdp.recvfrom(1500)
            except socket.timeout:
                continue
            if b"M-SEARCH" in data:
                answer = (
                    "HTTP/1.1 200 OK\r\n"
                    "ST: urn:schemas-upnp-org:device:InternetGatewayDevice:1\r\n"
                    f"LOCATION: http://127.0.0.1:{http_port}/desc.xml\r\n\r\n"
                ).encode()
                ssdp.sendto(answer, addr)

    threading.Thread(target=responder, daemon=True).start()
    monkeypatch.setattr(upnp, "SSDP_ADDR", ("127.0.0.1", ssdp_port))
    _FakeIGD.mappings.clear()
    yield
    stop.set()
    httpd.shutdown()


class TestUPnP:
    def test_discover_and_map(self, gateway):
        nat = upnp.discover(timeout=2.0)
        assert nat.service_type.endswith("WANIPConnection:1")
        assert nat.external_ip() == "127.0.0.1"
        nat.add_port_mapping("tcp", 18123, 18123)
        assert 18123 in _FakeIGD.mappings
        nat.delete_port_mapping("tcp", 18123)
        assert 18123 not in _FakeIGD.mappings

    def test_probe_reports_capabilities(self, gateway):
        from cometbft_tpu.libs.net import free_ports

        (port,) = free_ports(1)
        caps = upnp.probe(internal_port=port)
        assert caps.port_mapping
        assert caps.hairpin  # ext ip is 127.0.0.1 → we dial our own listener
        assert port not in _FakeIGD.mappings  # cleaned up

    def test_no_gateway_is_clean_error(self, monkeypatch):
        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        silent_port = sock.getsockname()[1]
        monkeypatch.setattr(upnp, "SSDP_ADDR", ("127.0.0.1", silent_port))
        with pytest.raises(upnp.UPnPError):
            upnp.discover(timeout=0.3)
        sock.close()

    def test_cli_probe_without_gateway(self, capsys, monkeypatch):
        import json

        from cometbft_tpu.cmd.commands import main as cli_main

        sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        sock.bind(("127.0.0.1", 0))
        monkeypatch.setattr(
            upnp, "SSDP_ADDR", ("127.0.0.1", sock.getsockname()[1])
        )
        monkeypatch.setattr(upnp, "discover", lambda timeout=0.3: (_ for _ in ()).throw(upnp.UPnPError("none")))
        assert cli_main(["probe-upnp"]) == 0
        out = json.loads(capsys.readouterr().out.strip())
        assert "error" in out
        sock.close()
