"""Adversarial-committee rung (PR 18) in tier-1.

A hostile committee drives the full scheduler -> supervisor -> service
stack through one storm campaign: byzantine signature floods at a 25%
lane rate, double-sign evidence bursts through the ``evidence`` QoS
tenant, non-validator vote spam on ``mempool``, a valset rotation
mid-storm (keystore generation invalidation + service re-register),
and a verifyd kill/restart while a request is on the wire. The
zero-wrong-verdict invariants are the same ones tools/chaos.py
--adversary gates on; the fast rung here runs a 128-seat committee so
tier-1 stays quick, the slow soak walks the 512-seat acceptance shape.
"""

import math

import pytest


def _assert_invariants(s):
    # safety: no wrong verdict anywhere — not on device, not on the
    # CPU fallback, not across the service wire, not vs the oracle
    assert s["wrong_verdicts"] == 0, s["wrong_by_kind"]
    assert s["service_wrong_verdicts"] == 0
    # attribution: every injected byzantine lane charged to consensus,
    # nothing charged to the honest evidence/spam tenants
    assert s["offenders_exact"], (s["offenders"], s["expected_offenders"])
    # triage stayed inside the bisection pass bound per run
    assert s["triage_pass_bound_ok"], (
        f"{s['triage_passes']} passes over {s['triage_runs']} runs, "
        f"bound {s['triage_pass_bound']}/run"
    )
    # liveness: block-class tenants never shed or dropped, the breaker
    # never left healthy, and storm p99 held the committee-scaled SLO
    assert s["consensus_sheds"] == 0 and s["consensus_drops"] == 0
    assert s["evidence_sheds"] == 0 and s["evidence_drops"] == 0
    assert s["supervisor_state"] == "healthy"
    assert s["latency_ok"], (
        f"loaded p99 {s['loaded_p99_ms']}ms over bound "
        f"{s['latency_bound_ms']}ms"
    )


class TestAdversaryRung:
    def test_adversary_campaign_fast(self):
        from cometbft_tpu.crypto.adversary import (
            AttackPlan,
            campaign_ok,
            run_campaign,
        )

        plan = AttackPlan(
            committee=128,
            heights=8,
            byzantine_rate=0.25,
            churn_every=4,
            equivocation_every=2,
            equivocation_burst=4,
            spam_per_height=16,
            service=True,
            kill_restart_height=4,
            seed=37,
        )
        s = run_campaign(plan)
        _assert_invariants(s)
        # the storm actually happened: floods, bursts, spam, a rotation
        assert s["injected"]["byzantine"] == 8 * 32
        assert s["injected"]["equivocation_pairs"] >= 8
        assert s["injected"]["spam"] >= 64
        assert s["rotations"] >= 1
        assert s["keystore"]["registrations"] >= 1
        # the rotation churned the committee through the keystore
        # without thrashing live entries out from under a dispatch
        assert s["triage_runs"] >= 1
        # restart recovery: the mid-storm kill resolved the in-flight
        # request locally with the distinct reason, then the client
        # walked reconnect -> re-register -> indexed resume
        svc = s["service"]
        assert svc["restarts"] == 1
        assert svc["client"]["disconnected"] >= 1
        assert svc["client"]["connects"] >= 2
        assert svc["client"]["registrations"] >= 2
        assert svc["client"]["remote_ok"] >= 1
        # the single gate the chaos CLI applies agrees
        assert campaign_ok(s), s

    def test_pass_bound_shape(self):
        # the structural bound the campaign asserts per triage run is
        # the PR 5 bisection guarantee: ceil(log2 n) + 1 passes
        from cometbft_tpu.crypto.adversary import AttackPlan

        p = AttackPlan(committee=512, spam_per_height=32,
                       equivocation_burst=8)
        worst = p.committee + p.spam_per_height + 2 * p.equivocation_burst
        assert math.ceil(math.log2(worst)) + 1 == 11

    @pytest.mark.slow
    def test_adversary_acceptance_512_soak(self):
        from cometbft_tpu.crypto.adversary import run_chaos_adversary

        s = run_chaos_adversary(seed=41, committee=512, heights=16,
                                byzantine_rate=0.25, churn_every=8)
        _assert_invariants(s)
        assert s["injected"]["byzantine"] == 16 * 128
        assert s["service"]["restarts"] == 1
        assert s["service"]["client"]["disconnected"] >= 1
        assert s["service"]["client"]["connects"] >= 2
