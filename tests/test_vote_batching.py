"""addVote micro-batching: N queued votes → ONE BatchVerifier call,
with outcomes identical to the serial path.

The VERDICT's done-criterion for the consensus hot path (reference
types/vote_set.go:205 verifies one signature per vote on the single
receive thread; here the receive loop drains its queue and verifies the
whole drain in one batch).
"""

import queue

import pytest

from cometbft_tpu.abci.client import LocalClient
from cometbft_tpu.abci.kvstore import KVStoreApplication
from cometbft_tpu.config import test_config as make_test_config
from cometbft_tpu.consensus.messages import MsgInfo, VoteMessage
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.consensus.wal import NilWAL
from cometbft_tpu.crypto import batch as cryptobatch
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.proxy import AppConnConsensus
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PREVOTE
from cometbft_tpu.types.vote_set import VoteSet

CHAIN_ID = "votebatch-chain"


def _make_cs(n_vals=4):
    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    doc = GenesisDoc(
        genesis_time=Timestamp(1_700_000_000, 0),
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    state = make_genesis_state(doc)
    store = Store(MemDB())
    store.save(state)
    client = LocalClient(KVStoreApplication())
    client.start()
    executor = BlockExecutor(store, AppConnConsensus(client))
    cfg = make_test_config().consensus
    cfg.wal_path = ""
    cs = ConsensusState(cfg, state, executor, BlockStore(MemDB()), wal=NilWAL())
    cs.set_priv_validator(privs[0])
    # initialize round state without starting the receive thread
    cs._update_to_state_locked(state) if hasattr(
        cs, "_update_to_state_locked"
    ) else cs.update_to_state(state)
    return cs, vals, privs


def _prevote(privs, idx, height, round_, bid=None):
    return test_util.make_vote(
        privs[idx], CHAIN_ID, idx, height, round_, SIGNED_MSG_TYPE_PREVOTE,
        bid or test_util.make_block_id(),
    )


class TestVoteSetMarker:
    def test_marker_skips_serial_verify_only_for_matching_key(self):
        vals, privs = test_util.deterministic_validator_set(4, 10)
        vs = VoteSet(CHAIN_ID, 5, 0, SIGNED_MSG_TYPE_PREVOTE, vals)
        v = _prevote(privs, 1, 5, 0)
        v.signature = b"\x01" * 64  # garbage signature
        # marker naming the right key+chain: accepted without serial verify
        v.sig_batch_verified = (CHAIN_ID, vals.validators[1].pub_key.bytes())
        added, err = vs.add_vote(v, True)
        assert added, err
        # marker naming the WRONG key: serial verify runs and rejects
        v2 = _prevote(privs, 2, 5, 0)
        v2.signature = b"\x02" * 64
        v2.sig_batch_verified = (CHAIN_ID, b"\x00" * 32)
        added, err = vs.add_vote(v2, True)
        assert not added and "verify" in err

    def test_no_marker_serial_verify_still_runs(self):
        vals, privs = test_util.deterministic_validator_set(4, 10)
        vs = VoteSet(CHAIN_ID, 5, 0, SIGNED_MSG_TYPE_PREVOTE, vals)
        v = _prevote(privs, 1, 5, 0)
        v.signature = b"\x03" * 64
        added, err = vs.add_vote(v, True)
        assert not added and "verify" in err


class TestReceiveLoopBatching:
    def test_n_queued_votes_one_batch_call(self):
        """The headline assertion: a drain of N queued votes produces
        exactly ONE BatchVerifier call, and every vote lands."""
        cs, vals, privs = _make_cs(4)
        h, r = cs.rs.height, cs.rs.round
        bid = test_util.make_block_id()
        votes = [_prevote(privs, i, h, r, bid) for i in range(1, 4)]
        for v in votes:
            cs.peer_msg_queue.put(MsgInfo(VoteMessage(v), f"peer{v.validator_index}"))

        first = cs.peer_msg_queue.get_nowait()
        batch = cs._drain_peer_queue(first)
        assert len(batch) == 3

        calls_before = cs.n_batch_verify_calls
        cs._batch_preverify_votes(batch)
        assert cs.n_batch_verify_calls == calls_before + 1

        # every vote is marked and then applies without serial verification
        for m in batch:
            assert m.msg.vote.sig_batch_verified[0] == CHAIN_ID
            cs._handle_msg(m)
        prevotes = cs.rs.votes.prevotes(r)
        assert sum(
            1 for i in range(4) if prevotes.get_vote(i) is not None
        ) == 3

    def test_bad_signature_in_batch_rejected(self):
        """A forged vote inside the drain is NOT marked and the serial
        path rejects it — outcomes identical to unbatched processing."""
        cs, vals, privs = _make_cs(4)
        h, r = cs.rs.height, cs.rs.round
        bid = test_util.make_block_id()
        good1 = _prevote(privs, 1, h, r, bid)
        forged = _prevote(privs, 2, h, r, bid)
        forged.signature = b"\x05" * 64
        good2 = _prevote(privs, 3, h, r, bid)
        batch = [
            MsgInfo(VoteMessage(v), "p") for v in (good1, forged, good2)
        ]
        cs._batch_preverify_votes(batch)
        assert getattr(good1, "sig_batch_verified", None) is not None
        assert getattr(forged, "sig_batch_verified", None) is None
        assert getattr(good2, "sig_batch_verified", None) is not None
        for m in batch:
            cs._handle_msg(m)
        prevotes = cs.rs.votes.prevotes(r)
        assert prevotes.get_vote(1) is not None
        assert prevotes.get_vote(2) is None  # forged vote rejected
        assert prevotes.get_vote(3) is not None

    def test_single_vote_skips_batching(self):
        cs, vals, privs = _make_cs(4)
        h, r = cs.rs.height, cs.rs.round
        batch = [MsgInfo(VoteMessage(_prevote(privs, 1, h, r)), "p")]
        calls = cs.n_batch_verify_calls
        cs._batch_preverify_votes(batch)
        assert cs.n_batch_verify_calls == calls  # singleton → serial path

    def test_txs_poke_survives_the_drain(self):
        """A txs-available poke (msg=None) drained mid-batch must still be
        delivered to _handle_txs_available, not silently dropped."""
        cs, vals, privs = _make_cs(4)
        h, r = cs.rs.height, cs.rs.round
        cs.peer_msg_queue.put(MsgInfo(None, "@txs"))
        cs.peer_msg_queue.put(
            MsgInfo(VoteMessage(_prevote(privs, 2, h, r)), "p")
        )
        first = cs.peer_msg_queue.get_nowait()
        batch = cs._drain_peer_queue(
            MsgInfo(VoteMessage(_prevote(privs, 1, h, r)), "p")
        )
        # the drain keeps pokes in order (first was consumed manually here,
        # so re-add it at the front for the assertion)
        all_msgs = [first] + batch
        assert any(m.msg is None for m in all_msgs)

    def test_unresolvable_votes_fall_back_to_serial(self):
        """Votes for an unknown future height are left unmarked (the
        serial path decides what to do with them)."""
        cs, vals, privs = _make_cs(4)
        v1 = _prevote(privs, 1, cs.rs.height + 5, 0)
        v2 = _prevote(privs, 2, cs.rs.height + 5, 0)
        batch = [MsgInfo(VoteMessage(v), "p") for v in (v1, v2)]
        calls = cs.n_batch_verify_calls
        cs._batch_preverify_votes(batch)
        assert cs.n_batch_verify_calls == calls
        assert getattr(v1, "sig_batch_verified", None) is None


class TestNotifyTxsAvailable:
    def test_full_queue_drops_instead_of_parking_a_thread(self):
        """notify_txs_available on a FULL peer queue must return
        immediately without spawning a fallback thread (it can fire ON
        the consensus thread via the mempool-update callback — a
        blocking put would deadlock the node). The signal is
        level-triggered, so dropping is safe: the next mempool update
        re-fires it."""
        import threading
        import time

        cs, _, _ = _make_cs(4)
        while True:
            try:
                cs.peer_msg_queue.put_nowait(MsgInfo(None, "@filler"))
            except queue.Full:
                break
        before = threading.active_count()
        t0 = time.monotonic()
        cs.notify_txs_available()  # must neither block nor park a thread
        assert time.monotonic() - t0 < 1.0
        assert threading.active_count() == before
        assert cs.peer_msg_queue.full()

        # with room available the poke lands
        while not cs.peer_msg_queue.empty():
            cs.peer_msg_queue.get_nowait()
        cs.notify_txs_available()
        mi = cs.peer_msg_queue.get_nowait()
        assert mi.msg is None and mi.peer_id == "@txs"
