"""Evidence pool + verification unit tests.

Model: reference evidence/pool_test.go (add/duplicate/expiry/committed/
pending caps/consensus buffer) and evidence/verify_test.go (duplicate-vote
signature and power checks).
"""

import pytest

from cometbft_tpu.evidence.pool import Pool
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.state import make_genesis_state
from cometbft_tpu.state.store import Store
from cometbft_tpu.store import BlockStore
from cometbft_tpu.types import test_util
from cometbft_tpu.types.block import Commit
from cometbft_tpu.types.evidence import DuplicateVoteEvidence
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.vote import SIGNED_MSG_TYPE_PRECOMMIT

CHAIN_ID = "evidence-test-chain"
GENESIS_TIME = Timestamp(1_700_000_000, 0)


def _make_chain(n_vals=4, heights=3):
    """Build a state store + block store with `heights` committed empty
    blocks, signed by a deterministic validator set."""
    vals, privs = test_util.deterministic_validator_set(n_vals, 10)
    doc = GenesisDoc(
        genesis_time=GENESIS_TIME,
        chain_id=CHAIN_ID,
        validators=[
            GenesisValidator(v.address, v.pub_key, v.voting_power, "")
            for v in vals.validators
        ],
    )
    state = make_genesis_state(doc)
    state_store = Store(MemDB())
    state_store.save(state)
    block_store = BlockStore(MemDB())

    last_commit = Commit(height=0, round=0)
    for h in range(1, heights + 1):
        proposer = state.validators.validators[0].address
        block, parts = state.make_block(h, [], last_commit, [], proposer)
        block_id = test_util.make_block_id(
            block.hash(), parts.header().total, parts.header().hash
        )
        seen_commit = test_util.make_commit(
            block_id, h, 0, state.validators, privs, CHAIN_ID,
            now=Timestamp(GENESIS_TIME.seconds + h, 0),
        )
        block_store.save_block(block, parts, seen_commit)
        state.last_block_height = h
        state.last_block_id = block_id
        state.last_block_time = block.header.time
        state.last_validators = state.validators
        state_store.save(state)
        last_commit = seen_commit
    return state, state_store, block_store, vals, privs


def _dup_vote_ev(state, block_store, vals, privs, height=1, val_idx=0):
    """Two conflicting precommits from the same validator at `height`."""
    block_time = block_store.load_block_meta(height).header.time
    pv = privs[val_idx]
    v1 = test_util.make_vote(
        pv, CHAIN_ID, val_idx, height, 0, SIGNED_MSG_TYPE_PRECOMMIT,
        test_util.make_block_id(b"\xaa" * 32), timestamp=block_time,
    )
    v2 = test_util.make_vote(
        pv, CHAIN_ID, val_idx, height, 0, SIGNED_MSG_TYPE_PRECOMMIT,
        test_util.make_block_id(b"\xbb" * 32), timestamp=block_time,
    )
    return DuplicateVoteEvidence.new(v1, v2, block_time, vals)


def _mk_pool(state_store, block_store):
    return Pool(MemDB(), state_store, block_store)


class TestEvidencePool:
    def test_add_valid_evidence(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        pool.add_evidence(ev)
        assert pool.size() == 1
        pending, size = pool.pending_evidence(-1)
        assert pending == [ev] and size > 0
        # idempotent
        pool.add_evidence(ev)
        assert pool.size() == 1

    def test_reject_bad_signature(self):
        from cometbft_tpu.types.evidence import ErrInvalidEvidence

        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        ev.vote_b.signature = b"\x00" * 64
        # a verification failure is classified as invalid (peer-punishable)
        with pytest.raises(ErrInvalidEvidence, match="signature"):
            pool.add_evidence(ev)
        assert pool.size() == 0

    def test_malformed_evidence_is_invalid_evidence(self):
        """validate_basic failures are protocol violations (the reactor
        disconnects the sender), not benign context errors."""
        from cometbft_tpu.types.evidence import ErrInvalidEvidence

        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        ev.vote_a = None  # structurally malformed
        with pytest.raises(ErrInvalidEvidence):
            pool.add_evidence(ev)

    def test_missing_header_is_not_invalid_evidence(self):
        """Context failures must NOT be ErrInvalidEvidence — the reactor
        would disconnect an honest peer over a pruning/height race."""
        from cometbft_tpu.types.evidence import ErrInvalidEvidence

        state, ss, bs, vals, privs = _make_chain(heights=3)
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs, height=1)
        # evidence claims a height this node has no header for
        ev.vote_a.height = ev.vote_b.height = 50
        with pytest.raises(ValueError, match="don't have header") as ei:
            pool.add_evidence(ev)
        assert not isinstance(ei.value, ErrInvalidEvidence)

    def test_reject_unknown_validator(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        other_vals, other_privs = test_util.deterministic_validator_set(5, 7)
        block_time = bs.load_block_meta(1).header.time
        pv = other_privs[4]
        v1 = test_util.make_vote(
            pv, CHAIN_ID, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
            test_util.make_block_id(b"\xaa" * 32), timestamp=block_time,
        )
        v2 = test_util.make_vote(
            pv, CHAIN_ID, 0, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
            test_util.make_block_id(b"\xbb" * 32), timestamp=block_time,
        )
        ev = DuplicateVoteEvidence.new(v1, v2, block_time, other_vals)
        with pytest.raises(ValueError):
            pool.add_evidence(ev)

    def test_reject_wrong_time(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        ev.timestamp = Timestamp(ev.timestamp.seconds + 100, 0)
        with pytest.raises(ValueError, match="different time"):
            pool.add_evidence(ev)

    def test_reject_expired_evidence(self):
        state, ss, bs, vals, privs = _make_chain(heights=3)
        # tighten the expiry window so height-1 evidence is already stale
        state.consensus_params.evidence.max_age_num_blocks = 1
        state.consensus_params.evidence.max_age_duration_ns = 1
        ss.save(state)
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs, height=1)
        with pytest.raises(ValueError, match="too old"):
            pool.add_evidence(ev)

    def test_update_marks_committed_and_prunes(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        pool.add_evidence(ev)
        assert pool.size() == 1
        state.last_block_height += 1  # the block carrying the evidence
        pool.update(state, [ev])
        assert pool.size() == 0
        assert pool.pending_evidence(-1)[0] == []
        # committed evidence can't come back
        pool.add_evidence(ev)
        assert pool.size() == 0
        with pytest.raises(ValueError, match="committed"):
            pool.check_evidence([ev])

    def test_check_evidence_adds_unseen_and_rejects_duplicates_in_block(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        ev = _dup_vote_ev(state, bs, vals, privs)
        pool.check_evidence([ev])  # not pending yet → verified + added
        assert pool.size() == 1
        with pytest.raises(ValueError, match="duplicate"):
            pool.check_evidence([ev, ev])

    def test_pending_evidence_byte_cap(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        e1 = _dup_vote_ev(state, bs, vals, privs, val_idx=0)
        e2 = _dup_vote_ev(state, bs, vals, privs, val_idx=1)
        pool.add_evidence(e1)
        pool.add_evidence(e2)
        all_evs, total = pool.pending_evidence(-1)
        assert len(all_evs) == 2
        some, size = pool.pending_evidence(total - 1)
        assert len(some) == 1 and size < total

    def test_consensus_buffer_processed_on_update(self):
        state, ss, bs, vals, privs = _make_chain()
        pool = _mk_pool(ss, bs)
        block_time = bs.load_block_meta(1).header.time
        pv = privs[2]
        v1 = test_util.make_vote(
            pv, CHAIN_ID, 2, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
            test_util.make_block_id(b"\xaa" * 32), timestamp=block_time,
        )
        v2 = test_util.make_vote(
            pv, CHAIN_ID, 2, 1, 0, SIGNED_MSG_TYPE_PRECOMMIT,
            test_util.make_block_id(b"\xbb" * 32), timestamp=block_time,
        )
        pool.report_conflicting_votes(v1, v2)
        assert pool.size() == 0  # buffered, not yet pending
        state.last_block_height += 1
        pool.update(state, [])
        assert pool.size() == 1
        ev = pool.pending_evidence(-1)[0][0]
        assert isinstance(ev, DuplicateVoteEvidence)
        assert ev.validator_power == 10
