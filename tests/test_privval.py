"""FilePV: persistence, CheckHRS double-sign guard, crash-window reuse.

Model: reference privval/file_test.go (TestUnmarshalValidator,
TestSignVote, TestSignProposal, TestDifferByTimestamp).
"""

import os
import tempfile

import pytest

from cometbft_tpu.privval import (
    STEP_PRECOMMIT,
    STEP_PREVOTE,
    FilePV,
    gen_file_pv,
    load_file_pv,
    load_or_gen_file_pv,
)
from cometbft_tpu.privval.file import ErrDoubleSign
from cometbft_tpu.proto.gogo import Timestamp
from cometbft_tpu.types.block import BlockID
from cometbft_tpu.types.part_set import PartSetHeader
from cometbft_tpu.types.proposal import Proposal
from cometbft_tpu.types.vote import (
    SIGNED_MSG_TYPE_PRECOMMIT,
    SIGNED_MSG_TYPE_PREVOTE,
    SIGNED_MSG_TYPE_PROPOSAL,
    Vote,
)

CHAIN_ID = "pv-test-chain"


def _paths(d):
    return os.path.join(d, "pv_key.json"), os.path.join(d, "pv_state.json")


def _block_id(b=b"\x01"):
    return BlockID(b * 32, PartSetHeader(2, b"\x02" * 32))


def _vote(height, round_, type_=SIGNED_MSG_TYPE_PREVOTE, bid=None, ts=None):
    return Vote(
        type=type_,
        height=height,
        round=round_,
        block_id=bid if bid is not None else _block_id(),
        timestamp=ts or Timestamp(1_700_000_100, 0),
    )


class TestFilePVPersistence:
    def test_gen_save_load_roundtrip(self):
        with tempfile.TemporaryDirectory() as d:
            kp, sp = _paths(d)
            pv = gen_file_pv(kp, sp)
            pv.save()
            pv2 = load_file_pv(kp, sp)
            assert pv2.get_pub_key().bytes() == pv.get_pub_key().bytes()
            assert pv2.get_address() == pv.get_address()
            # key file has restrictive permissions
            assert os.stat(kp).st_mode & 0o777 == 0o600

    def test_load_or_gen(self):
        with tempfile.TemporaryDirectory() as d:
            kp, sp = _paths(d)
            pv = load_or_gen_file_pv(kp, sp)
            pv2 = load_or_gen_file_pv(kp, sp)
            assert pv.get_address() == pv2.get_address()

    def test_sign_state_persisted(self):
        with tempfile.TemporaryDirectory() as d:
            kp, sp = _paths(d)
            pv = gen_file_pv(kp, sp)
            pv.save()
            v = _vote(5, 2)
            pv.sign_vote(CHAIN_ID, v)
            lss = load_file_pv(kp, sp).last_sign_state
            assert (lss.height, lss.round, lss.step) == (5, 2, STEP_PREVOTE)
            assert lss.signature == v.signature
            assert lss.sign_bytes == v.sign_bytes(CHAIN_ID)


class TestDoubleSignGuard:
    def _pv(self, d):
        kp, sp = _paths(d)
        pv = gen_file_pv(kp, sp)
        pv.save()
        return pv, kp, sp

    def test_height_regression(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            pv.sign_vote(CHAIN_ID, _vote(10, 0))
            with pytest.raises(ErrDoubleSign, match="height regression"):
                pv.sign_vote(CHAIN_ID, _vote(9, 0))

    def test_round_regression(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            pv.sign_vote(CHAIN_ID, _vote(10, 3))
            with pytest.raises(ErrDoubleSign, match="round regression"):
                pv.sign_vote(CHAIN_ID, _vote(10, 2))

    def test_step_regression(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            pv.sign_vote(CHAIN_ID, _vote(10, 0, SIGNED_MSG_TYPE_PRECOMMIT))
            with pytest.raises(ErrDoubleSign, match="step regression"):
                pv.sign_vote(CHAIN_ID, _vote(10, 0, SIGNED_MSG_TYPE_PREVOTE))

    def test_same_vote_reuses_signature(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            v1 = _vote(10, 0)
            pv.sign_vote(CHAIN_ID, v1)
            v2 = _vote(10, 0)
            pv.sign_vote(CHAIN_ID, v2)
            assert v2.signature == v1.signature

    def test_timestamp_only_difference_reuses_sig_and_timestamp(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            ts1 = Timestamp(1_700_000_100, 0)
            v1 = _vote(10, 0, ts=ts1)
            pv.sign_vote(CHAIN_ID, v1)
            v2 = _vote(10, 0, ts=Timestamp(1_700_000_200, 500))
            pv.sign_vote(CHAIN_ID, v2)
            assert v2.signature == v1.signature
            assert v2.timestamp == ts1  # pinned to the first signing

    def test_conflicting_block_id_same_hrs_raises(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            pv.sign_vote(CHAIN_ID, _vote(10, 0, bid=_block_id(b"\xaa")))
            with pytest.raises(ErrDoubleSign, match="conflicting data"):
                pv.sign_vote(CHAIN_ID, _vote(10, 0, bid=_block_id(b"\xbb")))

    def test_restart_mid_height_cannot_double_sign(self):
        """The VERDICT's done-criterion: crash after signing, reload from
        disk, the new process must refuse to sign conflicting data and must
        reproduce the identical signature for identical data."""
        with tempfile.TemporaryDirectory() as d:
            pv, kp, sp = self._pv(d)
            v = _vote(7, 1, SIGNED_MSG_TYPE_PRECOMMIT, bid=_block_id(b"\xaa"))
            pv.sign_vote(CHAIN_ID, v)
            del pv  # "crash"

            pv2 = load_file_pv(kp, sp)
            # conflicting precommit at the same HRS: refused
            with pytest.raises(ErrDoubleSign, match="conflicting data"):
                pv2.sign_vote(
                    CHAIN_ID,
                    _vote(7, 1, SIGNED_MSG_TYPE_PRECOMMIT, bid=_block_id(b"\xbb")),
                )
            # identical precommit: identical signature (idempotent re-sign)
            v2 = _vote(7, 1, SIGNED_MSG_TYPE_PRECOMMIT, bid=_block_id(b"\xaa"))
            pv2.sign_vote(CHAIN_ID, v2)
            assert v2.signature == v.signature

    def test_proposal_flow(self):
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            p1 = Proposal(
                type=SIGNED_MSG_TYPE_PROPOSAL,
                height=4,
                round=0,
                pol_round=-1,
                block_id=_block_id(),
                timestamp=Timestamp(1_700_000_100, 0),
            )
            pv.sign_proposal(CHAIN_ID, p1)
            assert p1.signature
            # same proposal, different timestamp → reuse
            p2 = Proposal(
                type=SIGNED_MSG_TYPE_PROPOSAL,
                height=4,
                round=0,
                pol_round=-1,
                block_id=_block_id(),
                timestamp=Timestamp(1_700_000_999, 0),
            )
            pv.sign_proposal(CHAIN_ID, p2)
            assert p2.signature == p1.signature
            assert p2.timestamp == p1.timestamp
            # conflicting proposal at same HR → refused
            p3 = Proposal(
                type=SIGNED_MSG_TYPE_PROPOSAL,
                height=4,
                round=0,
                pol_round=-1,
                block_id=_block_id(b"\xcc"),
                timestamp=Timestamp(1_700_000_100, 0),
            )
            with pytest.raises(ErrDoubleSign, match="conflicting data"):
                pv.sign_proposal(CHAIN_ID, p3)
            # proposal (step 1) then prevote (step 2) at same height/round: OK
            pv.sign_vote(CHAIN_ID, _vote(4, 0))

    def test_failed_save_does_not_poison_reuse_path(self):
        """If the state file can't be written, the in-memory state must not
        record the signature either — otherwise a later same-HRS sign would
        release a signature that survives no crash."""
        with tempfile.TemporaryDirectory() as d:
            pv, _, _ = self._pv(d)
            # parent "directory" is a regular file → the atomic write fails
            blocker = os.path.join(d, "blocker")
            open(blocker, "w").close()
            pv.last_sign_state.file_path = os.path.join(blocker, "state.json")
            with pytest.raises(OSError):
                pv.sign_vote(CHAIN_ID, _vote(10, 0))
            # memory unchanged: height still 0, no signature recorded
            assert pv.last_sign_state.height == 0
            assert not pv.last_sign_state.signature

    def test_vote_after_reset_starts_clean(self):
        with tempfile.TemporaryDirectory() as d:
            pv, kp, sp = self._pv(d)
            pv.sign_vote(CHAIN_ID, _vote(10, 0))
            pv.reset()
            pv2 = load_file_pv(kp, sp)
            pv2.sign_vote(CHAIN_ID, _vote(3, 0))  # no regression error
