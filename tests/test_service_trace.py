"""Cross-process trace propagation (PR 19): the frame v2 trace-context
extension, v1<->v2 interop (identical verdicts, zero refusals, unknown
extension bytes ignored), and the stitched client->server trace over a
real Unix socket — client pack/wire_wait spans and the server's adopted
request span sharing ONE trace_id, merged into one stage table by
tools/trace_report.py. Runs on the virtual CPU mesh (conftest.py)."""

import os
import socket
import struct
import sys
import threading
import time

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto import service as svc
from cometbft_tpu.crypto.scheduler import VerifyScheduler
from cometbft_tpu.libs.trace import Tracer

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)

_LEN = struct.Struct("<I")
_CTX = (0x1A2B3C4D5E6F7081 & 0x7FFFFFFFFFFFFFFF, 0x55AA55AA55AA55A1, True)


def _batch(n, tag=b"trc", bad=()):
    keys = [ed.gen_priv_key_from_secret(tag + b"-%d" % i) for i in range(n)]
    items = []
    for i, k in enumerate(keys):
        msg = tag + b" msg %d" % i
        sig = k.sign(msg)
        if i in bad:
            sig = bytes(sig[:-1]) + bytes([sig[-1] ^ 0x01])
        items.append((k.pub_key(), msg, sig))
    return items


def _expected(items):
    return [
        ed.PubKeyEd25519(svc._pk_bytes(pk)).verify_signature(m, s)
        for pk, m, s in items
    ]


# ---------------------------------------------------------------------------
# frame v2 codec: the trace extension block
# ---------------------------------------------------------------------------


class TestTraceExtensionCodec:
    def test_no_ctx_emits_the_exact_v1_wire(self):
        """A v2 sender without a trace context MUST be byte-identical to
        v1 — that is the whole interop story."""
        buf = svc.encode_frame(
            svc.FT_REQ, req_id=9, n_lanes=1, payload=b"\x42" * 128,
        )
        assert buf[8] == svc.MIN_VERSION == 1
        (length,) = _LEN.unpack(buf[:4])
        assert length == svc.HEADER_BYTES + 128  # no extension byte
        f = svc.decode_frame(buf[4:])
        assert f.trace_ctx is None
        assert f.payload == b"\x42" * 128

    @pytest.mark.parametrize("sampled", [True, False])
    def test_trace_ctx_round_trips(self, sampled):
        tid, sid, _ = _CTX
        buf = svc.encode_frame(
            svc.FT_REQ, qclass=2, kind=svc.KIND_COMPACT, req_id=77,
            n_lanes=3, payload=b"\x07" * (3 * 128),
            trace_ctx=(tid, sid, sampled),
        )
        assert buf[8] == 2
        f = svc.decode_frame(buf[4:])
        assert f.trace_ctx == (tid, sid, sampled)
        assert f.req_id == 77 and f.n_lanes == 3
        assert f.payload == b"\x07" * (3 * 128)

    def test_unknown_extension_tlvs_are_skipped(self):
        """Future minor revisions may ride new TLVs next to the trace
        one; a v2 decoder skips what it does not know and still finds
        the payload at the right offset."""
        tid, sid, _ = _CTX
        whole = svc.encode_frame(
            svc.FT_REQ, req_id=5, n_lanes=1, payload=b"\x11" * 128,
            trace_ctx=(tid, sid, True),
        )
        body = bytearray(whole[4:])
        ext_len = body[svc.HEADER_BYTES]
        old_ext = bytes(
            body[svc.HEADER_BYTES + 1:svc.HEADER_BYTES + 1 + ext_len]
        )
        unknown = bytes([0x7F, 3]) + b"abc"  # type 0x7f, 3 value bytes
        new_ext = unknown + old_ext + unknown
        rebuilt = (
            bytes(body[:svc.HEADER_BYTES])
            + bytes([len(new_ext)]) + new_ext
            + bytes(body[svc.HEADER_BYTES + 1 + ext_len:])
        )
        f = svc.decode_frame(rebuilt)
        assert f.trace_ctx == (tid, sid, True)
        assert f.payload == b"\x11" * 128

    def test_extension_overruns_are_typed_malformed(self):
        tid, sid, _ = _CTX
        whole = svc.encode_frame(
            svc.FT_REQ, n_lanes=1, payload=b"\x00" * 128,
            trace_ctx=(tid, sid, True),
        )
        # ext_len pointing past the end of the frame
        body = bytearray(whole[4:])
        body[svc.HEADER_BYTES] = 255
        short = bytes(body[:svc.HEADER_BYTES + 10])
        with pytest.raises(svc.FrameError) as ei:
            svc.decode_frame(short)
        assert ei.value.code == svc.ERR_MALFORMED
        # TLV length overrunning its block
        body = bytearray(whole[4:])
        body[svc.HEADER_BYTES + 2] = 250
        with pytest.raises(svc.FrameError) as ei:
            svc.decode_frame(bytes(body))
        assert ei.value.code == svc.ERR_MALFORMED

    def test_v2_header_cut_before_ext_is_typed_malformed(self):
        tid, sid, _ = _CTX
        whole = svc.encode_frame(
            svc.FT_REQ, n_lanes=1, payload=b"\x00" * 128,
            trace_ctx=(tid, sid, True),
        )
        with pytest.raises(svc.FrameError) as ei:
            svc.decode_frame(whole[4:4 + svc.HEADER_BYTES])
        assert ei.value.code == svc.ERR_MALFORMED

    def test_max_frame_budget_covers_the_extension_block(self):
        tid, sid, _ = _CTX
        whole = svc.encode_frame(
            svc.FT_REQ, n_lanes=4, payload=b"\x00" * (4 * 128),
            trace_ctx=(tid, sid, True),
        )
        assert len(whole) - 4 <= svc.max_frame_bytes(4)


# ---------------------------------------------------------------------------
# live interop: v1 clients x v2 servers in every combination
# ---------------------------------------------------------------------------


class _Daemon:
    """One scheduler + service on a fresh Unix socket, optionally traced
    and optionally advertising the v2 trace capability."""

    def __init__(self, tag, advertise_trace=True, tracer=None):
        self.tracer = tracer
        self.sched = VerifyScheduler(
            spec="cpu", flush_us=200, lane_budget=256, max_queue=256,
            qos="off", tracer=tracer,
        )
        self.path = "/tmp/cbft-test-trc-%s-%d.sock" % (tag, os.getpid())
        self.address = "unix://" + self.path
        self.service = svc.VerifyService(
            self.sched, self.address, advertise_trace=advertise_trace,
        )
        self.sched.start()
        self.service.start()
        self.clients = []

    def client(self, tenant, tracer=None):
        c = svc.RemoteVerifier(
            self.address, tenant=tenant, timeout_ms=15_000,
            retry_s=0.05, tracer=tracer,
        )
        self.clients.append(c)
        return c

    def stop(self):
        for c in self.clients:
            c.close()
        self.service.stop()
        self.sched.stop()
        try:
            os.unlink(self.path)
        except OSError:
            pass


def _raw_conn(daemon):
    deadline = time.monotonic() + 20
    while True:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(10)
        try:
            s.connect(daemon.path)
            break
        except OSError:
            # accept backlog briefly full under the fuzz loop's
            # connection churn — retry until the listener drains
            s.close()
            if time.monotonic() > deadline:
                raise
            time.sleep(0.01)
    s.sendall(svc.encode_frame(
        svc.FT_CLIENT_HELLO, payload=b"raw",
    ))
    return s


def _read_frame(s):
    buf = b""
    while len(buf) < 4:
        chunk = s.recv(4 - len(buf))
        if not chunk:
            return None
        buf += chunk
    (length,) = _LEN.unpack(buf)
    buf = b""
    while len(buf) < length:
        chunk = s.recv(length - len(buf))
        if not chunk:
            return None
        buf += chunk
    return svc.decode_frame(buf)


def _no_refusals(service):
    snap = service.snapshot()
    assert snap["errors"] == {}, snap["errors"]
    for tenant, rec in snap["tenants_panel"].items():
        assert rec["refusals"] == {}, (tenant, rec["refusals"])


class TestInterop:
    def test_v2_client_against_v1_server_stays_on_v1_wire(self):
        """advertise_trace=False IS a v1 server: no capability byte in
        the HELLO payload, so a traced v2 client must keep shipping
        plain v1 frames — same verdicts, zero refusals."""
        d = _Daemon("v1srv", advertise_trace=False)
        try:
            tracer = Tracer(sample=1.0, seed=7)
            c = d.client("v2c", tracer=tracer)
            items = _batch(6, tag=b"v1srv", bad=(1, 4))
            ok, mask = c.submit(items, subsystem="consensus").result(
                timeout=30
            )
            assert not ok and mask == _expected(items)
            assert c.snapshot()["server_proto"] == 1
            assert tracer.n_started >= 1  # client still traces locally
            _no_refusals(d.service)
        finally:
            d.stop()

    def test_v1_client_against_v2_server(self):
        """An untraced client (= the v1 wire: no tracer, no extension
        bytes ever) gets identical verdicts from a v2 server."""
        d = _Daemon("v1cli", advertise_trace=True)
        try:
            c = d.client("v1c")
            items = _batch(6, tag=b"v1cli", bad=(0,))
            ok, mask = c.submit(items, subsystem="consensus").result(
                timeout=30
            )
            assert not ok and mask == _expected(items)
            _no_refusals(d.service)
        finally:
            d.stop()

    def test_raw_v2_trace_frame_gets_a_normal_verdict(self):
        """A hand-built frame carrying the trace extension verifies like
        its v1 twin — the server strips the extension before the exact
        payload-size check."""
        d = _Daemon("rawv2")
        try:
            items = _batch(2, tag=b"rawv2")
            wire, _ = svc.pack_items_compact(items)
            tid, sid, _ = _CTX
            s = _raw_conn(d)
            try:
                s.sendall(svc.encode_frame(
                    svc.FT_REQ, req_id=3, n_lanes=2,
                    payload=wire.tobytes(), trace_ctx=(tid, sid, True),
                ))
                frame = _read_frame(s)
                while frame is not None and frame.ftype == svc.FT_HELLO:
                    frame = _read_frame(s)
                assert frame is not None and frame.ftype == svc.FT_RESP
                assert frame.req_id == 3
                assert frame.payload[0] == svc.ST_OK
                bits = np.unpackbits(
                    np.frombuffer(frame.payload[1:], np.uint8),
                    bitorder="little",
                )[:2]
                assert list(bits.astype(bool)) == [True, True]
            finally:
                s.close()
            _no_refusals(d.service)
        finally:
            d.stop()

    def test_trace_frame_truncation_at_every_offset(self):
        """The every-offset truncation fuzz, rerun over the EXTENDED
        header: no cut of a trace-bearing frame may kill the accept
        loop."""
        d = _Daemon("fuzzv2")
        try:
            items = _batch(2, tag=b"fuzzv2")
            wire, _ = svc.pack_items_compact(items)
            tid, sid, _ = _CTX
            whole = svc.encode_frame(
                svc.FT_REQ, kind=svc.KIND_COMPACT, req_id=1, n_lanes=2,
                payload=wire.tobytes(), trace_ctx=(tid, sid, True),
            )
            for cut in range(1, len(whole)):
                s = _raw_conn(d)
                s.sendall(whole[:cut])
                s.close()
            ok, mask = d.client("after-fuzz").submit(
                items, subsystem="consensus"
            ).result(timeout=30)
            assert ok and mask == [True, True]
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# the stitched trace: one trace_id across two flight recorders
# ---------------------------------------------------------------------------


def _wait(pred, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


class TestStitchedTrace:
    def test_submit_stitches_across_the_socket(self):
        server_tracer = Tracer(sample=0.0, seed=11)
        client_tracer = Tracer(sample=1.0, seed=13)
        d = _Daemon("stitch", advertise_trace=True, tracer=server_tracer)
        try:
            c = d.client("stitch-t", tracer=client_tracer)
            # warm up: the capability byte rides the async HELLO, so the
            # first submit may still be on proto 1
            c.submit(_batch(2, tag=b"warm")).result(timeout=30)
            assert _wait(lambda: c.snapshot()["server_proto"] >= 2)

            before = {t["trace_id"] for t in client_tracer.recent()}
            items = _batch(4, tag=b"stitch", bad=(2,))
            ok, mask = c.submit(items, subsystem="consensus").result(
                timeout=30
            )
            assert not ok and mask == _expected(items)

            assert _wait(lambda: any(
                t["trace_id"] not in before
                for t in client_tracer.recent()
            ))
            ctrace = next(
                t for t in client_tracer.recent()
                if t["trace_id"] not in before
            )
            assert ctrace["root"] == "submit"
            cnames = {s["name"] for s in ctrace["spans"]}
            assert {"submit", "pack", "wire_wait"} <= cnames

            # the server adopted the client's trace: same trace_id in
            # the OTHER process's flight recorder even though the server
            # tracer samples nothing locally (sample=0)
            assert _wait(lambda: any(
                t["trace_id"] == ctrace["trace_id"]
                for t in server_tracer.recent()
            ))
            strace = next(
                t for t in server_tracer.recent()
                if t["trace_id"] == ctrace["trace_id"]
            )
            req = next(
                s for s in strace["spans"] if s["name"] == "request"
            )
            submit_span = next(
                s for s in ctrace["spans"] if s["name"] == "submit"
            )
            assert submit_span["parent_id"] is None
            assert req["parent_id"] == submit_span["span_id"]

            # tools/trace_report.py fuses the two dumps into one tree
            import trace_report

            merged = trace_report.merge_traces(
                [[ctrace], [strace]]
            )
            assert len(merged) == 1
            mnames = {s["name"] for s in merged[0]["spans"]}
            assert {"submit", "pack", "wire_wait", "request"} <= mnames
            stages = {
                r["stage"] for r in trace_report.stage_table(merged)
            }
            assert {"submit", "request"} <= stages
        finally:
            d.stop()

    def test_unsampled_submit_ships_no_extension(self):
        """sample=0 on the client = NOOP span = pure v1 frames even
        against a v2 server; the server never adopts anything."""
        server_tracer = Tracer(sample=0.0, seed=3)
        d = _Daemon("nosample", tracer=server_tracer)
        try:
            c = d.client("quiet", tracer=Tracer(sample=0.0))
            c.submit(_batch(2, tag=b"warm2")).result(timeout=30)
            assert _wait(lambda: c.snapshot()["server_proto"] >= 2)
            ok, _mask = c.submit(_batch(3, tag=b"quiet")).result(
                timeout=30
            )
            assert ok
            assert server_tracer.recent() == []
            _no_refusals(d.service)
        finally:
            d.stop()
