"""Tx + block indexers and the EventBus-fed IndexerService.

Model: reference state/txindex/kv/kv_test.go (index, get-by-hash, search
by events/height/ranges), state/indexer/block/kv/kv_test.go, and
state/txindex/indexer_service_test.go.
"""

import time

from cometbft_tpu.abci import types as abci
from cometbft_tpu.libs.db import MemDB
from cometbft_tpu.libs.pubsub.query import parse_query
from cometbft_tpu.state.indexer import (
    IndexerService,
    KVBlockIndexer,
    KVTxIndexer,
    NullTxIndexer,
)
from cometbft_tpu.state.indexer.tx import _tx_hash
from cometbft_tpu.types.event_bus import (
    EventBus,
    EventDataNewBlockHeader,
    EventDataTx,
)


def _tx_result(height, index, tx, events=None):
    return abci.TxResult(
        height=height,
        index=index,
        tx=tx,
        result=abci.ResponseDeliverTx(code=0, events=events or []),
    )


def _event(type_, **attrs):
    return abci.Event(
        type=type_,
        attributes=[
            abci.EventAttribute(k.encode(), v.encode(), True)
            for k, v in attrs.items()
        ],
    )


def _unindexed_event(type_, **attrs):
    return abci.Event(
        type=type_,
        attributes=[
            abci.EventAttribute(k.encode(), v.encode(), False)
            for k, v in attrs.items()
        ],
    )


class TestKVTxIndexer:
    def test_index_and_get_by_hash(self):
        idx = KVTxIndexer(MemDB())
        res = _tx_result(3, 0, b"hello=world")
        idx.index(res)
        got = idx.get(_tx_hash(b"hello=world"))
        assert got is not None
        assert (got.height, got.index, got.tx) == (3, 0, b"hello=world")
        assert idx.get(b"\x00" * 32) is None

    def test_search_by_hash_fast_path(self):
        idx = KVTxIndexer(MemDB())
        idx.index(_tx_result(5, 1, b"a=1"))
        h = _tx_hash(b"a=1").hex().upper()
        out = idx.search(parse_query(f"tx.hash='{h}'"))
        assert len(out) == 1 and out[0].height == 5

    def test_search_by_event_and_height(self):
        idx = KVTxIndexer(MemDB())
        idx.index(
            _tx_result(1, 0, b"t1", [_event("app", creator="alice")])
        )
        idx.index(
            _tx_result(2, 0, b"t2", [_event("app", creator="bob")])
        )
        idx.index(
            _tx_result(7, 0, b"t3", [_event("app", creator="alice")])
        )
        out = idx.search(parse_query("app.creator='alice'"))
        assert [r.height for r in out] == [1, 7]
        # conjunction narrows
        out = idx.search(parse_query("app.creator='alice' AND tx.height>2"))
        assert [r.height for r in out] == [7]
        # ranges
        out = idx.search(parse_query("tx.height>=2"))
        assert [r.height for r in out] == [2, 7]
        out = idx.search(parse_query("tx.height=2"))
        assert [r.height for r in out] == [2]
        # no match
        assert idx.search(parse_query("app.creator='carol'")) == []

    def test_unindexed_attributes_are_not_searchable(self):
        idx = KVTxIndexer(MemDB())
        idx.index(
            _tx_result(1, 0, b"t1", [_unindexed_event("app", creator="x")])
        )
        assert idx.search(parse_query("app.creator='x'")) == []
        # but the tx itself is still retrievable
        assert idx.get(_tx_hash(b"t1")) is not None

    def test_contains_and_exists(self):
        idx = KVTxIndexer(MemDB())
        idx.index(
            _tx_result(4, 2, b"t", [_event("transfer", addr="cosmos1xyz")])
        )
        assert idx.search(parse_query("transfer.addr CONTAINS 'xyz'"))
        assert idx.search(parse_query("transfer.addr EXISTS"))
        assert idx.search(parse_query("transfer.other EXISTS")) == []

    def test_null_indexer(self):
        idx = NullTxIndexer()
        idx.index(_tx_result(1, 0, b"x"))
        assert idx.get(_tx_hash(b"x")) is None


class TestKVBlockIndexer:
    def test_index_and_search(self):
        idx = KVBlockIndexer(MemDB())
        idx.index({"begin_block.proposer": ["aa"]}, 1)
        idx.index({"end_block.foo": ["bar"]}, 2)
        idx.index({"begin_block.proposer": ["aa"]}, 9)
        assert idx.has(1) and not idx.has(5)
        assert idx.search(parse_query("begin_block.proposer='aa'")) == [1, 9]
        assert idx.search(parse_query("block.height>1")) == [2, 9]
        assert idx.search(
            parse_query("begin_block.proposer='aa' AND block.height>1")
        ) == [9]
        assert idx.search(parse_query("end_block.foo='baz'")) == []


class TestIndexerService:
    def test_indexes_blocks_from_event_bus(self):
        bus = EventBus()
        bus.start()
        tx_idx = KVTxIndexer(MemDB())
        blk_idx = KVBlockIndexer(MemDB())
        svc = IndexerService(tx_idx, blk_idx, bus)
        svc.start()
        try:

            class _Header:
                height = 10

            bus.publish_event_new_block_header(
                EventDataNewBlockHeader(
                    header=_Header(),
                    num_txs=2,
                    result_begin_block=abci.ResponseBeginBlock(
                        events=[_event("bb", k="v")]
                    ),
                    result_end_block=abci.ResponseEndBlock(),
                )
            )
            for i, tx in enumerate((b"x=1", b"y=2")):
                bus.publish_event_tx(
                    EventDataTx(
                        height=10, index=i, tx=tx,
                        result=abci.ResponseDeliverTx(code=0),
                    )
                )
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if tx_idx.get(_tx_hash(b"y=2")) is not None and blk_idx.has(10):
                    break
                time.sleep(0.05)
            assert blk_idx.has(10)
            assert blk_idx.search(parse_query("bb.k='v'")) == [10]
            got = tx_idx.get(_tx_hash(b"x=1"))
            assert got is not None and got.height == 10
            assert [
                r.index for r in tx_idx.search(parse_query("tx.height=10"))
            ] == [0, 1]
        finally:
            svc.stop()
            bus.stop()

    def test_survives_blocks_with_many_txs(self):
        """>100 tx events in one burst must not evict the indexer's
        subscription (the bus's slow-client policy would silently kill
        indexing forever) — reference uses SubscribeUnbuffered."""
        bus = EventBus()
        bus.start()
        tx_idx = KVTxIndexer(MemDB())
        blk_idx = KVBlockIndexer(MemDB())
        svc = IndexerService(tx_idx, blk_idx, bus)
        svc.start()
        try:

            class _Header:
                height = 5

            n = 250
            bus.publish_event_new_block_header(
                EventDataNewBlockHeader(
                    header=_Header(),
                    num_txs=n,
                    result_begin_block=abci.ResponseBeginBlock(),
                    result_end_block=abci.ResponseEndBlock(),
                )
            )
            for i in range(n):
                bus.publish_event_tx(
                    EventDataTx(
                        height=5, index=i, tx=b"tx%d" % i,
                        result=abci.ResponseDeliverTx(code=0),
                    )
                )
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                if tx_idx.get(_tx_hash(b"tx%d" % (n - 1))) is not None:
                    break
                time.sleep(0.05)
            assert tx_idx.get(_tx_hash(b"tx0")) is not None
            assert tx_idx.get(_tx_hash(b"tx%d" % (n - 1))) is not None
        finally:
            svc.stop()
            bus.stop()
