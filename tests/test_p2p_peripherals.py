"""P2P peripherals: FuzzedSocket fault injection, EWMA trust metric,
behaviour reporter, and the PEX reactor's request/response flow over real
switches.

Model: reference p2p/fuzz.go, p2p/trust/metric_test.go,
behaviour/reporter_test.go, p2p/pex/pex_reactor_test.go.
"""

import random
import socket
import threading
import time

import pytest

from cometbft_tpu.behaviour import (
    MockReporter,
    SwitchReporter,
    bad_message,
    block_part,
    consensus_vote,
)
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.p2p import (
    MultiplexTransport,
    NetAddress,
    NodeInfo,
    NodeKey,
    ProtocolVersion,
    Switch,
)
from cometbft_tpu.p2p.conn.connection import ChannelDescriptor
from cometbft_tpu.p2p.conn.secret_connection import SecretConnection
from cometbft_tpu.p2p.base_reactor import Reactor
from cometbft_tpu.p2p.fuzz import (
    FUZZ_MODE_DELAY,
    FUZZ_MODE_DROP,
    FuzzConnConfig,
    FuzzedSocket,
)
from cometbft_tpu.p2p.pex.addrbook import AddrBook
from cometbft_tpu.p2p.pex.reactor import PEX_CHANNEL, PEXReactor
from cometbft_tpu.p2p.trust import TrustMetric, TrustMetricStore


def _wait(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError("condition not met before timeout")


class TestFuzzedSocket:
    def _pair(self):
        return socket.socketpair()

    def test_write_drops_lose_data(self):
        a, b = self._pair()
        fuzz = FuzzedSocket(
            a,
            FuzzConnConfig(mode=FUZZ_MODE_DROP, prob_drop_rw=1.0),
            rng=random.Random(7),
        )
        fuzz.sendall(b"vanishes")
        assert fuzz.dropped_writes == 1
        b.settimeout(0.2)
        with pytest.raises(TimeoutError):
            b.recv(16)
        a.close()
        b.close()

    def test_delay_mode_still_delivers(self):
        a, b = self._pair()
        fuzz = FuzzedSocket(
            a,
            FuzzConnConfig(mode=FUZZ_MODE_DELAY, max_delay=0.05),
            rng=random.Random(7),
        )
        t0 = time.monotonic()
        for _ in range(5):
            fuzz.sendall(b"x")
        assert b.recv(16)  # data arrives despite delays
        assert time.monotonic() - t0 < 2.0
        a.close()
        b.close()

    def test_fuzzing_starts_after_delay(self):
        a, b = self._pair()
        fuzz = FuzzedSocket(
            a,
            FuzzConnConfig(mode=FUZZ_MODE_DROP, prob_drop_rw=1.0),
            start_after=30.0,
            rng=random.Random(7),
        )
        fuzz.sendall(b"delivered")  # fuzzing not active yet
        assert b.recv(16) == b"delivered"
        assert fuzz.dropped_writes == 0
        a.close()
        b.close()

    def test_secret_connection_survives_delay_fuzzing(self):
        """An encrypted session over a delay-fuzzed wire still works."""
        a, b = self._pair()
        fa = FuzzedSocket(
            a,
            FuzzConnConfig(mode=FUZZ_MODE_DELAY, max_delay=0.01),
            rng=random.Random(3),
        )
        k1, k2 = ed.gen_priv_key(), ed.gen_priv_key()
        out = {}

        def side_a():
            out["a"] = SecretConnection.make(fa, k1)

        t = threading.Thread(target=side_a, daemon=True)
        t.start()
        sc_b = SecretConnection.make(b, k2)
        t.join(10)
        sc_a = out["a"]
        msg = b"over the fuzzed wire"
        sc_a.write(msg)
        assert sc_b.read_exact(len(msg)) == msg
        sc_a.close()
        sc_b.close()


class TestTrustMetric:
    def test_all_good_is_full_trust(self):
        m = TrustMetric()
        m.good_events(10)
        assert m.trust_score() == 100

    def test_bad_events_lower_trust(self):
        m = TrustMetric()
        m.good_events(1)
        m.bad_events(9)
        assert m.trust_value() < 0.5
        assert 0 <= m.trust_score() <= 100

    def test_history_fades(self):
        m = TrustMetric()
        # a terrible first interval...
        m.bad_events(10)
        m.tick()
        low = m.trust_value()
        # ...then consistently good intervals recover trust
        for _ in range(8):
            m.good_events(10)
            m.tick()
        assert m.trust_value() > low
        assert m.trust_value() > 0.9

    def test_pause_freezes_ticks_until_next_event(self):
        """Reference metric.go: pause stops interval accounting; ANY
        event (good or bad) resumes and is itself counted."""
        m = TrustMetric()
        m.bad_events(10)
        m.tick()
        m.pause()
        history_len = len(m._history)
        m.tick()
        m.tick()
        assert len(m._history) == history_len  # frozen while paused
        m.good_events(1)  # resumes AND counts
        m.tick()
        assert len(m._history) == history_len + 1
        assert m._history[-1] == 1.0

    def test_store(self):
        store = TrustMetricStore()
        a = store.get_peer_trust_metric("peerA")
        assert store.get_peer_trust_metric("peerA") is a
        a.bad_events(5)
        a.tick()
        store.tick_all()
        blob = store.to_json()
        restored = TrustMetricStore()
        restored.from_json(blob)
        assert restored.size() == 1
        assert restored.get_peer_trust_metric("peerA")._history


# -- behaviour reporter over real switches -----------------------------------


class _NopReactor(Reactor):
    def __init__(self, chs):
        super().__init__("nop")
        self.chs = chs

    def get_channels(self):
        return [ChannelDescriptor(id=c, priority=1) for c in self.chs]

    def add_peer(self, peer):
        pass

    def remove_peer(self, peer, reason):
        pass

    def receive(self, ch_id, peer, msg_bytes):
        pass


def _make_switch(network="bhv-chain", chs=(0x01,), pex=False,
                 addr_book=None, seeds=None):
    nk = NodeKey(ed.gen_priv_key())
    channels = bytes(list(chs) + ([PEX_CHANNEL] if pex else []))
    info = NodeInfo(
        protocol_version=ProtocolVersion(),
        node_id=nk.id(),
        listen_addr="127.0.0.1:0",
        network=network,
        channels=channels,
        moniker="peripheral-test",
    )
    t = MultiplexTransport(info, nk)
    t.listen(NetAddress("", "127.0.0.1", 0))
    info.listen_addr = f"127.0.0.1:{t.listen_addr.port}"
    sw = Switch(t, reconnect_interval=0.1)
    sw.add_reactor("nop", _NopReactor(list(chs)))
    pex_r = None
    if pex:
        book = addr_book or AddrBook(file_path="", routability_strict=False)
        pex_r = PEXReactor(
            book, seeds=seeds or [], ensure_peers_period=0.2
        )
        sw.add_reactor("PEX", pex_r)
        sw.addr_book = book
    return sw, pex_r


class TestBehaviourReporter:
    def test_mock_reporter_records(self):
        r = MockReporter()
        r.report(consensus_vote("p1"))
        r.report(bad_message("p1", "garbage"))
        got = r.get_behaviours("p1")
        assert [b.reason for b in got] == ["consensus_vote", "bad_message"]
        assert r.get_behaviours("p2") == []

    def test_switch_reporter_stops_bad_peer(self):
        sw1, _ = _make_switch()
        sw2, _ = _make_switch()
        sw1.start()
        sw2.start()
        try:
            sw2.dial_peer_with_address(sw1.transport.listen_addr)
            _wait(lambda: sw1.peers.size() == 1)
            peer_id = sw1.peers.list()[0].id()
            SwitchReporter(sw1).report(bad_message(peer_id, "bad wire bytes"))
            _wait(lambda: sw1.peers.size() == 0)
            with pytest.raises(ValueError):
                SwitchReporter(sw1).report(block_part("missing-peer"))
        finally:
            sw1.stop()
            sw2.stop()


@pytest.mark.slow
class TestPEXOverRealSwitches:
    def test_addrs_flow_and_third_node_is_dialed(self):
        """C knows only B; B knows A. Via PEX request/response C learns A's
        address and its ensure-peers loop dials A (pex_reactor_test.go
        TestPEXReactorAbuseAttackPeer-adjacent happy path)."""
        sw_a, _ = _make_switch(pex=True)
        sw_b, pex_b = _make_switch(pex=True)
        sw_a.start()
        sw_b.start()
        a_addr = sw_a.transport.listen_addr
        b_addr = sw_b.transport.listen_addr
        try:
            # B dials A so B's book learns A's address
            sw_b.add_persistent_peers([f"{a_addr.id}@127.0.0.1:{a_addr.port}"])
            sw_b.dial_peer_with_address(a_addr)
            _wait(lambda: sw_b.peers.size() == 1)
            pex_b.book.add_address(a_addr, a_addr)

            # C boots knowing only B as seed
            sw_c, pex_c = _make_switch(
                pex=True, seeds=[f"{b_addr.id}@127.0.0.1:{b_addr.port}"]
            )
            sw_c.start()
            try:
                # C must end up connected to BOTH B (seed) and A (learned
                # via a PEX addrs response)
                _wait(
                    lambda: {p.id() for p in sw_c.peers.list()}
                    >= {a_addr.id, b_addr.id},
                    timeout=30.0,
                )
                assert pex_c.book.has_address(a_addr)
            finally:
                sw_c.stop()
        finally:
            sw_b.stop()
            sw_a.stop()


class TestFuzzWiring:
    def test_test_fuzz_wraps_transport_conns(self):
        """[p2p] test_fuzz was inert: the FuzzedSocket existed but no
        transport ever applied it. A node built with the knob on must
        wrap raw conns before the secret-connection upgrade."""
        import tempfile

        from cometbft_tpu.cmd.commands import main as cli_main, _load_config
        from cometbft_tpu.libs.net import free_ports
        from cometbft_tpu.node import default_new_node
        from cometbft_tpu.p2p.fuzz import FuzzedSocket

        with tempfile.TemporaryDirectory() as d:
            cli_main(["--home", d, "init", "--chain-id", "fuzz-wire"])
            (p2p_port,) = free_ports(1)
            cfg = _load_config(d)
            cfg.base.proxy_app = "kvstore"
            cfg.rpc.laddr = ""
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.p2p.test_fuzz = True
            node = default_new_node(cfg)
            try:
                assert node.transport.conn_wrapper is not None

                class _Sock:
                    pass

                wrapped = node.transport.conn_wrapper(_Sock())
                assert isinstance(wrapped, FuzzedSocket)
            finally:
                node._abort_init()
