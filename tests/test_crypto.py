"""Crypto layer tests (reference models: crypto/*/..._test.go)."""

import hashlib

import pytest

from cometbft_tpu.crypto import ed25519, secp256k1, sha256, tmhash
from cometbft_tpu.crypto.batch import CPUBatchVerifier, new_batch_verifier
from cometbft_tpu.crypto import merkle
from cometbft_tpu.crypto.ripemd160 import ripemd160


class TestEd25519:
    def test_sign_verify(self):
        priv = ed25519.gen_priv_key()
        pub = priv.pub_key()
        msg = b"sign me please"
        sig = priv.sign(msg)
        assert len(sig) == 64
        assert pub.verify_signature(msg, sig)
        assert not pub.verify_signature(b"other msg", sig)
        bad = bytearray(sig)
        bad[0] ^= 1
        assert not pub.verify_signature(msg, bytes(bad))

    def test_rfc8032_vector(self):
        # RFC 8032 §7.1 TEST 3
        seed = bytes.fromhex(
            "c5aa8df43f9f837bedb7442f31dcb7b166d38535076f094b85ce3a2e0b4458f7"
        )
        pub = bytes.fromhex(
            "fc51cd8e6218a1a38da47ed00230f0580816ed13ba3303ac5deb911548908025"
        )
        msg = bytes.fromhex("af82")
        sig = bytes.fromhex(
            "6291d657deec24024827e69c3abe01a30ce548a284743a445e3680d7db5ac3ac"
            "18ff9b538d16f290ae67f760984dc6594a7c15e9716ed28dc027beceea1ec40a"
        )
        priv = ed25519.PrivKeyEd25519(seed)
        assert priv.pub_key().bytes() == pub
        assert priv.sign(msg) == sig
        assert priv.pub_key().verify_signature(msg, sig)

    def test_deterministic_keygen(self):
        a = ed25519.gen_priv_key_from_secret(b"secret")
        b = ed25519.gen_priv_key_from_secret(b"secret")
        assert a.bytes() == b.bytes()
        assert a.pub_key() == b.pub_key()

    def test_address_is_truncated_sha(self):
        priv = ed25519.gen_priv_key_from_secret(b"addr")
        pub = priv.pub_key()
        assert pub.address() == hashlib.sha256(pub.bytes()).digest()[:20]
        assert len(pub.address()) == 20

    def test_malformed_sig_len(self):
        priv = ed25519.gen_priv_key()
        assert not priv.pub_key().verify_signature(b"m", b"short")


class TestSecp256k1:
    def test_sign_verify(self):
        priv = secp256k1.gen_priv_key_from_secret(b"sec")
        pub = priv.pub_key()
        assert len(pub.bytes()) == 33
        msg = b"hello secp"
        sig = priv.sign(msg)
        assert len(sig) == 64
        assert pub.verify_signature(msg, sig)
        assert not pub.verify_signature(b"tampered", sig)

    def test_deterministic_signature(self):
        priv = secp256k1.gen_priv_key_from_secret(b"rfc6979")
        assert priv.sign(b"m") == priv.sign(b"m")

    def test_low_s_enforced(self):
        priv = secp256k1.gen_priv_key_from_secret(b"lows")
        sig = priv.sign(b"m")
        s = int.from_bytes(sig[32:], "big")
        n = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
        assert s <= n // 2
        # the high-S form of a valid sig must be rejected
        high = sig[:32] + (n - s).to_bytes(32, "big")
        assert not priv.pub_key().verify_signature(b"m", high)

    def test_address_len(self):
        pub = secp256k1.gen_priv_key_from_secret(b"a").pub_key()
        assert len(pub.address()) == 20


class TestRipemd160:
    def test_vectors(self):
        # standard RIPEMD-160 test vectors (Dobbertin et al.)
        assert ripemd160(b"").hex() == "9c1185a5c5e9fc54612808977ee8f548b2258d31"
        assert (
            ripemd160(b"abc").hex() == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc"
        )
        assert (
            ripemd160(b"message digest").hex()
            == "5d0689ef49d2fae572b881b123a85ffa21595f36"
        )
        assert (
            ripemd160(b"a" * 1000000).hex()
            == "52783243c1697bdbe16d37f97f68f08325dc1528"
        )


class TestMerkle:
    def test_rfc6962_empty_and_leaf(self):
        # RFC 6962 test vectors (same layout as reference tree.go)
        assert merkle.hash_from_byte_slices([]) == hashlib.sha256(b"").digest()
        assert (
            merkle.leaf_hash(b"").hex()
            == "6e340b9cffb37a989ca544e6bb780a2c78901d3fb33738768511a30617afa01d"
        )
        assert (
            merkle.hash_from_byte_slices([b"L123456"]).hex()
            == "395aa064aa4c29f7010acfe3f25db9485bbd4b91897b6ad7ad547639252b4d56"
        )

    def test_inner_split(self):
        items = [b"a", b"b", b"c"]
        root = merkle.hash_from_byte_slices(items)
        l = merkle.inner_hash(merkle.leaf_hash(b"a"), merkle.leaf_hash(b"b"))
        expect = merkle.inner_hash(l, merkle.leaf_hash(b"c"))
        assert root == expect

    @pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 9, 33, 100])
    def test_proofs(self, n):
        items = [bytes([i]) * 3 for i in range(n)]
        root, proofs = merkle.proofs_from_byte_slices(items)
        assert root == merkle.hash_from_byte_slices(items)
        for i, p in enumerate(proofs):
            p.verify(root, items[i])
            with pytest.raises(ValueError):
                p.verify(root, b"wrong leaf")
        # cross-proof misuse: proof i must not verify item j
        if n >= 2:
            with pytest.raises(ValueError):
                proofs[0].verify(root, items[1])

    def test_split_point(self):
        assert merkle.get_split_point(2) == 1
        assert merkle.get_split_point(3) == 2
        assert merkle.get_split_point(8) == 4
        assert merkle.get_split_point(9) == 8


class TestBatchVerifier:
    def _mk(self, n, bad=()):
        triples = []
        for i in range(n):
            priv = ed25519.gen_priv_key_from_secret(f"k{i}".encode())
            msg = f"msg {i}".encode()
            sig = priv.sign(msg)
            if i in bad:
                sig = sig[:-1] + bytes([sig[-1] ^ 1])
            triples.append((priv.pub_key(), msg, sig))
        return triples

    def test_cpu_all_valid(self):
        bv = CPUBatchVerifier()
        for pk, m, s in self._mk(16):
            bv.add(pk, m, s)
        assert bv.count() == 16
        ok, mask = bv.verify()
        assert ok and mask == [True] * 16
        assert bv.count() == 0  # reset

    def test_cpu_mixed_validity(self):
        bv = CPUBatchVerifier()
        for pk, m, s in self._mk(8, bad={2, 5}):
            bv.add(pk, m, s)
        ok, mask = bv.verify()
        assert not ok
        assert [i for i, v in enumerate(mask) if not v] == [2, 5]

    def test_empty_batch(self):
        ok, mask = CPUBatchVerifier().verify()
        assert not ok and mask == []

    def test_mixed_key_types(self):
        bv = CPUBatchVerifier()
        e = ed25519.gen_priv_key_from_secret(b"e")
        s = secp256k1.gen_priv_key_from_secret(b"s")
        bv.add(e.pub_key(), b"m1", e.sign(b"m1"))
        bv.add(s.pub_key(), b"m2", s.sign(b"m2"))
        ok, mask = bv.verify()
        assert ok and mask == [True, True]

    def test_registry(self):
        assert isinstance(new_batch_verifier("cpu"), CPUBatchVerifier)
        with pytest.raises(ValueError):
            new_batch_verifier("quantum")

    def test_verify_many_parity_with_serial(self):
        # fast loop / native call must be bit-identical to verify_signature,
        # including malformed sig and pubkey shapes
        triples = self._mk(100, bad={3, 71})
        pk0, m0, s0 = triples[0]
        triples[10] = (pk0, m0, s0[:40])           # short sig
        triples[11] = (ed25519.PubKeyEd25519(b"\xff" * 32), m0, s0)
        expected = [pk.verify_signature(m, s) for pk, m, s in triples]
        assert ed25519.verify_many(triples) == expected

    def test_native_verify_batch_parity(self):
        from cometbft_tpu import native

        triples = self._mk(80, bad={1, 40})
        mask = native.ed25519_verify_batch(
            [pk.bytes() for pk, _, _ in triples],
            [m for _, m, _ in triples],
            [s for _, _, s in triples],
            nthreads=4,
        )
        if mask is None:
            pytest.skip("native verifier unavailable (no toolchain/libcrypto)")
        expected = [pk.verify_signature(m, s) for pk, m, s in triples]
        assert mask == expected



    def test_native_challenges_parity(self):
        """cbft_ed25519_challenges vs the hashlib + big-int oracle,
        including skipped (absent) lanes and empty messages."""
        import hashlib
        import random

        import numpy as np

        from cometbft_tpu import native

        L = 2**252 + 27742317777372353535851937790883648493
        rng = random.Random(5)
        n = 120
        pk = np.frombuffer(
            bytes(rng.randrange(256) for _ in range(n * 32)), np.uint8
        ).reshape(n, 32)
        r = np.frombuffer(
            bytes(rng.randrange(256) for _ in range(n * 32)), np.uint8
        ).reshape(n, 32)
        valid = [rng.random() > 0.15 for _ in range(n)]
        msgs = [
            bytes(rng.randrange(256) for _ in range(rng.randrange(0, 150)))
            if v
            else None
            for v in valid
        ]
        raw = native.ed25519_challenges(pk.tobytes(), r.tobytes(), msgs, valid)
        if raw is None:
            pytest.skip("native challenges unavailable")
        got = np.frombuffer(raw, np.uint8).reshape(n, 32)
        for i in range(n):
            if not valid[i]:
                assert not got[i].any()
                continue
            h = (
                int.from_bytes(
                    hashlib.sha512(
                        r[i].tobytes() + pk[i].tobytes() + msgs[i]
                    ).digest(),
                    "little",
                )
                % L
            )
            assert got[i].tobytes() == h.to_bytes(32, "little"), i

    def test_device_plane_down_routes_to_cpu(self, monkeypatch):
        """A wedged TPU tunnel must degrade the tpu backend to CPU
        routing (bounded probe verdict), never hang or change results."""
        import threading

        from cometbft_tpu.crypto import batch as cryptobatch

        # stub the probe machinery BEFORE constructing the verifier:
        # the real probe thread would race the forced verdict (and a
        # successful cpu-env probe would flip it back to True mid-test)
        monkeypatch.setattr(
            cryptobatch, "start_device_probe", lambda: None
        )
        done = threading.Event()
        done.set()
        monkeypatch.setattr(cryptobatch, "_probe_done", done)
        monkeypatch.setattr(cryptobatch, "_probe_ok", False)
        bv = cryptobatch.TPUBatchVerifier(min_batch=1, slow_curve_min_batch=1, secp_min_batch=1)
        for pk, m, s in self._mk(8, bad={2}):
            bv.add(pk, m, s)
        ok, mask = bv.verify()
        assert not ok
        assert [i for i, v in enumerate(mask) if not v] == [2]


class TestHashers:
    def test_tmhash(self):
        assert tmhash.sum(b"x") == hashlib.sha256(b"x").digest()
        assert tmhash.sum_truncated(b"x") == hashlib.sha256(b"x").digest()[:20]
        assert sha256(b"") == hashlib.sha256(b"").digest()

