"""TPU batched ed25519 — bit-identical parity with the CPU verifier.

The north-star contract (BASELINE.json): accept/reject from the JAX batch
kernel must match the serial CPU path (crypto/ed25519/ed25519.go:148
semantics) on valid, corrupted, and adversarial edge-case signatures.
Runs on the virtual 8-device CPU mesh (conftest.py).
"""

import numpy as np
import pytest

from cometbft_tpu.crypto import batch as cbatch
from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.crypto.tpu import ed25519_batch, field as fe


def _cpu_verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    return ed.PubKeyEd25519(pk).verify_signature(msg, sig)


def _assert_parity(pks, msgs, sigs):
    got = ed25519_batch.verify_batch(pks, msgs, sigs)
    want = [_cpu_verify(p, m, s) for p, m, s in zip(pks, msgs, sigs)]
    assert got == want, f"mismatch: tpu={got} cpu={want}"
    return got


def _fe1(n: int):
    """One field element in the kernel's limb-major [17, 1] layout."""
    import jax.numpy as jnp

    return jnp.array(fe.int_to_limbs(n), jnp.int32)[:, None]


def _fe_int(x) -> int:
    return fe.limbs_to_int(np.asarray(fe.to_canonical(x))[:, 0])


class TestField:
    def test_roundtrip_and_ops(self):
        rng = np.random.default_rng(7)

        for _ in range(20):
            a = int(rng.integers(0, 2**63)) * int(rng.integers(0, 2**63)) % fe.P
            b = int(rng.integers(0, 2**63)) ** 3 % fe.P
            fa, fb = _fe1(a), _fe1(b)
            assert _fe_int(fe.add(fa, fb)) == (a + b) % fe.P
            assert _fe_int(fe.sub(fa, fb)) == (a - b) % fe.P
            assert _fe_int(fe.mul(fa, fb)) == (a * b) % fe.P

    def test_invert(self):
        a = 0xDEADBEEFCAFEBABE1234567890ABCDEF
        inv = _fe_int(fe.invert(_fe1(a)))
        assert a * inv % fe.P == 1

    def test_pow_p58(self):
        a = 0x1234567890ABCDEF ** 3 % fe.P
        got = _fe_int(fe.pow_p58(_fe1(a)))
        assert got == pow(a, (fe.P - 5) // 8, fe.P)

    def test_weak_input_canonicalized(self):
        # value p + 5 in limbs (non-canonical but weakly reduced)
        assert _fe_int(_fe1(fe.P + 5)) == 5

    @pytest.mark.parametrize("impl", sorted(fe._MUL_IMPLS))
    def test_every_mul_impl_matches_oracle(self, impl):
        """All CBFT_TPU_MUL forms must agree with the big-int oracle —
        the TPU default (stack) and the f32 form otherwise run only on
        hardware, never under CI's CPU-platform default (matmul)."""
        mul = fe._MUL_IMPLS[impl]
        rng = np.random.default_rng(impl.encode()[0])
        for _ in range(8):
            a = int(rng.integers(0, 2**63)) ** 5 % fe.P
            b = int(rng.integers(0, 2**63)) ** 7 % fe.P
            got = _fe_int(mul(_fe1(a), _fe1(b)))
            assert got == a * b % fe.P, impl
        # chained squarings push the weakly-reduced (non-canonical)
        # intermediate representation through each impl's bound analysis
        x = _fe1(fe.P - 2)
        for _ in range(6):
            x = mul(x, x)
        assert _fe_int(x) == pow(fe.P - 2, 2**6, fe.P), impl


class TestWireUnpack:
    """Device-side unpack of the compact u32 wire vs independent numpy
    oracles — the wire format is the dispatch ABI, so a silent bit-slip
    here would corrupt every lane."""

    def test_fe_limbs_match_int_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(11)
        raw = rng.integers(0, 256, size=(9, 32)).astype(np.uint8)
        words = jnp.asarray(ed25519_batch._le_words(raw))
        got = np.asarray(ed25519_batch.unpack_fe_limbs(words))
        for b in range(raw.shape[0]):
            val = int.from_bytes(raw[b].tobytes(), "little") & ((1 << 255) - 1)
            assert fe.limbs_to_int(got[:, b]) == val, b
            assert all(0 <= int(v) < 2**15 for v in got[:, b])

    def test_digits_match_bit_oracle(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(13)
        raw = rng.integers(0, 256, size=(7, 32)).astype(np.uint8)
        words = jnp.asarray(ed25519_batch._le_words(raw))
        got = np.asarray(ed25519_batch.unpack_digits(words))
        bits = np.unpackbits(raw, axis=-1, bitorder="little")
        digits = bits[:, 0:254:2] + 2 * bits[:, 1:254:2]
        want = np.ascontiguousarray(digits[:, ::-1].astype(np.int32).T)
        assert (got == want).all()

    def test_sign_bits_through_production_unpack(self):
        import jax.numpy as jnp

        pk = np.zeros((2, 32), np.uint8)
        pk[1, 31] = 0x80  # A sign bit set on lane 1
        r = np.zeros((2, 32), np.uint8)
        r[0, 31] = 0x80  # R sign bit set on lane 0
        zero = np.zeros((2, 32), np.uint8)
        wire = jnp.asarray(
            np.concatenate(
                [ed25519_batch._le_words(a) for a in (pk, r, zero, zero)],
                axis=0,
            )
        )
        ay, a_sign, r_y, r_sign, s_dig, h_dig = ed25519_batch.unpack_wire(wire)
        assert list(np.asarray(a_sign)) == [0, 1]
        assert list(np.asarray(r_sign)) == [1, 0]
        # and the sign bit never leaks into the limbs
        assert fe.limbs_to_int(np.asarray(ay)[:, 1]) == 0
        assert fe.limbs_to_int(np.asarray(r_y)[:, 0]) == 0


class TestVerifyBatchParity:
    def test_valid_signatures(self):
        keys = [ed.gen_priv_key_from_secret(bytes([i])) for i in range(8)]
        msgs = [b"vote %d" % i for i in range(8)]
        sigs = [k.sign(m) for k, m in zip(keys, msgs)]
        pks = [k.pub_key().bytes() for k in keys]
        got = _assert_parity(pks, msgs, sigs)
        assert all(got)

    def test_corrupted_signature_rejected(self):
        k = ed.gen_priv_key_from_secret(b"x")
        msg = b"block part"
        sig = bytearray(k.sign(msg))
        pks, msgs, sigs = [], [], []
        # flip a bit in R, in S, and in the message
        for variant in range(3):
            s = bytearray(sig)
            m = msg
            if variant == 0:
                s[0] ^= 1
            elif variant == 1:
                s[40] ^= 0x80
            else:
                m = b"other msg"
            pks.append(k.pub_key().bytes())
            msgs.append(m)
            sigs.append(bytes(s))
        got = _assert_parity(pks, msgs, sigs)
        assert not any(got)

    def test_wrong_pubkey_rejected(self):
        k1 = ed.gen_priv_key_from_secret(b"a")
        k2 = ed.gen_priv_key_from_secret(b"b")
        msg = b"proposal"
        got = _assert_parity([k2.pub_key().bytes()], [msg], [k1.sign(msg)])
        assert got == [False]

    def test_noncanonical_s_rejected(self):
        k = ed.gen_priv_key_from_secret(b"s")
        msg = b"m"
        sig = bytearray(k.sign(msg))
        s_int = int.from_bytes(sig[32:], "little") + fe.L
        sig[32:] = s_int.to_bytes(32, "little")
        got = _assert_parity([k.pub_key().bytes()], [msg], [bytes(sig)])
        assert got == [False]

    def test_mixed_batch(self):
        rng = np.random.default_rng(3)
        pks, msgs, sigs, expect = [], [], [], []
        for i in range(33):  # odd size → exercises padding
            k = ed.gen_priv_key_from_secret(bytes([i, 1]))
            m = rng.bytes(rng.integers(0, 200))
            s = bytearray(k.sign(m))
            good = i % 3 != 0
            if not good:
                s[rng.integers(0, 64)] ^= 1 << rng.integers(0, 8)
            pks.append(k.pub_key().bytes())
            msgs.append(bytes(m))
            sigs.append(bytes(s))
            expect.append(good)
        got = _assert_parity(pks, msgs, sigs)
        # corrupt sigs could theoretically still verify; parity is the real
        # assertion — but sanity-check the good ones accepted
        for i, e in enumerate(expect):
            if e:
                assert got[i]

    def test_garbage_pubkey(self):
        # all-0xff y is not on the curve → decompression failure path
        pks = [b"\xff" * 32, b"\x00" * 32]
        msgs = [b"m1", b"m2"]
        k = ed.gen_priv_key_from_secret(b"g")
        sigs = [k.sign(b"m1"), k.sign(b"m2")]
        _assert_parity(pks, msgs, sigs)

    def test_identity_pubkey_parity(self):
        # A = neutral element (y=1, x=0): [h]A vanishes, check degenerates
        # to [s]B == R. Craft an "accepting" signature without any secret:
        # pick s, set R = encode([s]B). Parity with OpenSSL matters most.
        import jax.numpy as jnp

        ident_pk = (1).to_bytes(32, "little")
        s = 12345
        s_bytes = s.to_bytes(32, "little")
        # compute [s]B via the kernel's own point ops on host python ints
        bx, by = ed25519_batch._BX, ed25519_batch._BY

        def edwards_add(p, q):
            (x1, y1), (x2, y2) = p, q
            den = fe.D * x1 * x2 * y1 * y2 % fe.P
            x3 = (x1 * y2 + x2 * y1) * pow(1 + den, fe.P - 2, fe.P) % fe.P
            y3 = (y1 * y2 + x1 * x2) * pow(1 - den, fe.P - 2, fe.P) % fe.P
            return (x3, y3)

        acc = (0, 1)
        base = (bx, by)
        for bit in bin(s)[2:]:
            acc = edwards_add(acc, acc)
            if bit == "1":
                acc = edwards_add(acc, base)
        r_enc = bytearray(acc[1].to_bytes(32, "little"))
        r_enc[31] |= (acc[0] & 1) << 7
        sig = bytes(r_enc) + s_bytes
        _assert_parity([ident_pk], [b"any message"], [sig])

    def test_wrong_length_inputs(self):
        k = ed.gen_priv_key_from_secret(b"l")
        got = ed25519_batch.verify_batch(
            [k.pub_key().bytes()], [b"m"], [b"\x01" * 63]
        )
        assert got == [False]

    def test_empty_batch(self):
        assert ed25519_batch.verify_batch([], [], []) == []


class TestDeviceHashMode:
    """CBFT_TPU_HASH=device: SHA-512 + sc_reduce + digits run on-device in
    the same dispatch as the group math. Accept/reject must stay
    bit-identical — including on small-order keys, where an inexact mod-L
    would change [h](-A)."""

    @pytest.fixture(autouse=True)
    def _device_hash(self, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_HASH", "device")

    def test_valid_and_corrupted(self):
        rng = np.random.default_rng(5)
        pks, msgs, sigs = [], [], []
        for i in range(9):
            k = ed.gen_priv_key_from_secret(bytes([i, 21]))
            m = rng.bytes(int(rng.integers(0, 300)))  # ragged block counts
            s = bytearray(k.sign(m))
            if i % 3 == 0:
                s[rng.integers(0, 64)] ^= 1
            pks.append(k.pub_key().bytes())
            msgs.append(bytes(m))
            sigs.append(bytes(s))
        _assert_parity(pks, msgs, sigs)

    def test_small_order_pubkey(self):
        # identity A: [h]A = 0 for h ≡ 0 mod ord(A)=1 — any h works, but
        # torsion points of order 8 make the result depend on h mod 8·L,
        # so the device mod-L must be exact. y = -1 has order 4.
        order4 = ((fe.P - 1) % fe.P).to_bytes(32, "little")
        k = ed.gen_priv_key_from_secret(b"t")
        msgs = [b"torsion", b"torsion2"]
        sigs = [k.sign(msgs[0]), b"\x01" * 64]
        _assert_parity([order4, order4], msgs, sigs)

    def test_wrong_lengths_and_empty(self):
        k = ed.gen_priv_key_from_secret(b"l2")
        got = ed25519_batch.verify_batch(
            [k.pub_key().bytes(), b"short"], [b"m", b"m"], [b"\x01" * 63, b"\x02" * 64]
        )
        assert got == [False, False]
        assert ed25519_batch.verify_batch([], [], []) == []


class TestTPUBatchVerifier:
    def test_small_batches_route_to_cpu_kernel_above_threshold(self):
        """The tpu boundary verifies small batches on CPU (measured
        crossover ~1k sigs) but MUST still drive the device kernel when
        forced below threshold — guards the hybrid routing both ways."""
        from cometbft_tpu.crypto.batch import TPUBatchVerifier

        keys = [ed.gen_priv_key_from_secret(bytes([i, 11])) for i in range(4)]
        bv = TPUBatchVerifier(min_batch=2)  # force the kernel path
        for i, k in enumerate(keys):
            msg = b"kernel path %d" % i
            sig = k.sign(msg) if i != 1 else b"\x11" * 64
            bv.add(k.pub_key(), msg, sig)
        ok, mask = bv.verify()
        assert not ok
        assert mask == [True, False, True, True]

    def test_default_threshold_keeps_small_batches_off_device(self, monkeypatch):
        from cometbft_tpu.crypto.tpu import ed25519_batch

        def boom(*a, **k):
            raise AssertionError("kernel dispatched for a small batch")

        monkeypatch.setattr(ed25519_batch, "verify_batch", boom)
        bv = cbatch.new_batch_verifier("tpu")  # default min_batch
        keys = [ed.gen_priv_key_from_secret(bytes([i, 13])) for i in range(6)]
        for i, k in enumerate(keys):
            m = b"cpu route %d" % i
            bv.add(k.pub_key(), m, k.sign(m))
        ok, mask = bv.verify()
        assert ok and all(mask)

    def test_backend_routing(self):
        bv = cbatch.new_batch_verifier("tpu")
        keys = [ed.gen_priv_key_from_secret(bytes([i, 9])) for i in range(5)]
        for i, k in enumerate(keys):
            msg = b"height %d" % i
            sig = k.sign(msg) if i != 2 else b"\x00" * 64
            bv.add(k.pub_key(), msg, sig)
        ok, mask = bv.verify()
        assert not ok
        assert mask == [True, True, False, True, True]
        assert bv.count() == 0

    def test_matches_cpu_backend(self):
        keys = [ed.gen_priv_key_from_secret(bytes([i, 7])) for i in range(6)]
        entries = []
        for i, k in enumerate(keys):
            msg = b"commit sig %d" % i
            sig = bytearray(k.sign(msg))
            if i % 2:
                sig[10] ^= 4
            entries.append((k.pub_key(), msg, bytes(sig)))
        results = []
        for backend in ("cpu", "tpu"):
            bv = cbatch.new_batch_verifier(backend)
            for pk, msg, sig in entries:
                bv.add(pk, msg, sig)
            results.append(bv.verify())
        assert results[0] == results[1]


class TestValsetResident:
    """Device-resident valset verification (verify_valset_resident):
    per-lane accept/reject must be bit-identical to verify_batch, with
    absent lanes masked False, across multiple resident chunks, and the
    cache must be reused by valset_id."""

    def _valset(self, n, tag=77):
        keys = [ed.gen_priv_key_from_secret(bytes([i, tag])) for i in range(n)]
        return keys, [k.pub_key().bytes() for k in keys]

    def test_parity_with_absent_and_invalid_lanes(self, monkeypatch):
        # chunk cap 64 (= the kernel's min pad) + 100 lanes → 2 resident
        # chunks, with absent/corrupt lanes in BOTH chunks
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "64")
        ed25519_batch._resident_cache.clear()
        n = 100
        keys, pks = self._valset(n)
        msgs, sigs = [], []
        for i, k in enumerate(keys):
            if i in (3, 70):  # absent lanes (nil votes)
                msgs.append(None)
                sigs.append(None)
                continue
            m = b"resident vote %d" % i
            s = bytearray(k.sign(m))
            if i in (5, 90):
                s[9] ^= 1  # corrupt
            if i == 65:
                s[32:] = ed25519_batch.L.to_bytes(32, "little")  # s = L
            msgs.append(m)
            sigs.append(bytes(s))
        import hashlib as h

        vid = h.sha256(b"".join(pks)).digest()
        got = ed25519_batch.verify_valset_resident(vid, pks, msgs, sigs)
        assert len(ed25519_batch._resident_cache[vid].chunks) == 2
        want = []
        for i in range(n):
            if msgs[i] is None:
                want.append(False)
            else:
                want.append(
                    ed.PubKeyEd25519(pks[i]).verify_signature(
                        msgs[i], sigs[i]
                    )
                )
        assert got == want
        for i in (3, 5, 65, 70, 90):
            assert not got[i]
        assert sum(got) == n - 5

    def test_cache_reused_across_commits_and_evicted_by_lru(self, monkeypatch):
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        ed25519_batch._resident_cache.clear()
        import hashlib as h

        keys, pks = self._valset(8, tag=78)
        vid = h.sha256(b"".join(pks)).digest()
        for height in range(2):
            msgs = [b"h%d vote %d" % (height, i) for i in range(8)]
            sigs = [k.sign(m) for k, m in zip(keys, msgs)]
            assert all(
                ed25519_batch.verify_valset_resident(vid, pks, msgs, sigs)
            )
        assert len(ed25519_batch._resident_cache) == 1  # one set, reused
        # rotate through >MAX distinct valsets: LRU bounds the cache
        for tag in range(100, 100 + ed25519_batch._RESIDENT_CACHE_MAX + 2):
            ks, ps = self._valset(4, tag=tag)
            v = h.sha256(b"".join(ps)).digest()
            m = [b"x"] * 4
            s = [k.sign(b"x") for k in ks]
            assert all(ed25519_batch.verify_valset_resident(v, ps, m, s))
        assert (
            len(ed25519_batch._resident_cache)
            == ed25519_batch._RESIDENT_CACHE_MAX
        )

    def test_verify_commit_routes_resident(self, monkeypatch):
        """End-to-end: ValidatorSet.verify_commit under the tpu backend
        takes the resident path when the floor allows, with behavior
        identical to the cpu backend."""
        monkeypatch.setenv("CBFT_TPU_MIN_BATCH", "1")
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        ed25519_batch._resident_cache.clear()
        from cometbft_tpu.types.test_util import (
            deterministic_validator_set,
            make_block_id,
            make_commit,
        )

        vset, privs = deterministic_validator_set(6)
        bid = make_block_id()
        commit = make_commit(bid, 5, 1, vset, privs, "res-chain")
        vset.verify_commit("res-chain", bid, 5, commit, backend="cpu")
        vset.verify_commit("res-chain", bid, 5, commit, backend="tpu")
        assert len(ed25519_batch._resident_cache) == 1  # resident path ran
        # corrupt one signature: both backends must reject identically
        bad = bytearray(commit.signatures[2].signature)
        bad[6] ^= 1
        commit.signatures[2].signature = bytes(bad)
        import pytest as _pytest

        with _pytest.raises(ValueError):
            vset.verify_commit("res-chain", bid, 5, commit, backend="cpu")
        with _pytest.raises(ValueError):
            vset.verify_commit("res-chain", bid, 5, commit, backend="tpu")


class TestChunkedPipelineParity:
    """The double-buffered chunked dispatch (the DEFAULT verify_batch
    path) must be bit-identical to a single dispatch and to the CPU
    serial verifier on adversarial batches: one corrupt signature
    walked across every chunk position, at sizes straddling the chunk
    boundary (cap 64 = the kernel's min pad, so 63/64/65/127/128/129
    cover last-lane-of-chunk, exact-fill, and one-lane-overflow)."""

    _POOL = {}

    def _pool(self, n):
        """n deterministic (pk, msg, sig) lanes, memoized — signing 129
        keys once keeps the walk over positions cheap."""
        if n not in self._POOL:
            keys = [
                ed.gen_priv_key_from_secret(b"chunk-%d" % i) for i in range(n)
            ]
            msgs = [b"pipelined vote %d" % i for i in range(n)]
            self._POOL[n] = (
                [k.pub_key().bytes() for k in keys],
                msgs,
                [k.sign(m) for k, m in zip(keys, msgs)],
            )
        pks, msgs, sigs = self._POOL[n]
        return list(pks), list(msgs), list(sigs)

    @pytest.mark.parametrize("size", [63, 64, 65, 127, 128, 129])
    def test_one_bad_lane_per_chunk_position(self, size, monkeypatch):
        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "64")
        pks, msgs, sigs = self._pool(size)
        # positions that matter for chunk reassembly: first/last lane of
        # each chunk, the boundary straddle, and the final ragged lane
        positions = sorted(
            {0, size - 1}
            | {p for p in (63, 64, 65, 127, 128) if p < size}
        )
        for bad in positions:
            s = list(sigs)
            corrupted = bytearray(s[bad])
            corrupted[8] ^= 1
            s[bad] = bytes(corrupted)
            got = ed25519_batch.verify_batch(pks, msgs, s)
            want = [i != bad for i in range(size)]
            # the corrupt lane must reject and, critically, reassembly
            # must not smear the verdict onto any neighbor lane
            assert got == want, f"size={size} bad={bad}: {got}"

    def test_pipelined_matches_single_dispatch_and_cpu(self, monkeypatch):
        """Same adversarial batch through three dispatch shapes — chunked
        double-buffered (depth 2), chunked serial (depth 1), and one
        unchunked dispatch — all equal to the CPU reference."""
        n = 129
        pks, msgs, sigs = self._pool(n)
        for i in range(0, n, 7):  # corrupt every 7th lane
            b = bytearray(sigs[i])
            b[40] ^= 0x80
            sigs[i] = bytes(b)
        want = [
            ed.PubKeyEd25519(p).verify_signature(m, s)
            for p, m, s in zip(pks, msgs, sigs)
        ]

        monkeypatch.setenv("CBFT_TPU_MAX_CHUNK", "64")
        monkeypatch.delenv("CBFT_TPU_PIPELINE_DEPTH", raising=False)
        assert ed25519_batch.verify_batch(pks, msgs, sigs) == want

        monkeypatch.setenv("CBFT_TPU_PIPELINE_DEPTH", "1")
        assert ed25519_batch.verify_batch(pks, msgs, sigs) == want

        monkeypatch.delenv("CBFT_TPU_PIPELINE_DEPTH", raising=False)
        monkeypatch.delenv("CBFT_TPU_MAX_CHUNK", raising=False)
        assert ed25519_batch.verify_batch(pks, msgs, sigs) == want
