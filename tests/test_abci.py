"""ABCI layer: message codecs, local + socket clients, kvstore apps.

Modeled on the reference's abci tests (abci/tests, example tests) —
envelope roundtrips, app semantics, and the socket transport end-to-end.
"""

import base64
import os
import tempfile

import pytest

from cometbft_tpu.abci import types as abci
from cometbft_tpu.abci.client import LocalClient, SocketClient
from cometbft_tpu.abci.kvstore import (
    KVStoreApplication,
    PersistentKVStoreApplication,
)
from cometbft_tpu.abci.server import SocketServer
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.proto.keys import PublicKeyProto


class TestCodecs:
    def test_request_envelope_roundtrip_all_kinds(self):
        samples = {
            "echo": abci.RequestEcho("hi"),
            "flush": abci.RequestFlush(),
            "info": abci.RequestInfo("v1", 11, 8),
            "set_option": abci.RequestSetOption("k", "v"),
            "init_chain": abci.RequestInitChain(chain_id="c", initial_height=5),
            "query": abci.RequestQuery(data=b"q", path="/p", height=3, prove=True),
            "check_tx": abci.RequestCheckTx(tx=b"t", type=abci.CHECK_TX_TYPE_RECHECK),
            "deliver_tx": abci.RequestDeliverTx(tx=b"x"),
            "end_block": abci.RequestEndBlock(height=9),
            "commit": abci.RequestCommit(),
            "list_snapshots": abci.RequestListSnapshots(),
            "offer_snapshot": abci.RequestOfferSnapshot(
                snapshot=abci.Snapshot(1, 2, 3, b"h", b"m"), app_hash=b"a"
            ),
            "load_snapshot_chunk": abci.RequestLoadSnapshotChunk(1, 2, 3),
            "apply_snapshot_chunk": abci.RequestApplySnapshotChunk(1, b"c", "s"),
        }
        for kind, msg in samples.items():
            env = abci.Request(kind, msg)
            dec = abci.Request.decode(env.encode())
            assert dec.kind == kind
            assert dec.value == msg, kind

    def test_response_envelope_roundtrip(self):
        samples = {
            "exception": abci.ResponseException("boom"),
            "info": abci.ResponseInfo("d", "v", 1, 10, b"hash"),
            "check_tx": abci.ResponseCheckTx(code=1, gas_wanted=5, priority=7),
            "deliver_tx": abci.ResponseDeliverTx(
                code=0,
                data=b"d",
                events=[
                    abci.Event("e", [abci.EventAttribute(b"k", b"v", True)])
                ],
            ),
            "end_block": abci.ResponseEndBlock(
                validator_updates=[
                    abci.ValidatorUpdate(PublicKeyProto("ed25519", b"\x01" * 32), 7)
                ]
            ),
            "commit": abci.ResponseCommit(data=b"apphash", retain_height=3),
        }
        for kind, msg in samples.items():
            dec = abci.Response.decode(abci.Response(kind, msg).encode())
            assert dec.kind == kind and dec.value == msg, kind

    def test_fork_extension_fields(self):
        r = abci.ResponseInitChain(
            app_hash=b"h",
            rollapp_params=abci.RollappParams(da="celestia", drs_version=2),
            genesis_bridge_data_bytes=b"gb",
        )
        dec = abci.ResponseInitChain.decode(r.encode())
        assert dec.rollapp_params == abci.RollappParams("celestia", 2)
        assert dec.genesis_bridge_data_bytes == b"gb"
        q = abci.RequestInitChain(chain_id="c", genesis_checksum="abc123")
        assert abci.RequestInitChain.decode(q.encode()).genesis_checksum == "abc123"


class TestKVStore:
    def test_deliver_commit_query(self):
        app = KVStoreApplication()
        assert app.deliver_tx(abci.RequestDeliverTx(b"name=satoshi")).is_ok()
        res = app.commit()
        assert len(res.data) == 8
        q = app.query(abci.RequestQuery(data=b"name"))
        assert q.value == b"satoshi" and q.log == "exists"
        q2 = app.query(abci.RequestQuery(data=b"missing"))
        assert q2.value == b"" and q2.log == "does not exist"
        info = app.info(abci.RequestInfo())
        assert info.last_block_height == 1
        assert info.last_block_app_hash == res.data

    def test_raw_tx_uses_tx_as_key_and_value(self):
        app = KVStoreApplication()
        app.deliver_tx(abci.RequestDeliverTx(b"solo"))
        assert app.query(abci.RequestQuery(data=b"solo")).value == b"solo"

    def test_persistent_validator_updates(self):
        app = PersistentKVStoreApplication()
        pk = ed25519.gen_priv_key_from_secret(b"v1").pub_key()
        b64 = base64.b64encode(pk.bytes()).decode()
        tx = PersistentKVStoreApplication.make_val_set_change_tx(b64, 10)
        app.begin_block(abci.RequestBeginBlock())
        assert app.deliver_tx(abci.RequestDeliverTx(tx)).is_ok()
        updates = app.end_block(abci.RequestEndBlock(height=1)).validator_updates
        assert len(updates) == 1 and updates[0].power == 10
        assert len(app.validators()) == 1
        # remove
        app.begin_block(abci.RequestBeginBlock())
        tx0 = PersistentKVStoreApplication.make_val_set_change_tx(b64, 0)
        assert app.deliver_tx(abci.RequestDeliverTx(tx0)).is_ok()
        assert len(app.validators()) == 0

    def test_bad_validator_tx(self):
        app = PersistentKVStoreApplication()
        res = app.deliver_tx(abci.RequestDeliverTx(b"val:garbage-no-bang"))
        assert not res.is_ok()


class TestLocalClient:
    def test_sync_calls(self):
        c = LocalClient(KVStoreApplication())
        c.start()
        try:
            assert c.echo_sync("ping").message == "ping"
            assert c.deliver_tx_sync(abci.RequestDeliverTx(b"a=b")).is_ok()
            assert len(c.commit_sync().data) == 8
        finally:
            c.stop()

    def test_async_callback(self):
        c = LocalClient(KVStoreApplication())
        c.start()
        got = []
        rr = c.check_tx_async(abci.RequestCheckTx(tx=b"t"))
        rr.set_callback(lambda res: got.append(res.kind))
        assert got == ["check_tx"]
        c.stop()


class TestSocketTransport:
    def test_end_to_end_over_unix_socket(self):
        with tempfile.TemporaryDirectory() as d:
            addr = f"unix://{os.path.join(d, 'abci.sock')}"
            server = SocketServer(addr, KVStoreApplication())
            server.start()
            client = SocketClient(addr)
            client.start()
            try:
                assert client.echo_sync("hello").message == "hello"
                info = client.info_sync(abci.RequestInfo(version="x"))
                assert info.last_block_height == 0
                # pipelined delivers + flush
                rrs = [
                    client.deliver_tx_async(
                        abci.RequestDeliverTx(b"k%d=v%d" % (i, i))
                    )
                    for i in range(10)
                ]
                client.flush_sync()
                for rr in rrs:
                    assert rr.wait(5).value.is_ok()
                commit = client.commit_sync()
                assert len(commit.data) == 8
                q = client.query_sync(abci.RequestQuery(data=b"k3"))
                assert q.value == b"v3"
            finally:
                client.stop()
                server.stop()

    def test_exception_response(self):
        class BoomApp(KVStoreApplication):
            def query(self, req):
                raise RuntimeError("kaboom")

        with tempfile.TemporaryDirectory() as d:
            addr = f"unix://{os.path.join(d, 'abci.sock')}"
            server = SocketServer(addr, BoomApp())
            server.start()
            client = SocketClient(addr)
            client.start()
            try:
                from cometbft_tpu.abci.client import ClientError

                with pytest.raises(ClientError, match="kaboom"):
                    client.query_sync(abci.RequestQuery(data=b"x"))
            finally:
                client.stop()
                server.stop()
