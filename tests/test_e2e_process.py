"""Process-isolated e2e perturbations.

Reference: test/e2e/runner/perturb.go:44-74 — kill (SIGKILL), pause
(docker pause), disconnect (network cut). Each node is a real
`python -m cometbft_tpu start` subprocess; see
cometbft_tpu/e2e/process_runner.py. One shared net, perturbations run
sequentially like the reference runner's Perturb phase.
"""

import time

import pytest

from cometbft_tpu.e2e.process_runner import ProcessTestnet


@pytest.fixture(scope="module")
def net():
    n = ProcessTestnet(n_validators=4)
    n.setup()
    n.start()
    n.wait_for_height(2, timeout=120)
    yield n
    n.stop()


def _stop_proc(p):
    """SIGTERM, escalate to SIGKILL — a wedged subprocess must not turn
    teardown into TimeoutExpired masking the real failure."""
    import subprocess

    if p is None:
        return
    p.terminate()
    try:
        p.wait(15)
    except subprocess.TimeoutExpired:
        p.kill()
        p.wait(10)


def _net_height(net, idxs):
    return max(net.height(i) for i in idxs)


class TestProcessPerturbations:
    def test_sigkill_and_rejoin(self, net):
        """SIGKILL mid-consensus: no WAL flush, no socket teardown. The
        survivors must keep committing (3/4 power) and the restarted
        node must replay its (possibly torn) WAL and rejoin."""
        victim = 3
        h0 = _net_height(net, [0, 1, 2])
        net.kill_node(victim)
        # chain must advance without the victim
        net.wait_for_height(h0 + 2, timeout=60, nodes=[0, 1, 2])
        net.start_node(victim)
        # the restarted node catches up past where the others are NOW
        h1 = _net_height(net, [0, 1, 2])
        net.wait_for_height(h1, timeout=90, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)

    def test_sigstop_pause_resume(self, net):
        """SIGSTOP 5s (docker pause): peers drop the frozen node; on
        SIGCONT it must recover its connections and catch up."""
        victim = 2
        h0 = _net_height(net, [0, 1, 3])
        net.pause_node(victim)
        try:
            net.wait_for_height(h0 + 2, timeout=60, nodes=[0, 1, 3])
            time.sleep(5)
        finally:
            net.resume_node(victim)
        h1 = _net_height(net, [0, 1, 3])
        net.wait_for_height(h1, timeout=90, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)

    def test_partition_and_heal(self, net):
        """Cut every p2p link of one node: the majority keeps going,
        the partitioned node stalls, and after healing it catches up
        (blocksync/consensus catch-up over re-dialed peers)."""
        victim = 1
        h0 = _net_height(net, [0, 2, 3])
        net.disconnect_node(victim)
        try:
            # generous timeouts: 4 subprocess nodes share one core on the
            # CI box, and concurrent load (e.g. a parallel compile) can
            # stretch a commit round several-fold
            net.wait_for_height(h0 + 2, timeout=120, nodes=[0, 2, 3])
            # the victim must NOT advance while cut off
            stalled = net.height(victim)
            time.sleep(3)
            assert net.height(victim) <= stalled + 1, (
                "partitioned node kept committing"
            )
        finally:
            net.connect_node(victim)
        h1 = _net_height(net, [0, 2, 3])
        net.wait_for_height(h1, timeout=240, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)


class TestRelay:
    """The partition primitive itself: a cut must sever LIVE pipes (the
    shutdown-before-close rule — a bare close leaves recv()-blocked
    pipe threads holding the kernel socket, and peers never see FIN)."""

    def test_cut_severs_and_heal_restores(self):
        import socket
        import threading

        from cometbft_tpu.e2e.process_runner import _Relay
        from cometbft_tpu.libs.net import free_ports

        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)

        def echo():
            while True:
                try:
                    c, _ = srv.accept()
                except OSError:
                    return

                def pump(c=c):
                    try:
                        while True:
                            d = c.recv(4096)
                            if not d:
                                break
                            c.sendall(d)
                    except OSError:
                        pass

                threading.Thread(target=pump, daemon=True).start()

        threading.Thread(target=echo, daemon=True).start()
        r = _Relay(free_ports(1)[0], srv.getsockname()[1])
        try:
            c = socket.create_connection(("127.0.0.1", r.listen_port))
            c.sendall(b"ping")
            assert c.recv(4) == b"ping"
            r.set_enabled(False)
            c.settimeout(3)
            assert c.recv(4) == b"", "cut did not sever the live pipe"
            r.set_enabled(True)
            c2 = socket.create_connection(("127.0.0.1", r.listen_port))
            c2.sendall(b"heal")
            assert c2.recv(4) == b"heal"
        finally:
            r.stop()
            srv.close()


class TestSocketABCI:
    """The e2e matrix's 'builtin vs socket ABCI' axis (ci.toml
    `abci_protocol`): a validator whose app runs OUT of process behind
    the ABCI socket server (`abci kvstore` = abci-cli kvstore), txs
    committed through the pipelined SocketClient."""

    def test_single_validator_over_socket_app(self):
        import base64
        import os
        import subprocess
        import sys
        import tempfile

        from cometbft_tpu.cmd.commands import main as cli_main, _load_config
        from cometbft_tpu.config import write_config_file
        from cometbft_tpu.libs.net import free_ports
        from cometbft_tpu.rpc.client import HTTPClient

        d = tempfile.mkdtemp(prefix="abci-sock-")
        cli_main(["--home", d, "init", "--chain-id", "sock-chain"])
        abci_port, rpc_port, p2p_port = free_ports(3)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        app = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu", "abci", "kvstore",
             "--address", f"tcp://127.0.0.1:{abci_port}"],
            cwd=repo, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        node = None
        try:
            cfg = _load_config(d)
            cfg.base.proxy_app = f"tcp://127.0.0.1:{abci_port}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.consensus.timeout_commit_ns = 200_000_000
            write_config_file(os.path.join(d, "config", "config.toml"), cfg)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["CMT_CRYPTO_BACKEND"] = "cpu"
            node = subprocess.Popen(
                [sys.executable, "-m", "cometbft_tpu", "--home", d, "start"],
                cwd=repo, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            c = HTTPClient(f"127.0.0.1:{rpc_port}", timeout=5)
            deadline = time.monotonic() + 60
            h = 0
            while time.monotonic() < deadline and h < 2:
                try:
                    h = int(c.status()["sync_info"]["latest_block_height"])
                except Exception:
                    pass
                time.sleep(0.3)
            assert h >= 2, "chain did not advance over the socket app"
            res = c.broadcast_tx_commit(b"sock=works")
            assert (res.get("deliver_tx") or {}).get("code", 1) == 0, res
            q = c.abci_query("/store", b"sock")
            assert base64.b64decode(
                (q["response"] or {}).get("value") or ""
            ) == b"works"
        finally:
            _stop_proc(node)
            _stop_proc(app)


class TestGRPCABCI:
    """The matrix's gRPC ABCI transport axis: the app behind the
    ABCIApplication gRPC service, node configured with [base] abci =
    "grpc" (node/node.py routes the client through GRPCClient)."""

    def test_single_validator_over_grpc_app(self):
        import os
        import subprocess
        import sys
        import tempfile

        from cometbft_tpu.abci.grpc import GRPCServer
        from cometbft_tpu.abci.kvstore import KVStoreApplication
        from cometbft_tpu.cmd.commands import main as cli_main, _load_config
        from cometbft_tpu.config import write_config_file
        from cometbft_tpu.libs.net import free_ports
        from cometbft_tpu.rpc.client import HTTPClient

        d = tempfile.mkdtemp(prefix="abci-grpc-")
        cli_main(["--home", d, "init", "--chain-id", "grpc-chain"])
        abci_port, rpc_port, p2p_port = free_ports(3)
        server = GRPCServer(f"127.0.0.1:{abci_port}", KVStoreApplication())
        server.start()
        node = None
        try:
            cfg = _load_config(d)
            cfg.base.abci = "grpc"
            cfg.base.proxy_app = f"tcp://127.0.0.1:{abci_port}"
            cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
            cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
            cfg.consensus.timeout_commit_ns = 200_000_000
            write_config_file(os.path.join(d, "config", "config.toml"), cfg)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["CMT_CRYPTO_BACKEND"] = "cpu"
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            node = subprocess.Popen(
                [sys.executable, "-m", "cometbft_tpu", "--home", d, "start"],
                cwd=repo, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
            c = HTTPClient(f"127.0.0.1:{rpc_port}", timeout=5)
            deadline = time.monotonic() + 60
            h = 0
            while time.monotonic() < deadline and h < 2:
                try:
                    h = int(c.status()["sync_info"]["latest_block_height"])
                except Exception:
                    pass
                time.sleep(0.3)
            assert h >= 2, "chain did not advance over the gRPC app"
            res = c.broadcast_tx_commit(b"grpc=works")
            assert (res.get("deliver_tx") or {}).get("code", 1) == 0, res
        finally:
            _stop_proc(node)
            server.stop()


class TestRemotePrivval:
    """The matrix's privval axis (ci.toml privval_protocol=tcp): the
    node holds NO signing key in-process — priv_validator_laddr makes it
    listen for a remote signer, and the SignerServer (holding the real
    FilePV) dials in over the authenticated socket. A single validator
    can only commit if remote signing round-trips work."""

    def test_single_validator_with_remote_signer(self):
        import os
        import subprocess
        import sys
        import tempfile

        from cometbft_tpu.cmd.commands import main as cli_main, _load_config
        from cometbft_tpu.config import write_config_file
        from cometbft_tpu.libs.net import free_ports
        from cometbft_tpu.privval.file import load_file_pv
        from cometbft_tpu.privval.socket import (
            SignerDialerEndpoint,
            SignerServer,
        )
        from cometbft_tpu.rpc.client import HTTPClient

        d = tempfile.mkdtemp(prefix="privval-tcp-")
        cli_main(["--home", d, "init", "--chain-id", "pv-chain"])
        pv_port, rpc_port, p2p_port = free_ports(3)
        cfg = _load_config(d)
        # the signer process owns the key; load it BEFORE the node (the
        # node must not touch priv_validator_key.json in this mode)
        pv = load_file_pv(
            cfg.base.priv_validator_key_path(),
            cfg.base.priv_validator_state_path(),
        )
        cfg.base.proxy_app = "kvstore"
        cfg.base.priv_validator_laddr = f"tcp://127.0.0.1:{pv_port}"
        cfg.rpc.laddr = f"tcp://127.0.0.1:{rpc_port}"
        cfg.p2p.laddr = f"tcp://127.0.0.1:{p2p_port}"
        cfg.consensus.timeout_commit_ns = 200_000_000
        write_config_file(os.path.join(d, "config", "config.toml"), cfg)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["CMT_CRYPTO_BACKEND"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        node = subprocess.Popen(
            [sys.executable, "-m", "cometbft_tpu", "--home", d, "start"],
            cwd=repo, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        server = None
        try:
            # dial the node until its privval listener is up
            deadline = time.monotonic() + 30
            last = None
            while time.monotonic() < deadline and server is None:
                try:
                    dialer = SignerDialerEndpoint(
                        f"tcp://127.0.0.1:{pv_port}", timeout_read=5.0
                    )
                    dialer.connect()
                    server = SignerServer(dialer, "pv-chain", pv)
                    server.start()
                except Exception as exc:  # noqa: BLE001 - node still booting
                    last = exc
                    time.sleep(0.3)
            assert server is not None, f"signer never connected: {last}"
            c = HTTPClient(f"127.0.0.1:{rpc_port}", timeout=5)
            deadline = time.monotonic() + 60
            h = 0
            while time.monotonic() < deadline and h < 2:
                try:
                    h = int(c.status()["sync_info"]["latest_block_height"])
                except Exception:
                    pass
                time.sleep(0.3)
            assert h >= 2, "chain did not advance with a remote signer"
        finally:
            _stop_proc(node)
            if server is not None:
                server.stop()
