"""Process-isolated e2e perturbations.

Reference: test/e2e/runner/perturb.go:44-74 — kill (SIGKILL), pause
(docker pause), disconnect (network cut). Each node is a real
`python -m cometbft_tpu start` subprocess; see
cometbft_tpu/e2e/process_runner.py. One shared net, perturbations run
sequentially like the reference runner's Perturb phase.
"""

import time

import pytest

from cometbft_tpu.e2e.process_runner import ProcessTestnet


@pytest.fixture(scope="module")
def net():
    n = ProcessTestnet(n_validators=4)
    n.setup()
    n.start()
    n.wait_for_height(2, timeout=120)
    yield n
    n.stop()


def _net_height(net, idxs):
    return max(net.height(i) for i in idxs)


class TestProcessPerturbations:
    def test_sigkill_and_rejoin(self, net):
        """SIGKILL mid-consensus: no WAL flush, no socket teardown. The
        survivors must keep committing (3/4 power) and the restarted
        node must replay its (possibly torn) WAL and rejoin."""
        victim = 3
        h0 = _net_height(net, [0, 1, 2])
        net.kill_node(victim)
        # chain must advance without the victim
        net.wait_for_height(h0 + 2, timeout=60, nodes=[0, 1, 2])
        net.start_node(victim)
        # the restarted node catches up past where the others are NOW
        h1 = _net_height(net, [0, 1, 2])
        net.wait_for_height(h1, timeout=90, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)

    def test_sigstop_pause_resume(self, net):
        """SIGSTOP 5s (docker pause): peers drop the frozen node; on
        SIGCONT it must recover its connections and catch up."""
        victim = 2
        h0 = _net_height(net, [0, 1, 3])
        net.pause_node(victim)
        try:
            net.wait_for_height(h0 + 2, timeout=60, nodes=[0, 1, 3])
            time.sleep(5)
        finally:
            net.resume_node(victim)
        h1 = _net_height(net, [0, 1, 3])
        net.wait_for_height(h1, timeout=90, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)

    def test_partition_and_heal(self, net):
        """Cut every p2p link of one node: the majority keeps going,
        the partitioned node stalls, and after healing it catches up
        (blocksync/consensus catch-up over re-dialed peers)."""
        victim = 1
        h0 = _net_height(net, [0, 2, 3])
        net.disconnect_node(victim)
        try:
            net.wait_for_height(h0 + 2, timeout=60, nodes=[0, 2, 3])
            # the victim must NOT advance while cut off
            stalled = net.height(victim)
            time.sleep(3)
            assert net.height(victim) <= stalled + 1, (
                "partitioned node kept committing"
            )
        finally:
            net.connect_node(victim)
        h1 = _net_height(net, [0, 2, 3])
        net.wait_for_height(h1, timeout=120, nodes=[victim])
        net.check_app_hashes_agree(h0 + 1)
