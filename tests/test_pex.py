"""PEX + address book tests.

Model: reference p2p/pex/addrbook_test.go, pex_reactor_test.go.
"""

import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.p2p import NetAddress, NodeKey
from cometbft_tpu.p2p.pex.addrbook import AddrBook, KnownAddress
from cometbft_tpu.p2p.pex.reactor import (
    PEX_CHANNEL,
    PEXReactor,
    decode_pex_message,
    encode_pex_addrs,
    encode_pex_request,
)


def _addr(i: int, port: int = 26656) -> NetAddress:
    nid = ed.gen_priv_key_from_secret(bytes([i, 7])).pub_key().address().hex()
    return NetAddress(nid, f"8.8.{i % 256}.{(i * 7) % 256}", port)


class TestAddrBook:
    def test_add_and_pick(self):
        book = AddrBook()
        for i in range(10):
            book.add_address(_addr(i), None)
        assert book.size() == 10
        picked = book.pick_address(50)
        assert picked is not None and book.has_address(picked)

    def test_non_routable_rejected_when_strict(self):
        book = AddrBook(routability_strict=True)
        local = NetAddress("aa" * 20, "127.0.0.1", 26656)
        with pytest.raises(ValueError):
            book.add_address(local, None)
        lax = AddrBook(routability_strict=False)
        lax.add_address(local, None)
        assert lax.size() == 1

    def test_mark_good_promotes_to_old(self):
        book = AddrBook()
        a = _addr(1)
        book.add_address(a, None)
        assert not book.is_good(a)
        book.mark_good(a.id)
        assert book.is_good(a)
        # old picks with bias 0
        assert book.pick_address(0) == a

    def test_mark_bad_bans(self):
        book = AddrBook()
        a = _addr(2)
        book.add_address(a, None)
        book.mark_bad(a, ban_time=60.0)
        assert book.is_banned(a)
        assert book.size() == 0
        assert book.pick_address(50) is None

    def test_ban_expires(self):
        book = AddrBook()
        a = _addr(3)
        book.add_address(a, None)
        book.mark_bad(a, ban_time=0.01)
        time.sleep(0.05)
        book.reinstate_bad_peers()
        assert not book.is_banned(a)
        assert book.size() == 1

    def test_our_address_ignored(self):
        book = AddrBook()
        a = _addr(4)
        book.add_our_address(a)
        book.add_address(a, None)
        assert book.size() == 0

    def test_selection_bounds(self):
        book = AddrBook()
        for i in range(50):
            book.add_address(_addr(i), None)
        sel = book.get_selection()
        assert 0 < len(sel) <= 50
        assert len({a.id for a in sel}) == len(sel)

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        book = AddrBook(file_path=path)
        book.start()
        for i in range(5):
            book.add_address(_addr(i), None)
        book.mark_good(_addr(0).id)
        book.stop()

        book2 = AddrBook(file_path=path)
        book2.start()
        assert book2.size() == 5
        assert book2.is_good(_addr(0))
        book2.stop()


class TestPexWire:
    def test_request_roundtrip(self):
        kind, addrs = decode_pex_message(encode_pex_request())
        assert kind == "request" and addrs is None

    def test_addrs_roundtrip(self):
        addrs = [_addr(i) for i in range(3)]
        kind, got = decode_pex_message(encode_pex_addrs(addrs))
        assert kind == "addrs"
        assert got == addrs

    def test_empty_addrs(self):
        kind, got = decode_pex_message(encode_pex_addrs([]))
        assert kind == "addrs" and got == []
