"""PEX + address book tests.

Model: reference p2p/pex/addrbook_test.go, pex_reactor_test.go.
"""

import time

import pytest

from cometbft_tpu.crypto import ed25519 as ed
from cometbft_tpu.p2p import NetAddress, NodeKey
from cometbft_tpu.p2p.pex.addrbook import AddrBook, KnownAddress
from cometbft_tpu.p2p.pex.reactor import (
    PEX_CHANNEL,
    PEXReactor,
    decode_pex_message,
    encode_pex_addrs,
    encode_pex_request,
)


def _addr(i: int, port: int = 26656) -> NetAddress:
    nid = ed.gen_priv_key_from_secret(bytes([i, 7])).pub_key().address().hex()
    return NetAddress(nid, f"8.8.{i % 256}.{(i * 7) % 256}", port)


class TestAddrBook:
    def test_add_and_pick(self):
        book = AddrBook()
        for i in range(10):
            book.add_address(_addr(i), None)
        assert book.size() == 10
        picked = book.pick_address(50)
        assert picked is not None and book.has_address(picked)

    def test_non_routable_rejected_when_strict(self):
        book = AddrBook(routability_strict=True)
        local = NetAddress("aa" * 20, "127.0.0.1", 26656)
        with pytest.raises(ValueError):
            book.add_address(local, None)
        lax = AddrBook(routability_strict=False)
        lax.add_address(local, None)
        assert lax.size() == 1

    def test_mark_good_promotes_to_old(self):
        book = AddrBook()
        a = _addr(1)
        book.add_address(a, None)
        assert not book.is_good(a)
        book.mark_good(a.id)
        assert book.is_good(a)
        # old picks with bias 0
        assert book.pick_address(0) == a

    def test_mark_bad_bans(self):
        book = AddrBook()
        a = _addr(2)
        book.add_address(a, None)
        book.mark_bad(a, ban_time=60.0)
        assert book.is_banned(a)
        assert book.size() == 0
        assert book.pick_address(50) is None

    def test_ban_expires(self):
        book = AddrBook()
        a = _addr(3)
        book.add_address(a, None)
        book.mark_bad(a, ban_time=0.01)
        time.sleep(0.05)
        book.reinstate_bad_peers()
        assert not book.is_banned(a)
        assert book.size() == 1

    def test_our_address_ignored(self):
        book = AddrBook()
        a = _addr(4)
        book.add_our_address(a)
        book.add_address(a, None)
        assert book.size() == 0

    def test_selection_bounds(self):
        book = AddrBook()
        for i in range(50):
            book.add_address(_addr(i), None)
        sel = book.get_selection()
        assert 0 < len(sel) <= 50
        assert len({a.id for a in sel}) == len(sel)

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "addrbook.json")
        book = AddrBook(file_path=path)
        book.start()
        for i in range(5):
            book.add_address(_addr(i), None)
        book.mark_good(_addr(0).id)
        book.stop()

        book2 = AddrBook(file_path=path)
        book2.start()
        assert book2.size() == 5
        assert book2.is_good(_addr(0))
        book2.stop()


class TestPexWire:
    def test_request_roundtrip(self):
        kind, addrs = decode_pex_message(encode_pex_request())
        assert kind == "request" and addrs is None

    def test_addrs_roundtrip(self):
        addrs = [_addr(i) for i in range(3)]
        kind, got = decode_pex_message(encode_pex_addrs(addrs))
        assert kind == "addrs"
        assert got == addrs

    def test_empty_addrs(self):
        kind, got = decode_pex_message(encode_pex_addrs([]))
        assert kind == "addrs" and got == []


class TestBucketedAddrBook:
    """The reference's 256-new/64-old hashed-bucket anti-eclipse design
    (addrbook.go:46-60, params.go) — eviction, collision containment,
    per-address bucket caps, and old-bucket displacement."""

    def test_new_bucket_eviction_stays_within_bucket(self):
        book = AddrBook(routability_strict=False)
        # same /16 group + same source → all land in ONE new bucket
        src = _addr(1)
        target = book.calc_new_bucket(_addr(2), src)
        added = []
        i = 2
        while len(added) < 70:  # overfill one bucket (size 64)
            a = _addr(i)
            i += 1
            if book.calc_new_bucket(a, src) != target:
                continue
            book.add_address(a, src)
            added.append(a)
        bucket = book._new_buckets[target]
        assert len(bucket) == 64  # evicted down to capacity
        # eviction stayed within the bucket: book-wide survivors are the
        # 64 in the bucket, and nothing leaked into other buckets
        assert book.size() == 64
        for b_idx, b in enumerate(book._new_buckets):
            if b_idx != target:
                assert not b

    def test_flooded_group_cannot_displace_other_groups(self):
        """An attacker netblock (one /16) fills its slice of NEW buckets;
        proven-good (old-table) peers are insulated entirely, and the
        flood is contained to its newBucketsPerGroup slice."""
        book = AddrBook(routability_strict=False)
        honest = [
            NetAddress(
                ed.gen_priv_key_from_secret(bytes([i, 91])).pub_key().address().hex(),
                f"9.{i}.1.1", 26656,
            )
            for i in range(20)
        ]
        for a in honest:
            book.add_address(a, a)
            book.mark_good(a.id)  # proven peers live in the old table
        flood_src = _addr(200)
        for i in range(2000):
            nid = ed.gen_priv_key_from_secret(
                i.to_bytes(2, "big") + b"flood"
            ).pub_key().address().hex()
            # one /16: 66.66.x.y
            a = NetAddress(nid, f"66.66.{i % 250}.{(i // 250) % 250}", 26656)
            book.add_address(a, flood_src)
        for a in honest:
            assert book.has_address(a), "flood evicted an honest address"
        # the flood is contained to <= newBucketsPerGroup buckets
        flood_buckets = {
            idx
            for idx, b in enumerate(book._new_buckets)
            for k in b.values()
            if k.addr.ip.startswith("66.66.")
        }
        assert len(flood_buckets) <= 32

    def test_address_capped_at_four_new_buckets(self):
        book = AddrBook(routability_strict=False)
        a = _addr(3)
        # re-advertised from many different /16 sources
        for i in range(40):
            src = NetAddress(
                ed.gen_priv_key_from_secret(bytes([i, 77])).pub_key().address().hex(),
                f"{10 + i}.{i}.0.1", 26656,
            )
            book.add_address(a, src)
        ka = book._addrs[a.id]
        assert 1 <= len(ka.buckets) <= 4

    def test_mark_good_moves_between_tables(self):
        book = AddrBook(routability_strict=False)
        a = _addr(5)
        book.add_address(a, _addr(6))
        ka = book._addrs[a.id]
        new_buckets = list(ka.buckets)
        book.mark_good(a.id)
        assert ka.is_old and len(ka.buckets) == 1
        old_idx = ka.buckets[0]
        assert a.id in book._old_buckets[old_idx]
        for b in new_buckets:
            assert a.id not in book._new_buckets[b]
        # demotion on mark_bad returns it to a new bucket
        book.mark_bad(a, ban_time=0.05)
        assert not ka.is_old
        assert a.id not in book._old_buckets[old_idx]

    def test_old_bucket_overflow_demotes_oldest(self):
        book = AddrBook(routability_strict=False)
        src = _addr(9)
        promoted = []
        i = 0
        target = None
        while len(promoted) < 65:
            nid = ed.gen_priv_key_from_secret(
                i.to_bytes(2, "big") + b"old"
            ).pub_key().address().hex()
            a = NetAddress(nid, f"77.{i % 200}.{i // 200}.9", 26656)
            i += 1
            if target is None:
                target = book.calc_old_bucket(a)
            elif book.calc_old_bucket(a) != target:
                continue
            book.add_address(a, src)
            book.mark_good(a.id)
            promoted.append(a)
        bucket = book._old_buckets[target]
        assert len(bucket) == 64
        # every promoted address is still KNOWN — the displaced one went
        # back to a new bucket rather than being dropped
        assert all(book.has_address(a) for a in promoted)
        demoted = [a for a in promoted if not book._addrs[a.id].is_old]
        assert len(demoted) == 1

    def test_persistence_restores_buckets(self, tmp_path):
        path = str(tmp_path / "book.json")
        book = AddrBook(file_path=path, routability_strict=False)
        for i in range(30):
            book.add_address(_addr(i + 1), _addr(99))
        book.mark_good(_addr(1).id)
        book.save()
        book2 = AddrBook(file_path=path, routability_strict=False)
        book2._load()
        assert book2.size() == book.size()
        ka = book2._addrs[_addr(1).id]
        assert ka.is_old and len(ka.buckets) == 1
        assert _addr(1).id in book2._old_buckets[ka.buckets[0]]


class TestPexDiscoveryOverSwitches:
    """The reactor request/response/seed-mode flow over real TCP
    switches: a fresh node discovers a third peer it was never told
    about, via a seed (pex_reactor.go end-to-end)."""

    def _pex_node(self, seed_mode=False, seeds=None, period=0.3):
        from tests.test_p2p import _make_transport
        from cometbft_tpu.p2p.switch import Switch

        t = _make_transport(channels=bytes([PEX_CHANNEL]))
        sw = Switch(t, reconnect_interval=0.1)
        book = AddrBook(routability_strict=False)
        r = PEXReactor(
            book, seeds=seeds, seed_mode=seed_mode,
            ensure_peers_period=period,
        )
        sw.add_reactor("PEX", r)
        sw.addr_book = book
        return sw, r, book

    def test_fresh_node_discovers_peer_via_seed(self):
        import time as _t

        seed_sw, seed_r, seed_book = self._pex_node(seed_mode=True)
        c_sw, c_r, c_book = self._pex_node()
        seed_sw.start()
        c_sw.start()
        b_sw = None
        try:
            # C connects to the seed → the seed's book learns C's address
            c_sw.dial_peer_with_address(seed_sw.transport.listen_addr)
            deadline = _t.monotonic() + 10
            while _t.monotonic() < deadline and seed_book.size() < 1:
                _t.sleep(0.05)
            assert seed_book.size() >= 1, "seed never learned C's address"

            # B boots knowing ONLY the seed
            seed_addr = str(seed_sw.transport.listen_addr)
            b_sw, b_r, b_book = self._pex_node(seeds=[seed_addr])
            b_sw.start()
            b_sw.dial_peer_with_address(seed_sw.transport.listen_addr)

            # B must end up CONNECTED to C without ever being told about C
            c_id = c_sw.node_info().node_id
            deadline = _t.monotonic() + 20
            while _t.monotonic() < deadline:
                if any(p.id() == c_id for p in b_sw.peers.list()):
                    break
                _t.sleep(0.1)
            assert any(p.id() == c_id for p in b_sw.peers.list()), (
                f"B never discovered C: book={b_book.size()} "
                f"peers={[p.id()[:8] for p in b_sw.peers.list()]}"
            )
            # seed mode hangs up after answering: observe B dropping off
            # the seed's peer list at least once (B's ensure-peers loop
            # may redial afterwards — that's fine, each request gets one
            # answer-and-hangup)
            b_id = b_sw.node_info().node_id
            observed_hangup = False
            deadline = _t.monotonic() + 10
            while _t.monotonic() < deadline:
                if all(p.id() != b_id for p in seed_sw.peers.list()):
                    observed_hangup = True
                    break
                _t.sleep(0.05)
            assert observed_hangup, "seed never hung up on the requester"
        finally:
            seed_sw.stop()
            c_sw.stop()
            if b_sw is not None:
                b_sw.stop()
