"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding code paths
compile and execute without TPU hardware. Must be set before JAX import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env pins "axon"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
