"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding code paths
compile and execute without TPU hardware. Must be set before JAX import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env pins "axon"
# Trust the (virtual CPU) platform instead of probing: the probe
# SUBPROCESS inherits the ambient axon platform (sitecustomize overrides
# env), so a busy/wedged tunnel would latch the device plane DOWN and
# silently reroute every device-path test to the CPU fallback — masks
# agree, so nothing would fail, but the kernels under test never run.
os.environ["CBFT_TPU_PROBE"] = "0"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Persistent compilation cache: the ed25519 kernel takes minutes to compile
# on CPU; cache it across pytest runs.
import jax  # noqa: E402

# The environment may pre-import jax at interpreter startup (sitecustomize)
# with JAX_PLATFORMS=axon — the env vars above are then too late, so force
# the platform through the live config before any backend initializes.
jax.config.update("jax_platforms", "cpu")

_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 5.0)


from cometbft_tpu.libs.net import free_ports  # noqa: E402,F401  (shared test helper)
